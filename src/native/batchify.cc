// Native batchify: GIL-free parallel sample collation.
//
// Reference analog: src/io/batchify.cc (StackBatchify::Batchify runs an
// OMP-parallel copy of N samples into one batch buffer) and the image
// pipeline's normalize/transpose kernels (iter_image_recordio_2.cc) that
// run on dmlc worker threads. Python's numpy stack holds the GIL per
// element; these entry points take raw pointers so the Python side
// releases the GIL once for the whole batch.
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include "mxt_native.h"

namespace {

// Run fn(i) for i in [0, n) over up to n_threads workers.
template <typename F>
void ParallelFor(int n, int n_threads, F fn) {
  if (n_threads <= 1 || n <= 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  int workers = std::min(n_threads, n);
  std::vector<std::thread> pool;
  pool.reserve(workers);
  std::atomic<int> next{0};
  for (int w = 0; w < workers; ++w) {
    pool.emplace_back([&] {
      int i;
      while ((i = next.fetch_add(1)) < n) fn(i);
    });
  }
  for (auto &t : pool) t.join();
}

}  // namespace

extern "C" {

int MXTBatchifyStack(const void *const *srcs, int n, size_t sample_bytes,
                     void *dst, int n_threads) {
  if (!srcs || !dst || n < 0) {
    MXTSetLastError("MXTBatchifyStack: bad arguments");
    return -1;
  }
  char *out = static_cast<char *>(dst);
  ParallelFor(n, n_threads, [&](int i) {
    std::memcpy(out + static_cast<size_t>(i) * sample_bytes, srcs[i],
                sample_bytes);
  });
  return 0;
}

// HWC uint8 images -> NCHW float32 batch with (x/255 - mean[c]) / std[c]:
// the fused decode-side normalize+transpose of the reference image
// pipeline (image/image.cc NormalizeAug + swap to CHW), one sample per
// worker thread.
int MXTBatchifyImageNormalize(const uint8_t *const *srcs, int n, int h,
                              int w, int c, const float *mean,
                              const float *stddev, float *dst,
                              int n_threads) {
  if (!srcs || !dst || n < 0 || c <= 0) {
    MXTSetLastError("MXTBatchifyImageNormalize: bad arguments");
    return -1;
  }
  const size_t plane = static_cast<size_t>(h) * w;
  ParallelFor(n, n_threads, [&](int i) {
    const uint8_t *src = srcs[i];
    float *out = dst + static_cast<size_t>(i) * c * plane;
    for (int ch = 0; ch < c; ++ch) {
      const float m = mean ? mean[ch] : 0.0f;
      const float s = stddev ? stddev[ch] : 1.0f;
      const float inv = 1.0f / (255.0f * s);
      float *op = out + ch * plane;
      const uint8_t *ip = src + ch;
      for (size_t p = 0; p < plane; ++p) {
        op[p] = static_cast<float>(ip[p * c]) * inv - m / s;
      }
    }
  });
  return 0;
}

}  // extern "C"
