// Native JPEG decode via libjpeg.
//
// Reference analog: the OpenCV-backed decode threads of the image pipeline
// (src/io/iter_image_recordio_2.cc + image_aug_default.cc): JPEG decode is
// the data-path hot loop, so it must run GIL-free on C++ threads. Two-phase
// API: probe dimensions, then decode into a caller-allocated HWC uint8
// buffer (grayscale sources expand to the requested channel count, like
// cv::imread's IMREAD_COLOR).
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <jpeglib.h>

#include "mxt_native.h"

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf jump;
  char msg[JMSG_LENGTH_MAX];
};

void ErrorExit(j_common_ptr cinfo) {
  ErrorMgr *err = reinterpret_cast<ErrorMgr *>(cinfo->err);
  (*cinfo->err->format_message)(cinfo, err->msg);
  longjmp(err->jump, 1);
}

}  // namespace

extern "C" {

int MXTImageJPEGInfo(const uint8_t *data, size_t len, int *h, int *w,
                     int *c) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = ErrorExit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    MXTSetLastError(jerr.msg);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t *>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  *h = static_cast<int>(cinfo.image_height);
  *w = static_cast<int>(cinfo.image_width);
  *c = cinfo.num_components;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

// Decode into out (h * w * out_c HWC uint8). out_c 3 = RGB (grayscale
// sources replicate), 1 = grayscale (color sources luminance-convert via
// libjpeg's JCS_GRAYSCALE output path).
int MXTImageJPEGDecode(const uint8_t *data, size_t len, uint8_t *out,
                       int out_c) {
  if (out_c != 1 && out_c != 3) {
    MXTSetLastError("MXTImageJPEGDecode: out_c must be 1 or 3");
    return -1;
  }
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = ErrorExit;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    MXTSetLastError(jerr.msg);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t *>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (out_c == 3) ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);
  const int w = static_cast<int>(cinfo.output_width);
  const int stride = w * out_c;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t *row = out + static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // extern "C"
