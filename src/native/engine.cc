/*
 * Threaded dependency engine for host-side work.
 *
 * Reference analog: src/engine/threaded_engine.{h,cc} — versioned variables
 * with shared-read/exclusive-write scheduling, per-op wait counters, and
 * exception capture surfaced at sync points. Device work is XLA's job on
 * TPU; this engine orders host tasks (IO, decode, checkpointing, Python
 * callbacks) with the same semantics the reference's engine guaranteed.
 */
#include "mxt_native.h"

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

thread_local std::string tls_error;
thread_local std::string tls_callback_error;

void set_error(const std::string &msg) { tls_error = msg; }

struct Op;

/* A versioned variable: a FIFO of pending ops with shared-read /
 * exclusive-write admission (reference ThreadedVar, threaded_engine.h:120). */
struct Var {
  std::mutex m;
  std::deque<std::pair<Op *, bool>> q;  // (op, is_write) in push order
  int active_readers = 0;
  bool active_writer = false;
  std::atomic<uint64_t> version{0};
  bool to_delete = false;               // delete after queue drains
};

struct Engine;

struct Op {
  MXTOpFn fn = nullptr;
  void *ctx = nullptr;
  MXTOpDeleter deleter = nullptr;
  std::vector<Var *> const_vars, mut_vars;
  std::atomic<int> wait{0};
  Engine *engine = nullptr;
  std::function<void()> on_complete;    // optional (sync ops)
};

struct Engine {
  std::vector<std::thread> workers;
  std::deque<Op *> tasks;
  std::mutex task_m;
  std::condition_variable task_cv;
  bool shutdown = false;

  std::atomic<long> outstanding{0};
  std::mutex done_m;
  std::condition_variable done_cv;

  std::mutex err_m;
  std::string first_error;              // first async failure, kept until read

  explicit Engine(int n) {
    for (int i = 0; i < n; ++i)
      workers.emplace_back([this] { worker_loop(); });
  }

  ~Engine() {
    {
      std::lock_guard<std::mutex> lk(task_m);
      shutdown = true;
    }
    task_cv.notify_all();
    for (auto &t : workers) t.join();
  }

  void enqueue_ready(Op *op) {
    {
      std::lock_guard<std::mutex> lk(task_m);
      tasks.push_back(op);
    }
    task_cv.notify_one();
  }

  void record_error(const std::string &msg) {
    std::lock_guard<std::mutex> lk(err_m);
    if (first_error.empty()) first_error = msg;
  }

  /* Returns and clears the stored async error ("" if none). */
  std::string take_error() {
    std::lock_guard<std::mutex> lk(err_m);
    std::string e;
    std::swap(e, first_error);
    return e;
  }

  void worker_loop() {
    for (;;) {
      Op *op = nullptr;
      {
        std::unique_lock<std::mutex> lk(task_m);
        task_cv.wait(lk, [this] { return shutdown || !tasks.empty(); });
        if (shutdown && tasks.empty()) return;
        op = tasks.front();
        tasks.pop_front();
      }
      run_op(op);
    }
  }

  void grant(Var *v, std::vector<Op *> &ready_out) {
    // called with v->m held: admit queue head(s) per read/write rules
    while (!v->q.empty()) {
      Op *op = v->q.front().first;
      bool is_write = v->q.front().second;
      if (is_write) {
        if (v->active_readers == 0 && !v->active_writer) {
          v->active_writer = true;
          v->q.pop_front();
          if (op->wait.fetch_sub(1) == 1) ready_out.push_back(op);
        }
        break;
      }
      if (v->active_writer) break;
      v->active_readers++;
      v->q.pop_front();
      if (op->wait.fetch_sub(1) == 1) ready_out.push_back(op);
    }
  }

  void complete_on_var(Var *v, bool was_write, std::vector<Op *> &ready_out,
                       std::vector<Var *> &dead_vars) {
    std::lock_guard<std::mutex> lk(v->m);
    if (was_write) {
      v->active_writer = false;
      v->version.fetch_add(1);
    } else {
      v->active_readers--;
    }
    grant(v, ready_out);
    if (v->to_delete && v->q.empty() && v->active_readers == 0 &&
        !v->active_writer)
      dead_vars.push_back(v);
  }

  void run_op(Op *op) {
    tls_callback_error.clear();
    int rc = 0;
    if (op->fn) rc = op->fn(op->ctx);
    if (rc != 0) {
      record_error(tls_callback_error.empty()
                       ? "async engine op failed"
                       : tls_callback_error);
    }
    if (op->deleter) op->deleter(op->ctx);

    std::vector<Op *> ready;
    std::vector<Var *> dead;
    for (Var *v : op->const_vars) complete_on_var(v, false, ready, dead);
    for (Var *v : op->mut_vars) complete_on_var(v, true, ready, dead);
    if (op->on_complete) op->on_complete();
    delete op;
    for (Var *v : dead) delete v;
    for (Op *r : ready) enqueue_ready(r);
    if (outstanding.fetch_sub(1) == 1) {
      std::lock_guard<std::mutex> lk(done_m);
      done_cv.notify_all();
    }
  }

  void push(Op *op) {
    outstanding.fetch_add(1);
    op->engine = this;
    int total = static_cast<int>(op->const_vars.size() + op->mut_vars.size());
    if (total == 0) {
      enqueue_ready(op);
      return;
    }
    op->wait.store(total + 1);  // +1 guard: full registration before launch
    std::vector<Op *> ready;
    for (Var *v : op->const_vars) {
      std::lock_guard<std::mutex> lk(v->m);
      v->q.emplace_back(op, false);
      grant(v, ready);
    }
    for (Var *v : op->mut_vars) {
      std::lock_guard<std::mutex> lk(v->m);
      v->q.emplace_back(op, true);
      grant(v, ready);
    }
    if (op->wait.fetch_sub(1) == 1) ready.push_back(op);  // drop guard
    for (Op *r : ready) enqueue_ready(r);
  }
};

/* Dedup vars; a var appearing in both lists is treated as a write
 * (reference engine.h:291 dedup contract). */
void normalize_vars(std::vector<Var *> &cv, std::vector<Var *> &mv) {
  auto uniq = [](std::vector<Var *> &v) {
    std::sort(v.begin(), v.end());
    v.erase(std::unique(v.begin(), v.end()), v.end());
  };
  uniq(cv);
  uniq(mv);
  std::vector<Var *> cv2;
  for (Var *v : cv)
    if (!std::binary_search(mv.begin(), mv.end(), v)) cv2.push_back(v);
  cv.swap(cv2);
}

}  // namespace

extern "C" {

const char *MXTGetLastError(void) { return tls_error.c_str(); }

void MXTSetLastError(const char *msg) { set_error(msg ? msg : ""); }

void MXTSetCallbackError(const char *msg) {
  tls_callback_error = msg ? msg : "";
}

int MXTEngineCreate(int num_threads, MXTEngineHandle *out) {
  if (num_threads <= 0) num_threads = std::thread::hardware_concurrency();
  if (num_threads <= 0) num_threads = 2;
  *out = new Engine(num_threads);
  return 0;
}

int MXTEngineDestroy(MXTEngineHandle h) {
  auto *eng = static_cast<Engine *>(h);
  MXTEngineWaitForAll(h);
  delete eng;
  return 0;
}

int MXTEngineNewVar(MXTEngineHandle, MXTVarHandle *out) {
  *out = new Var();
  return 0;
}

int MXTEngineDeleteVar(MXTEngineHandle h, MXTVarHandle var) {
  auto *v = static_cast<Var *>(var);
  bool now;
  {
    std::lock_guard<std::mutex> lk(v->m);
    v->to_delete = true;
    now = v->q.empty() && v->active_readers == 0 && !v->active_writer;
  }
  if (now) delete v;
  (void)h;
  return 0;
}

int MXTEnginePushAsync(MXTEngineHandle h, MXTOpFn fn, void *ctx,
                       MXTOpDeleter del, MXTVarHandle *const_vars, int n_const,
                       MXTVarHandle *mutable_vars, int n_mut) {
  auto *eng = static_cast<Engine *>(h);
  auto *op = new Op();
  op->fn = fn;
  op->ctx = ctx;
  op->deleter = del;
  for (int i = 0; i < n_const; ++i)
    op->const_vars.push_back(static_cast<Var *>(const_vars[i]));
  for (int i = 0; i < n_mut; ++i)
    op->mut_vars.push_back(static_cast<Var *>(mutable_vars[i]));
  normalize_vars(op->const_vars, op->mut_vars);
  eng->push(op);
  return 0;
}

int MXTEngineWaitForVar(MXTEngineHandle h, MXTVarHandle var) {
  auto *eng = static_cast<Engine *>(h);
  auto *v = static_cast<Var *>(var);
  std::mutex m;
  std::condition_variable cv;
  bool done = false;
  auto *op = new Op();
  op->on_complete = [&] {
    std::lock_guard<std::mutex> lk(m);
    done = true;
    cv.notify_all();
  };
  op->const_vars.push_back(v);
  eng->push(op);
  std::unique_lock<std::mutex> lk(m);
  cv.wait(lk, [&] { return done; });
  std::string e = eng->take_error();
  if (!e.empty()) {
    set_error(e);
    return -1;
  }
  return 0;
}

int MXTEngineWaitForAll(MXTEngineHandle h) {
  auto *eng = static_cast<Engine *>(h);
  std::unique_lock<std::mutex> lk(eng->done_m);
  eng->done_cv.wait(lk, [&] { return eng->outstanding.load() == 0; });
  lk.unlock();
  std::string e = eng->take_error();
  if (!e.empty()) {
    set_error(e);
    return -1;
  }
  return 0;
}

int MXTEngineVarVersion(MXTEngineHandle, MXTVarHandle var, uint64_t *out) {
  *out = static_cast<Var *>(var)->version.load();
  return 0;
}

}  // extern "C"
