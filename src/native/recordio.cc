/*
 * RecordIO reader/writer + threaded prefetcher.
 *
 * Wire format is dmlc RecordIO (the reference's dataset container,
 * 3rdparty/dmlc-core recordio.h semantics as used by src/io/): each record
 * is framed as
 *   uint32 magic = 0xced7230a
 *   uint32 lrec  = (cflag << 29) | length      (cflag 0 = whole record)
 *   payload, zero-padded to a 4-byte boundary
 * Long records that would need continuation flags are written whole here
 * (cflag 0) — readers of both implementations accept that; payloads
 * containing the magic are still unambiguous because framing is
 * length-driven on read.
 *
 * The prefetcher is the reference's iter_prefetcher.h idea: a C++ IO
 * thread reads ahead into a bounded queue so Python-side decode/transform
 * overlaps with file IO without holding the GIL.
 */
#include "mxt_native.h"

#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Writer {
  FILE *fp;
  uint64_t pos = 0;
};

struct Reader {
  FILE *fp;
  std::string buf;
};

bool read_record(FILE *fp, std::string *out, std::string *err) {
  uint32_t magic, lrec;
  size_t n = fread(&magic, 1, 4, fp);
  if (n == 0) return false;  // clean EOF
  if (n != 4 || magic != kMagic) {
    *err = "recordio: bad magic (corrupt or misaligned file)";
    return false;
  }
  if (fread(&lrec, 1, 4, fp) != 4) {
    *err = "recordio: truncated header";
    return false;
  }
  uint32_t len = lrec & ((1u << 29) - 1);
  out->resize(len);
  if (len && fread(&(*out)[0], 1, len, fp) != len) {
    *err = "recordio: truncated payload";
    return false;
  }
  size_t pad = (4 - (len & 3)) & 3;
  if (pad) {
    char junk[4];
    if (fread(junk, 1, pad, fp) != pad) {
      *err = "recordio: truncated padding";
      return false;
    }
  }
  return true;
}

}  // namespace

extern "C" {

static void set_err(const char *msg) { MXTSetLastError(msg); }

int MXTRecordIOWriterCreate(const char *path, MXTRecordIOHandle *out) {
  FILE *fp = fopen(path, "wb");
  if (!fp) {
    set_err("recordio: cannot open file for writing");
    return -1;
  }
  auto *w = new Writer();
  w->fp = fp;
  *out = w;
  return 0;
}

int MXTRecordIOWriterWrite(MXTRecordIOHandle h, const char *data, size_t len,
                           uint64_t *out_pos) {
  auto *w = static_cast<Writer *>(h);
  if (out_pos) *out_pos = w->pos;
  uint32_t magic = kMagic;
  uint32_t lrec = static_cast<uint32_t>(len) & ((1u << 29) - 1);
  if (len >= (1u << 29)) {
    set_err("recordio: record too large (>512MB)");
    return -1;
  }
  if (fwrite(&magic, 1, 4, w->fp) != 4 || fwrite(&lrec, 1, 4, w->fp) != 4 ||
      (len && fwrite(data, 1, len, w->fp) != len)) {
    set_err("recordio: write failed");
    return -1;
  }
  size_t pad = (4 - (len & 3)) & 3;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && fwrite(zeros, 1, pad, w->fp) != pad) {
    set_err("recordio: write failed");
    return -1;
  }
  w->pos += 8 + len + pad;
  return 0;
}

int MXTRecordIOWriterTell(MXTRecordIOHandle h, uint64_t *out) {
  *out = static_cast<Writer *>(h)->pos;
  return 0;
}

int MXTRecordIOWriterClose(MXTRecordIOHandle h) {
  auto *w = static_cast<Writer *>(h);
  fclose(w->fp);
  delete w;
  return 0;
}

int MXTRecordIOReaderCreate(const char *path, MXTRecordIOHandle *out) {
  FILE *fp = fopen(path, "rb");
  if (!fp) {
    set_err("recordio: cannot open file for reading");
    return -1;
  }
  auto *r = new Reader();
  r->fp = fp;
  *out = r;
  return 0;
}

int MXTRecordIOReaderNext(MXTRecordIOHandle h, const char **out_data,
                          size_t *out_len) {
  auto *r = static_cast<Reader *>(h);
  std::string err;
  if (!read_record(r->fp, &r->buf, &err)) {
    if (!err.empty()) {
      set_err(err.c_str());
      return -1;
    }
    *out_data = nullptr;
    *out_len = 0;
    return 0;
  }
  *out_data = r->buf.data();
  *out_len = r->buf.size();
  return 0;
}

int MXTRecordIOReaderSeek(MXTRecordIOHandle h, uint64_t pos) {
  auto *r = static_cast<Reader *>(h);
  if (fseek(r->fp, static_cast<long>(pos), SEEK_SET) != 0) {
    set_err("recordio: seek failed");
    return -1;
  }
  return 0;
}

int MXTRecordIOReaderTell(MXTRecordIOHandle h, uint64_t *out) {
  auto *r = static_cast<Reader *>(h);
  long p = ftell(r->fp);
  if (p < 0) {
    set_err("recordio: tell failed");
    return -1;
  }
  *out = static_cast<uint64_t>(p);
  return 0;
}

int MXTRecordIOReaderClose(MXTRecordIOHandle h) {
  auto *r = static_cast<Reader *>(h);
  fclose(r->fp);
  delete r;
  return 0;
}

/* ---- threaded prefetcher ---- */

namespace {

struct Prefetcher {
  FILE *fp = nullptr;
  std::thread th;
  std::deque<std::string> queue;
  size_t capacity;
  std::mutex m;
  std::condition_variable cv_pop, cv_push;
  bool eof = false, stop = false;
  std::string error;
  std::string cur;  // buffer handed to the consumer

  void loop() {
    for (;;) {
      std::string rec, err;
      bool ok = read_record(fp, &rec, &err);
      std::unique_lock<std::mutex> lk(m);
      if (!ok) {
        if (!err.empty()) error = err;
        eof = true;
        cv_pop.notify_all();
        return;
      }
      cv_push.wait(lk, [this] { return queue.size() < capacity || stop; });
      if (stop) return;
      queue.push_back(std::move(rec));
      cv_pop.notify_one();
    }
  }
};

}  // namespace

int MXTPrefetchCreate(const char *path, int capacity, MXTPrefetchHandle *out) {
  FILE *fp = fopen(path, "rb");
  if (!fp) {
    set_err("prefetch: cannot open file");
    return -1;
  }
  auto *p = new Prefetcher();
  p->fp = fp;
  p->capacity = capacity > 0 ? capacity : 64;
  p->th = std::thread([p] { p->loop(); });
  *out = p;
  return 0;
}

int MXTPrefetchNext(MXTPrefetchHandle h, const char **out_data,
                    size_t *out_len) {
  auto *p = static_cast<Prefetcher *>(h);
  std::unique_lock<std::mutex> lk(p->m);
  p->cv_pop.wait(lk, [p] { return !p->queue.empty() || p->eof; });
  if (p->queue.empty()) {
    if (!p->error.empty()) {
      set_err(p->error.c_str());
      return -1;
    }
    *out_data = nullptr;
    *out_len = 0;
    return 0;
  }
  p->cur = std::move(p->queue.front());
  p->queue.pop_front();
  p->cv_push.notify_one();
  *out_data = p->cur.data();
  *out_len = p->cur.size();
  return 0;
}

int MXTPrefetchDestroy(MXTPrefetchHandle h) {
  auto *p = static_cast<Prefetcher *>(h);
  {
    std::lock_guard<std::mutex> lk(p->m);
    p->stop = true;
  }
  p->cv_push.notify_all();
  p->th.join();
  fclose(p->fp);
  delete p;
  return 0;
}

}  // extern "C"
