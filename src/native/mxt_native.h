/*
 * mxnet_tpu native runtime — C ABI.
 *
 * Reference analog: include/mxnet/engine.h (dependency engine),
 * dmlc-core recordio (src/io/), iter_prefetcher.h (threaded prefetch).
 *
 * TPU-native division of labor: XLA/PjRt already schedules *device* work
 * asynchronously, so this engine schedules *host* work — file IO, decode,
 * checkpoint writes, Python callbacks — with the reference's versioned-
 * variable semantics (shared reads, exclusive writes, exception capture at
 * sync points). The RecordIO reader/writer and prefetcher give the data
 * pipeline GIL-free C++ threads, the job OpenCV/dmlc threads did in the
 * reference (src/io/iter_image_recordio_2.cc).
 */
#ifndef MXT_NATIVE_H_
#define MXT_NATIVE_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *MXTEngineHandle;
typedef void *MXTVarHandle;
typedef void *MXTRecordIOHandle;
typedef void *MXTPrefetchHandle;

/* Async op body: runs on an engine worker thread. Return 0 on success,
 * nonzero on failure (an error recorded with MXTSetCallbackError is
 * rethrown at the next sync point). */
typedef int (*MXTOpFn)(void *ctx);
/* Called exactly once after the op completes (success or failure) — used
 * by bindings to release the closure. May be NULL. */
typedef void (*MXTOpDeleter)(void *ctx);

const char *MXTGetLastError(void);
void MXTSetLastError(const char *msg);
void MXTSetCallbackError(const char *msg);

/* ---- dependency engine ---- */
int MXTEngineCreate(int num_threads, MXTEngineHandle *out);
int MXTEngineDestroy(MXTEngineHandle h);
int MXTEngineNewVar(MXTEngineHandle h, MXTVarHandle *out);
/* Deletes the var once all pending ops on it complete. */
int MXTEngineDeleteVar(MXTEngineHandle h, MXTVarHandle var);
int MXTEnginePushAsync(MXTEngineHandle h, MXTOpFn fn, void *ctx,
                       MXTOpDeleter del, MXTVarHandle *const_vars,
                       int n_const, MXTVarHandle *mutable_vars, int n_mut);
/* Blocks until every op that writes `var` (pushed before this call) has
 * completed; returns -1 and sets the error if any async op failed. */
int MXTEngineWaitForVar(MXTEngineHandle h, MXTVarHandle var);
int MXTEngineWaitForAll(MXTEngineHandle h);
/* Var version counter: bumps on each completed write (reference
 * engine.h:44 Var::version). */
int MXTEngineVarVersion(MXTEngineHandle h, MXTVarHandle var, uint64_t *out);

/* ---- RecordIO (dmlc wire format: magic 0xced7230a framing) ---- */
int MXTRecordIOWriterCreate(const char *path, MXTRecordIOHandle *out);
int MXTRecordIOWriterWrite(MXTRecordIOHandle h, const char *data, size_t len,
                           uint64_t *out_pos);
int MXTRecordIOWriterTell(MXTRecordIOHandle h, uint64_t *out);
int MXTRecordIOWriterClose(MXTRecordIOHandle h);
int MXTRecordIOReaderCreate(const char *path, MXTRecordIOHandle *out);
/* *out_data points into an internal buffer valid until the next call.
 * Returns 0 with *out_len == 0 and *out_data == NULL at EOF. */
int MXTRecordIOReaderNext(MXTRecordIOHandle h, const char **out_data,
                          size_t *out_len);
int MXTRecordIOReaderSeek(MXTRecordIOHandle h, uint64_t pos);
int MXTRecordIOReaderTell(MXTRecordIOHandle h, uint64_t *out);
int MXTRecordIOReaderClose(MXTRecordIOHandle h);

/* ---- batchify (src/io/batchify.cc analog) ---- */
/* Parallel stack of n equal-size samples into dst (n * sample_bytes). */
int MXTBatchifyStack(const void *const *srcs, int n, size_t sample_bytes,
                     void *dst, int n_threads);
/* HWC uint8 images -> NCHW float32 with (x/255 - mean[c]) / std[c]. */
int MXTBatchifyImageNormalize(const uint8_t *const *srcs, int n, int h,
                              int w, int c, const float *mean,
                              const float *stddev, float *dst,
                              int n_threads);

/* ---- JPEG decode (libjpeg; the OpenCV-decode-thread analog) ---- */
int MXTImageJPEGInfo(const uint8_t *data, size_t len, int *h, int *w,
                     int *c);
/* out: h*w*out_c HWC uint8; out_c = 3 (RGB) or 1 (grayscale). */
int MXTImageJPEGDecode(const uint8_t *data, size_t len, uint8_t *out,
                       int out_c);

/* ---- PNG decode (libpng simplified API; optional like JPEG) ---- */
int MXTImagePNGInfo(const uint8_t *data, size_t len, int *h, int *w,
                    int *c);
int MXTImagePNGDecode(const uint8_t *data, size_t len, uint8_t *out,
                      int out_c);

/* ---- threaded prefetching reader ---- */
int MXTPrefetchCreate(const char *path, int capacity, MXTPrefetchHandle *out);
/* Blocking pop; at EOF returns 0 with *out_len == 0. The buffer is owned
 * by the handle and valid until the next MXTPrefetchNext call. */
int MXTPrefetchNext(MXTPrefetchHandle h, const char **out_data,
                    size_t *out_len);
int MXTPrefetchDestroy(MXTPrefetchHandle h);

#ifdef __cplusplus
}
#endif

#endif  /* MXT_NATIVE_H_ */
