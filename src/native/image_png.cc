// Native PNG decode via libpng's simplified API.
//
// Reference analog: the same OpenCV decode-thread role as image.cc (JPEG);
// PNG is the second format the reference pipeline decodes
// (src/io/image_recordio parsing accepts any cv::imdecode format).
//
// Conversion parity contract (the Python fallback is PIL): the source is
// always decoded as RGBA, then alpha is DROPPED (PIL convert("RGB")
// semantics — no background compositing) and grayscale uses the exact
// fixed-point ITU-R 601-2 luma Pillow computes in ImagingConvert
// (L = (19595R + 38470G + 7471B + 0x8000) >> 16), so native and fallback
// paths are pixel-identical.
#include <cstdint>
#include <cstring>
#include <vector>

#include <png.h>

#include "mxt_native.h"

extern "C" {

int MXTImagePNGInfo(const uint8_t *data, size_t len, int *h, int *w,
                    int *c) {
  png_image img;
  std::memset(&img, 0, sizeof(img));
  img.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&img, data, len)) {
    MXTSetLastError(img.message);
    return -1;
  }
  *h = static_cast<int>(img.height);
  *w = static_cast<int>(img.width);
  *c = PNG_IMAGE_SAMPLE_CHANNELS(img.format);
  png_image_free(&img);
  return 0;
}

// Decode into out (h*w*out_c HWC uint8); out_c 3 = RGB, 1 = grayscale.
int MXTImagePNGDecode(const uint8_t *data, size_t len, uint8_t *out,
                      int out_c) {
  if (out_c != 1 && out_c != 3) {
    MXTSetLastError("MXTImagePNGDecode: out_c must be 1 or 3");
    return -1;
  }
  png_image img;
  std::memset(&img, 0, sizeof(img));
  img.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&img, data, len)) {
    MXTSetLastError(img.message);
    return -1;
  }
  img.format = PNG_FORMAT_RGBA;  // deterministic: no background composite
  // NOTE: gamma/colorspace-tagged files (gAMA/iCCP/cHRM) never reach this
  // path — the Python dispatcher routes them to PIL, because the
  // simplified API unconditionally converts such files to sRGB while PIL
  // ignores the tags (the pixel-parity contract in the header)
  const size_t n = static_cast<size_t>(img.height) * img.width;
  std::vector<uint8_t> rgba(n * 4);
  if (!png_image_finish_read(&img, nullptr, rgba.data(), 0, nullptr)) {
    MXTSetLastError(img.message);
    png_image_free(&img);
    return -1;
  }
  const uint8_t *src = rgba.data();
  if (out_c == 3) {
    for (size_t i = 0; i < n; ++i) {  // drop alpha (PIL convert("RGB"))
      out[i * 3 + 0] = src[i * 4 + 0];
      out[i * 3 + 1] = src[i * 4 + 1];
      out[i * 3 + 2] = src[i * 4 + 2];
    }
  } else {
    for (size_t i = 0; i < n; ++i) {  // Pillow's exact fixed-point luma
      const uint32_t l = 19595u * src[i * 4] + 38470u * src[i * 4 + 1]
                       + 7471u * src[i * 4 + 2] + 0x8000u;  // L24 rounding
      out[i] = static_cast<uint8_t>(l >> 16);
    }
  }
  return 0;
}

}  // extern "C"
