"""Declarative tunable registry — the autotuner's search-space half.

Every hot-path tunable the framework ships is a :class:`Tunable`
registered HERE, next to the constant it replaces (the constant becomes
the *default*, never a removal): the Pallas VMEM tile budget and rnn
timestep block (``ops/kernels``), the dispatch-window depth
(``engine.inflight_steps``), the ZeRO bucket floor
(``gluon/fused_step``), the serving coalescing knobs
(``serving/batcher``). Each declaration names its candidate grid, a
validity predicate (e.g. block bytes <= the physical VMEM, window
>= 0), and the *seam* that consumes it — the accessor call site hand-
tuners and the autotuner share.

Value resolution at every consumer seam is

    tuned override  >  env var  >  registered default

so a hand-set env var still works standalone, and an applied autotune
config (a trial candidate or a cached winner) wins while it is active.
Overrides are process-global and cheap to read — consumers resolve at
each use site, never at import.

This module is import-light by design (stdlib only): consumer modules
(``engine``, ``ops/kernels``) register at import time without pulling
jax or telemetry.
"""
from __future__ import annotations

import hashlib
import os
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

__all__ = ["Tunable", "SearchSpace", "register", "get", "table",
           "tunables", "value", "set_override", "get_override",
           "clear_overrides", "overrides", "apply_config", "trial",
           "space_signature", "ensure_registered", "SPACE_VERSION"]

#: bumped when the *semantics* of the space change incompatibly; the
#: per-content hash in :func:`space_signature` catches grid/default
#: edits — together they version the cache key.
SPACE_VERSION = 1


class Tunable:
    """One declared tunable: a named knob with a candidate grid.

    - ``name``: dotted ``<group>.<knob>`` (group = the owning layer:
      ``kernels``, ``engine``, ``zero``, ``serving``);
    - ``default``: the shipped constant (what ``MXNET_AUTOTUNE=off``
      and every un-tuned run uses);
    - ``grid``: the candidate values the search sweeps;
    - ``env``: the env var hand-tuners use for the same knob (resolved
      between override and default), with ``parse`` applied to it;
    - ``valid(value, config)``: candidate feasibility against the FULL
      candidate config (cross-knob constraints allowed); invalid
      candidates are filtered before measurement, not scored;
    - ``seam``: human-readable consumer call site (the diagnose table);
    - ``scope``: ``'train'`` | ``'serving'`` | ``'both'`` — which entry
      point sweeps it;
    - ``affects_program``: whether changing it changes the COMPILED
      program on the current backend (the analytical backend re-probes
      per distinct program-affecting subset and reuses its baseline
      probe for everything else).
    """

    def __init__(self, name: str, default: Any, grid: Sequence[Any],
                 seam: str, env: Optional[str] = None,
                 parse: Callable[[str], Any] = None,
                 valid: Optional[Callable[[Any, dict], bool]] = None,
                 scope: str = "train", affects_program: bool = False,
                 doc: str = ""):
        if "." not in name:
            raise ValueError(
                f"tunable name {name!r} must be '<group>.<knob>'")
        if scope not in ("train", "serving", "both"):
            raise ValueError(f"tunable {name!r}: bad scope {scope!r}")
        self.name = name
        self.default = default
        self.grid = tuple(grid)
        self.seam = seam
        self.env = env
        self.parse = parse or (lambda s: s)
        self._valid = valid
        self.scope = scope
        self.affects_program = bool(affects_program)
        self.doc = doc

    def valid(self, value: Any, config: Optional[dict] = None) -> bool:
        """Whether ``value`` is a feasible setting under ``config``
        (the full candidate config; defaults where unspecified)."""
        if self._valid is None:
            return True
        try:
            return bool(self._valid(value, config or {}))
        except Exception:
            return False

    def resolve(self) -> Any:
        """Current effective value at this knob's consumer seam:
        override > env > default."""
        found, v = get_override(self.name)
        if found:
            return v
        if self.env:
            raw = os.environ.get(self.env)
            if raw is not None and raw.strip() != "":
                try:
                    return self.parse(raw)
                except (TypeError, ValueError):
                    pass
        return self.default

    def __repr__(self):
        return (f"Tunable({self.name!r}, default={self.default!r}, "
                f"grid={self.grid!r}, scope={self.scope!r})")


# bare on purpose: leaf module-init lock; never nests with audited locks
_LOCK = threading.Lock()  # mx-lint: allow=MXA009
_REGISTRY: "Dict[str, Tunable]" = {}
_OVERRIDES: "Dict[str, Any]" = {}


def register(t: Tunable) -> Tunable:
    """Register (or re-register — module reloads are idempotent) one
    tunable. Returns it, so consumers can write
    ``_T = space.register(Tunable(...))``."""
    if t.default not in t.grid:
        # the default must be sweepable: search starts from it and the
        # off/cached-miss paths fall back to it
        t.grid = (t.default,) + t.grid
    with _LOCK:
        _REGISTRY[t.name] = t
    return t


def get(name: str) -> Optional[Tunable]:
    return _REGISTRY.get(name)


def tunables(scope: Optional[str] = None) -> Tuple[Tunable, ...]:
    """Registered tunables, name-sorted; ``scope`` filters to the ones
    an entry point sweeps ('train'/'serving' each include 'both')."""
    out = [t for _, t in sorted(_REGISTRY.items())]
    if scope is not None:
        out = [t for t in out if t.scope in (scope, "both")]
    return tuple(out)


def table() -> Tuple[dict, ...]:
    """The diagnose/docs view: one row per registered tunable."""
    return tuple({"name": t.name, "default": t.default,
                  "grid": t.grid, "scope": t.scope,
                  "current": t.resolve(), "seam": t.seam}
                 for t in tunables())


# ---------------------------------------------------------------------------
# overrides — what the autotuner (trials and applied winners) sets
# ---------------------------------------------------------------------------

def value(name: str, default: Any = None) -> Any:
    """Resolved value for ``name`` (override > env > registered
    default); ``default`` when the tunable is unknown. THE consumer-
    seam read — e.g. ``engine.inflight_steps`` resolves through
    here."""
    t = _REGISTRY.get(name)
    if t is None:
        found, v = get_override(name)
        return v if found else default
    return t.resolve()


def set_override(name: str, v: Any):
    with _LOCK:
        _OVERRIDES[name] = v


def get_override(name: str) -> Tuple[bool, Any]:
    """(found, value) — distinguishes 'override set to None/0' from
    'no override'."""
    with _LOCK:
        if name in _OVERRIDES:
            return True, _OVERRIDES[name]
    return False, None


def clear_overrides(names: Optional[Sequence[str]] = None):
    with _LOCK:
        if names is None:
            _OVERRIDES.clear()
        else:
            for n in names:
                _OVERRIDES.pop(n, None)


def overrides() -> Dict[str, Any]:
    with _LOCK:
        return dict(_OVERRIDES)


def apply_config(config: Dict[str, Any]):
    """Install a (partial) config as overrides — the 'make this the
    active tuned config' operation for cached winners."""
    for k, v in config.items():
        set_override(k, v)


class trial:
    """Context manager applying a candidate config for the duration of
    one measurement, restoring the prior overrides on exit (including
    removal of keys the trial introduced)."""

    def __init__(self, config: Dict[str, Any]):
        self._config = dict(config)
        self._saved: Optional[Dict[str, Any]] = None

    def __enter__(self):
        with _LOCK:
            self._saved = dict(_OVERRIDES)
            _OVERRIDES.update(self._config)
        return self

    def __exit__(self, *exc):
        with _LOCK:
            _OVERRIDES.clear()
            _OVERRIDES.update(self._saved or {})
        return False


class SearchSpace:
    """A scoped view over the registered tunables — what one search
    sweeps. The process-global registry is the universe;
    ``SearchSpace('train')`` / ``SearchSpace('serving')`` are the two
    entry-point slices."""

    def __init__(self, scope: Optional[str] = None):
        self.scope = scope

    @property
    def tunables(self) -> Tuple[Tunable, ...]:
        return tunables(self.scope)

    def defaults(self) -> Dict[str, Any]:
        return {t.name: t.default for t in self.tunables}

    def current(self) -> Dict[str, Any]:
        """Effective values at every seam right now (override > env >
        default)."""
        return {t.name: t.resolve() for t in self.tunables}

    def valid(self, config: Dict[str, Any]) -> bool:
        """Whether a full candidate config satisfies every tunable's
        predicate."""
        return all(t.valid(config.get(t.name, t.default), config)
                   for t in self.tunables)

    def signature(self) -> str:
        return space_signature(self.scope)

    def __len__(self):
        return len(self.tunables)

    def __iter__(self):
        return iter(self.tunables)


# ---------------------------------------------------------------------------
# space identity (cache-key component)
# ---------------------------------------------------------------------------

def space_signature(scope: Optional[str] = None) -> str:
    """Content hash of the registered space: name, default, grid and
    scope of every tunable (+ :data:`SPACE_VERSION`). A grid or
    default edit in any consumer module invalidates cached winners —
    a stale config for a space that no longer exists must never
    replay."""
    parts = [f"v{SPACE_VERSION}"]
    for t in tunables(scope):
        parts.append(f"{t.name}={t.default!r}:{t.grid!r}:{t.scope}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]


def ensure_registered():
    """Import every consumer module that registers tunables, so
    :func:`table` and the search see the full space regardless of what
    the process has touched so far."""
    import importlib
    for mod in ("mxnet_tpu.engine", "mxnet_tpu.ops.kernels",
                "mxnet_tpu.gluon.fused_step", "mxnet_tpu.serving.batcher",
                "mxnet_tpu.serving.decode"):
        try:
            importlib.import_module(mod)
        except Exception:        # pragma: no cover - partial installs
            pass
