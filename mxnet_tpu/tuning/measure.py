"""Measurement backends — how one candidate config becomes one score.

Two backends behind one interface (``measure(config, fidelity) ->
MeasureResult``, score = estimated/measured SECONDS per step or per
request row, lower is better):

- **timed** (:class:`TimedStepBackend` / :class:`TimedPredictorBackend`)
  — hardware truth: apply the candidate, run K warmup + N measured
  executions of the real compiled program through a real
  :class:`~mxnet_tpu.engine.DispatchWindow`, read the wall clock at the
  drain (the same retire-to-retire quantity the
  ``mx_step_time_seconds`` watchdog gauges). ``fidelity`` scales N —
  the successive-halving rungs re-measure survivors longer.
- **analytical** (:class:`AnalyticalStepBackend` /
  :class:`AnalyticalPredictorBackend`) — CPU/CI truth: score candidates
  from the compiled program's ``cost_analysis`` FLOPs and
  ``memory_analysis`` traffic against the checked-in roofline
  (analysis/fusion.py), plus closed-form models of the knobs the
  program itself cannot express — dispatch-overhead amortization over
  the in-flight window, per-collective latency over the ZeRO unit
  count, coalescing delay over the serving batch knobs. Deterministic:
  the same space always picks the same winner, which is what lets
  tier-1 exercise the full closed loop bit-reproducibly.

A candidate that FAILS — OOM, device loss, Mosaic lowering error — is
scored infeasible (``feasible=False``, score=inf) via the PR 11 failure
taxonomy (``elastic.detect.classify``) instead of killing the search;
the ``autotune.trial`` fault point brackets every measurement so the
chaos harness can inject exactly that.
"""
from __future__ import annotations

import logging
import math
import time
from typing import Any, Dict, Optional

from . import space as _space
from ..testing.faults import fault_point

__all__ = ["MeasureResult", "TimedStepBackend", "AnalyticalStepBackend",
           "TimedPredictorBackend", "AnalyticalPredictorBackend",
           "backend_mode", "select_step_backend",
           "select_predictor_backend", "HOST_DISPATCH_S",
           "COLLECTIVE_LAT_S"]

_LOG = logging.getLogger("mxnet_tpu.tuning")

#: per-step host dispatch overhead the in-flight window amortizes
#: (PR 1 measured the fused CPU MLP step at ~0.27 ms host-side; the
#: window overlaps it with device compute: overhead / (1 + W))
HOST_DISPATCH_S = 300e-6

#: fixed launch latency per collective op (ring setup, not wire bytes —
#: those are in the program's memory traffic already); the ZeRO bucket
#: floor trades this count against update-fusion granularity
COLLECTIVE_LAT_S = 5e-6

INFEASIBLE = float("inf")


class MeasureResult:
    """One trial's verdict: ``score`` seconds (lower is better; inf
    when infeasible), the feasibility flag + reason, and the backend's
    term breakdown for the BENCH/diagnose provenance."""

    def __init__(self, score: float, feasible: bool = True,
                 reason: str = "", detail: Optional[dict] = None):
        self.score = float(score)
        self.feasible = bool(feasible)
        self.reason = reason
        self.detail = detail or {}

    @classmethod
    def infeasible(cls, reason: str) -> "MeasureResult":
        return cls(INFEASIBLE, feasible=False, reason=reason)

    def __repr__(self):
        if not self.feasible:
            return f"MeasureResult(infeasible: {self.reason})"
        return f"MeasureResult({self.score:.3e}s)"


def _classify(exc: BaseException) -> str:
    try:
        from ..elastic import detect as _d
        return _d.classify(exc)
    except Exception:            # pragma: no cover - defensive
        return "fatal"


def guarded_measure(backend, config: Dict[str, Any],
                    fidelity: int = 1) -> MeasureResult:
    """Run one measurement with the full fault discipline: the
    ``autotune.trial`` chaos seam brackets it, and ANY failure becomes
    an infeasible score tagged with the PR 11 failure class — an OOM
    or device-lost candidate must never kill the search (the NEXT
    candidate may be fine; that is the point of searching)."""
    try:
        fault_point("autotune.trial", "before")
        out = backend.measure(config, fidelity=fidelity)
        fault_point("autotune.trial", "after")
        return out
    except (KeyboardInterrupt, SystemExit):
        raise
    except BaseException as e:
        kind = _classify(e)
        _LOG.warning("autotune: candidate %r infeasible (%s: %s: %s)",
                     config, kind, type(e).__name__, e)
        return MeasureResult.infeasible(
            f"{kind}: {type(e).__name__}: {e}")


# ---------------------------------------------------------------------------
# shared analytical constants
# ---------------------------------------------------------------------------

def _roofline():
    from ..analysis import fusion as _f
    return (_f.BENCH_ROOFLINE_TFLOPS * 1e12,
            _f.HBM_BANDWIDTH_GBPS * 1e9)


def _cfg_value(config: Dict[str, Any], name: str):
    if name in config:
        return config[name]
    t = _space.get(name)
    return t.resolve() if t is not None else None


# ---------------------------------------------------------------------------
# train-step backends
# ---------------------------------------------------------------------------

class _FreshPrograms:
    """Build trial programs in a scratch bucket cache: snapshot the
    step's compiled-bucket state, clear it so the next lower sees the
    TRIAL config, restore everything on exit — an autotune probe is not
    a training retrace and must not leave trial programs (or their
    signatures) behind."""

    def __init__(self, step):
        self._step = step

    def __enter__(self):
        s = self._step
        self._saved = (s._lru, set(s._trace_signatures),
                       list(s._sig_history), s._n_traces)
        from collections import OrderedDict
        s._lru = OrderedDict()
        return self

    def __exit__(self, *exc):
        s = self._step
        (s._lru, s._trace_signatures, s._sig_history,
         s._n_traces) = self._saved
        return False


def _program_key(config: Dict[str, Any], tunables) -> tuple:
    """The program-affecting slice of a candidate — probes are cached
    per distinct value of this (knobs that cannot change the compiled
    program on this backend share one probe)."""
    return tuple((t.name, config.get(t.name, t.default))
                 for t in tunables if t.affects_program)


class AnalyticalStepBackend:
    """Deterministic score for one ``CompiledTrainStep`` bucket:

    ``max(flops/F, traffic/B)``  (the program on the roofline)
    ``+ HOST_DISPATCH_S / (1 + inflight)``  (window amortization)
    ``+ n_zero_units(min_size) * COLLECTIVE_LAT_S``  (collective count)
    ``+ exposed_comm_s``  (schedule-level non-overlapped comm,
    analysis/overlap.py — rewards candidates whose collectives hide
    behind compute)

    The program term comes from ONE lower+compile per distinct
    program-affecting config slice (``cost_analysis`` FLOPs +
    ``memory_analysis`` argument/output/temp bytes), probed inside a
    :class:`_FreshPrograms` scratch so trials never pollute the live
    bucket cache."""

    name = "analytical"
    deterministic = True

    def __init__(self, step, args, kwargs=None,
                 batch_size: Optional[int] = None, tunables=()):
        self._step = step
        self._args = args
        self._kwargs = kwargs or {}
        self._batch_size = batch_size
        self._tunables = tuple(tunables)
        self._probes: Dict[tuple, dict] = {}

    def _probe(self, config: Dict[str, Any]) -> dict:
        key = _program_key(config, self._tunables)
        hit = self._probes.get(key)
        if hit is not None:
            return hit
        step = self._step
        with _space.trial(config), _FreshPrograms(step):
            info = step.lower_entry(*self._args,
                                    batch_size=self._batch_size,
                                    **self._kwargs)
            if info is None:
                # eager path: no program to score — every candidate
                # ties, the defaults win, which is the right answer
                probe = {"flops": 0.0, "traffic_bytes": 0.0,
                         "exposed_comm_s": 0.0, "overlap_fraction": 1.0}
            else:
                compiled = info["lowered"].compile()
                flops = 0.0
                try:
                    ca = compiled.cost_analysis()
                    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
                    flops = float(ca.get("flops", 0.0))
                except Exception:   # pragma: no cover - backend-dep
                    pass
                traffic = 0.0
                try:
                    from ..telemetry.memory import MemoryReport
                    rep = MemoryReport.from_compiled(compiled)
                    traffic = float(rep.argument_bytes
                                    + rep.output_bytes
                                    + rep.temp_bytes)
                except Exception:   # pragma: no cover - backend-dep
                    pass
                exposed, frac = 0.0, 1.0
                try:
                    # exposed-comm posture of the candidate's schedule
                    # (analysis/overlap.py): a bucketing knob that hides
                    # its collectives behind backward/update compute
                    # scores strictly better than one that serializes
                    # them, even at equal FLOPs and traffic
                    from ..analysis import overlap as _ov
                    orep = _ov.overlap_census(compiled.as_text())
                    exposed = float(orep.exposed_comm_s)
                    frac = float(orep.overlap_fraction)
                except Exception:   # pragma: no cover - backend-dep
                    pass
                probe = {"flops": flops, "traffic_bytes": traffic,
                         "exposed_comm_s": exposed,
                         "overlap_fraction": frac}
        self._probes[key] = probe
        return probe

    def _zero_units(self, min_size) -> int:
        """Reduce-scatter/all-gather unit count under a candidate
        bucket floor — pure host math over the trainable param sizes
        (mirrors _ZeroShardPlan's solo-vs-bucketed split)."""
        step = self._step
        if step._zero is None and step._zero_ok is None:
            return 0
        try:
            min_size = int(min_size)
        except (TypeError, ValueError):
            return 0
        solo = 0
        bucket_dtypes = set()
        for p in step._trainer._params:
            d = p._data._data if p._data is not None else None
            if d is None:
                continue
            if int(d.size) >= min_size:
                solo += 1
            else:
                bucket_dtypes.add(str(d.dtype))
        return solo + len(bucket_dtypes)

    def measure(self, config: Dict[str, Any],
                fidelity: int = 1) -> MeasureResult:
        probe = self._probe(config)
        F, B = _roofline()
        t_program = max(probe["flops"] / F,
                        probe["traffic_bytes"] / B)
        w = _cfg_value(config, "engine.inflight_steps")
        w = 0 if w is None else max(0, int(w))
        t_host = HOST_DISPATCH_S / (1.0 + w)
        n_units = self._zero_units(
            _cfg_value(config, "zero.shard_min_size"))
        t_coll = 2 * n_units * COLLECTIVE_LAT_S   # RS + AG per unit
        t_exposed = float(probe.get("exposed_comm_s", 0.0))
        score = t_program + t_host + t_coll + t_exposed
        if not math.isfinite(score):
            return MeasureResult.infeasible("non-finite analytical score")
        return MeasureResult(score, detail={
            "t_program": t_program, "t_host": t_host,
            "t_collective": t_coll, "flops": probe["flops"],
            "traffic_bytes": probe["traffic_bytes"],
            "zero_units": n_units,
            "exposed_comm_s": t_exposed,
            "overlap_fraction": probe.get("overlap_fraction", 1.0),
            "zero_bucket_bytes": _cfg_value(config, "zero.bucket_bytes")})


class TimedStepBackend:
    """Hardware truth for one ``CompiledTrainStep`` bucket: apply the
    candidate, run ``warmup`` + ``steps * fidelity`` real steps through
    a fresh :class:`~mxnet_tpu.engine.DispatchWindow` (so the
    ``engine.inflight_steps`` candidate actually governs the pipeline
    being timed), and score seconds/step at the drain.

    Trials EXECUTE the train step, so the orchestrator snapshots and
    restores the full train state around the search
    (``checkpoint.state.capture_train_state``) — tuning must never move
    the model. A candidate whose program-affecting knobs differ from
    the last measured one drops the step's bucket cache first (the
    recompile is the cost of measuring it — that is what
    ``MXNET_AUTOTUNE_BUDGET_TRIALS`` bounds)."""

    name = "timed"
    deterministic = False

    def __init__(self, step, args, kwargs=None,
                 batch_size: Optional[int] = None, tunables=(),
                 warmup: int = 2, steps: int = 4):
        self._step = step
        self._args = args
        self._kwargs = kwargs or {}
        self._batch_size = batch_size
        self._tunables = tuple(tunables)
        self._warmup = max(1, int(warmup))
        self._steps = max(1, int(steps))
        self._last_key: Optional[tuple] = None

    def measure(self, config: Dict[str, Any],
                fidelity: int = 1) -> MeasureResult:
        import jax
        from ..engine import DispatchWindow
        step = self._step
        with _space.trial(config):
            key = _program_key(config, self._tunables)
            if self._last_key is not None and key != self._last_key:
                step._lru.clear()
            self._last_key = key
            n = self._steps * max(1, int(fidelity))
            window = DispatchWindow(what="autotune trial step")
            for _ in range(self._warmup):
                window.push(step(*self._args,
                                 batch_size=self._batch_size,
                                 **self._kwargs)._data)
            window.drain()
            t0 = time.perf_counter()
            for i in range(n):
                window.push(step(*self._args,
                                 batch_size=self._batch_size,
                                 **self._kwargs)._data, tag=i)
            window.drain()
            dt = time.perf_counter() - t0
        return MeasureResult(dt / n, detail={
            "steps": n, "wall_s": dt,
            "inflight": window.max_inflight})


# ---------------------------------------------------------------------------
# predictor backends
# ---------------------------------------------------------------------------

class AnalyticalPredictorBackend:
    """Deterministic per-request-row latency model for one
    ``CompiledPredictor`` + ``DynamicBatcher`` deployment:

    ``t_bucket(max_batch)/max_batch``  (compute amortized over rows)
    ``+ HOST_DISPATCH_S / max_batch``  (one dispatch per micro-batch)
    ``+ batch_timeout/2``              (mean coalescing delay)

    ``t_bucket`` comes from the AOT flop count of the bucket
    ``max_batch`` pads into (the probe compiles it exactly as
    ``warmup()`` would — nothing is wasted)."""

    name = "analytical"
    deterministic = True

    def __init__(self, pred, example, tunables=()):
        self._pred = pred
        self._example = tuple(example)
        self._tunables = tuple(tunables)
        self._flops: Dict[int, float] = {}

    def _bucket_flops(self, bucket: int) -> float:
        hit = self._flops.get(bucket)
        if hit is not None:
            return hit
        from ..serving.predictor import (_ARRAY_TYPES, _data_of,
                                         _pad_rows)
        padded = tuple(
            _pad_rows(l, bucket) if isinstance(l, _ARRAY_TYPES)
            and getattr(_data_of(l), "ndim", 0) >= 1 else l
            for l in self._example)
        flops = self._pred.aot_compile(*padded) or 0.0
        self._flops[bucket] = float(flops)
        return self._flops[bucket]

    def measure(self, config: Dict[str, Any],
                fidelity: int = 1) -> MeasureResult:
        m = _cfg_value(config, "serving.max_batch")
        m = 1 if m is None else max(1, int(m))
        timeout_ms = _cfg_value(config, "serving.batch_timeout_ms")
        timeout_ms = 0.0 if timeout_ms is None else float(timeout_ms)
        with _space.trial(config):
            bucket = self._pred.bucket_for(m)   # raises -> infeasible
            flops = self._bucket_flops(bucket)
        F, _B = _roofline()
        t_bucket = flops / F
        score = (t_bucket + HOST_DISPATCH_S) / m + timeout_ms / 2e3
        return MeasureResult(score, detail={
            "bucket": bucket, "t_bucket": t_bucket,
            "max_batch": m, "timeout_ms": timeout_ms})


class TimedPredictorBackend:
    """Measured per-row latency: pad the example to the candidate
    ``serving.max_batch``'s bucket and time ``steps * fidelity``
    dispatches of the real compiled program (plus the candidate's mean
    coalescing delay as an additive term — the linger is policy, not
    program, so it is modeled, not slept)."""

    name = "timed"
    deterministic = False

    def __init__(self, pred, example, tunables=(), warmup: int = 2,
                 steps: int = 8):
        self._pred = pred
        self._example = tuple(example)
        self._warmup = max(1, int(warmup))
        self._steps = max(1, int(steps))

    def measure(self, config: Dict[str, Any],
                fidelity: int = 1) -> MeasureResult:
        import jax
        from ..serving.predictor import (_ARRAY_TYPES, _data_of,
                                         _pad_rows)
        m = _cfg_value(config, "serving.max_batch")
        m = 1 if m is None else max(1, int(m))
        timeout_ms = _cfg_value(config, "serving.batch_timeout_ms")
        timeout_ms = 0.0 if timeout_ms is None else float(timeout_ms)
        with _space.trial(config):
            bucket = self._pred.bucket_for(m)
            padded = tuple(
                _pad_rows(l, bucket) if isinstance(l, _ARRAY_TYPES)
                and getattr(_data_of(l), "ndim", 0) >= 1 else l
                for l in self._example)
            n = self._steps * max(1, int(fidelity))
            for _ in range(self._warmup):
                out = self._pred.predict(*padded)
            jax.tree_util.tree_map(
                lambda a: jax.block_until_ready(
                    getattr(a, "_data", a)), out)
            t0 = time.perf_counter()
            for _ in range(n):
                out = self._pred.predict(*padded)
            jax.tree_util.tree_map(
                lambda a: jax.block_until_ready(
                    getattr(a, "_data", a)), out)
            dt = time.perf_counter() - t0
        score = dt / n / m + timeout_ms / 2e3
        return MeasureResult(score, detail={
            "bucket": bucket, "dispatches": n, "wall_s": dt})


# ---------------------------------------------------------------------------
# backend selection
# ---------------------------------------------------------------------------

def backend_mode() -> str:
    """``MXNET_AUTOTUNE_BACKEND``: ``auto`` (timed on accelerators,
    analytical on CPU — CI stays deterministic) | ``timed`` |
    ``analytical``."""
    import os
    v = os.environ.get("MXNET_AUTOTUNE_BACKEND", "auto").strip().lower()
    return v if v in ("timed", "analytical") else "auto"


def _pick(kind: str) -> str:
    mode = backend_mode()
    if mode != "auto":
        return mode
    import jax
    return "timed" if jax.default_backend() != "cpu" else "analytical"


def select_step_backend(step, args, kwargs=None, batch_size=None,
                        tunables=()):
    cls = (TimedStepBackend if _pick("step") == "timed"
           else AnalyticalStepBackend)
    return cls(step, args, kwargs, batch_size=batch_size,
               tunables=tunables)


def select_predictor_backend(pred, example, tunables=()):
    cls = (TimedPredictorBackend if _pick("predict") == "timed"
           else AnalyticalPredictorBackend)
    return cls(pred, example, tunables=tunables)
