"""mx.tuning — the self-tuning performance autopilot.

Every hot-path tunable PRs 1-12 shipped as a hand-picked constant — the
Pallas VMEM tile budget and rnn timestep block, the in-flight window
depth, the ZeRO bucket floor, the serving coalescing knobs — is now a
declared :class:`~mxnet_tpu.tuning.space.Tunable` with a candidate
grid, a validity predicate, and the consumer seam it feeds. This
package closes the loop the observability stack (PRs 6-9) made
possible: *measure* each candidate (live step timing on hardware,
``cost_analysis``/``memory_analysis``-based scoring on CPU/CI),
*search* the joint space (budget-bounded coordinate descent with
successive halving, faulting candidates scored infeasible through the
PR 11 taxonomy), *persist* winners keyed by the program's compile-
cache-style signature so a restarted job replays its tuned config with
zero trials.

Gating — ``MXNET_AUTOTUNE``:

- ``off`` (default): nothing happens; every seam resolves env > the
  shipped default, exactly as before this package existed;
- ``cached``: cached winners replay (0 trials); a cache miss falls
  back to the defaults WITHOUT searching — the production setting
  (and the bench default): pay trials on the tuning box, never in the
  serving/training fleet;
- ``on``: cache miss runs the search (≤ ``MXNET_AUTOTUNE_BUDGET_
  TRIALS`` measurements), persists the winner to
  ``MXNET_AUTOTUNE_CACHE``, applies it.

Entry points: ``Trainer.compile_step(autotune=...)`` tunes on the
first step call (when a real batch pins the shape bucket);
``CompiledPredictor.warmup(autotune=...)`` tunes before AOT-compiling
the buckets. Both default the flag to the env gate, so arming
``MXNET_AUTOTUNE`` ambiently covers TrainLoop/bench/serving without
code changes.

Tunables never change numerics — only speed. The timed backend
snapshots and restores the full train state around its trials, the
analytical backend never executes the program at all, and
tests/test_tuning.py pins tuned-vs-default loss bit-exactness.
"""
from __future__ import annotations

import logging
import math
import os
import time as _time
from typing import Any, Dict, Optional

from . import cache, measure, search, space
from .cache import (AutotuneCache, cache_path, default_cache,
                    predictor_signature, signature_key, step_signature)
from .measure import (AnalyticalPredictorBackend, AnalyticalStepBackend,
                      MeasureResult, TimedPredictorBackend,
                      TimedStepBackend, backend_mode)
from .search import SearchResult, Trial, coordinate_search
from .space import SearchSpace, Tunable

__all__ = ["space", "measure", "search", "cache", "Tunable",
           "SearchSpace", "MeasureResult", "SearchResult", "Trial",
           "AutotuneCache", "AutotuneOutcome", "autotune_mode",
           "budget_trials", "tune_step", "tune_predictor",
           "outcomes", "last_outcome", "coordinate_search",
           "step_signature", "predictor_signature", "signature_key",
           "cache_path", "default_cache", "backend_mode"]

_LOG = logging.getLogger("mxnet_tpu.tuning")


def autotune_mode(explicit: Optional[str] = None) -> str:
    """Normalized gate: ``off`` | ``cached`` | ``on``. ``explicit``
    (the ``autotune=`` kwarg) wins over ``MXNET_AUTOTUNE``."""
    v = explicit if explicit is not None \
        else os.environ.get("MXNET_AUTOTUNE", "")
    if isinstance(v, bool):
        return "on" if v else "off"
    v = str(v).strip().lower()
    if v in ("on", "1", "true", "yes", "search"):
        return "on"
    if v in ("cached", "cache", "replay"):
        return "cached"
    return "off"


def budget_trials(default: int = 32) -> int:
    """``MXNET_AUTOTUNE_BUDGET_TRIALS`` — total measurements one
    search may spend (the default-config baseline is trial #1)."""
    try:
        v = int(os.environ.get("MXNET_AUTOTUNE_BUDGET_TRIALS",
                               str(default)))
    except (TypeError, ValueError):
        return default
    return max(1, v)


class AutotuneOutcome:
    """What one entry-point invocation did — the record bench/diagnose
    attach next to the kernel/fusion posture."""

    def __init__(self, mode: str, source: str, key: Optional[str] = None,
                 backend: Optional[str] = None,
                 config: Optional[Dict[str, Any]] = None,
                 trials: int = 0, delta_pct: Optional[float] = None,
                 score: Optional[float] = None,
                 default_score: Optional[float] = None):
        self.mode = mode          # off | cached | on
        self.source = source      # off | cache | default | search
        self.key = key
        self.backend = backend
        self.config = dict(config or {})   # the applied NON-default slice
        self.trials = int(trials)
        self.delta_pct = delta_pct
        self.score = score
        self.default_score = default_score

    def to_dict(self) -> dict:
        return {"mode": self.mode, "source": self.source,
                "key": self.key, "backend": self.backend,
                "config": self.config, "trials": self.trials,
                "delta_pct": self.delta_pct}

    def bench_dict(self) -> dict:
        """The three fields the BENCH json carries per leg."""
        return {"autotune_config": self.config,
                "autotune_trials": self.trials,
                "autotune_delta_pct": self.delta_pct}

    def __repr__(self):
        return (f"AutotuneOutcome({self.source}, trials={self.trials}, "
                f"config={self.config})")


_OUTCOMES: list = []


def outcomes() -> list:
    """Every AutotuneOutcome this process produced, oldest first."""
    return list(_OUTCOMES)


def last_outcome() -> Optional[AutotuneOutcome]:
    return _OUTCOMES[-1] if _OUTCOMES else None


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

def _telemetry():
    from .. import telemetry as _t
    return _t


def _publish_active(config: Dict[str, Any]):
    """``mx_autotune_active_config{tunable}`` info gauge: numeric
    values verbatim, non-numeric ones as their grid index (the gauge
    says WHICH candidate is live; the cache record holds the value)."""
    try:
        t = _telemetry()
        g = t.registry().gauge(t.names.AUTOTUNE_ACTIVE,
                               label_key="tunable")
        for name, v in config.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                tn = space.get(name)
                try:
                    v = tn.grid.index(v) if tn else 1
                except ValueError:
                    v = -1
            g.set(float(v), label=name)
    except Exception:            # pragma: no cover - telemetry guard
        _LOG.debug("active-config gauge publish failed", exc_info=True)


def _count(counter_name: str, label: Optional[str] = None, n: int = 1):
    try:
        t = _telemetry()
        c = t.registry().counter(
            counter_name,
            label_key="backend" if label is not None else None)
        c.inc(n, label=label) if label is not None else c.inc(n)
    except Exception:            # pragma: no cover - telemetry guard
        _LOG.debug("autotune counter failed", exc_info=True)


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

def _tune(scope: str, key: str, make_backend, mode: str,
          budget: Optional[int], db: Optional[AutotuneCache],
          snapshot_state=None) -> AutotuneOutcome:
    t = _telemetry()
    db = db or default_cache()
    rec = db.get(key)
    if rec is not None and isinstance(rec.get("config"), dict):
        _count(t.names.AUTOTUNE_CACHE_HITS)
        config = dict(rec["config"])
        space.apply_config(config)
        _publish_active(config)
        out = AutotuneOutcome(mode, "cache", key=key,
                              backend=rec.get("backend"),
                              config=config, trials=0,
                              delta_pct=rec.get("delta_pct"),
                              score=rec.get("score"),
                              default_score=rec.get("default_score"))
        _OUTCOMES.append(out)
        _LOG.info("autotune[%s]: cache HIT %s -> %r", scope, key[:12],
                  config)
        return out
    _count(t.names.AUTOTUNE_CACHE_MISSES)
    if mode != "on":
        # cached-mode miss: the defaults run, zero trials — production
        # never pays measurement cost it did not opt into
        out = AutotuneOutcome(mode, "default", key=key, trials=0)
        _OUTCOMES.append(out)
        _LOG.info("autotune[%s]: cache MISS %s (mode=cached; defaults)",
                  scope, key[:12])
        return out
    backend = make_backend()
    tunables = space.tunables(scope)
    budget = budget if budget is not None else budget_trials()

    def on_trial(trial):
        _count(t.names.AUTOTUNE_TRIALS, label=backend.name)

    state = None
    if snapshot_state is not None and not backend.deterministic:
        state = snapshot_state()
    try:
        result = coordinate_search(tunables, backend, budget,
                                   on_trial=on_trial)
    finally:
        if state is not None:
            state()
    tuned = result.tuned_overrides()
    db.put(key, {
        "config": tuned, "score":
            None if not math.isfinite(result.best_score)
            else result.best_score,
        "default_score":
            None if not math.isfinite(result.default_score)
            else result.default_score,
        "delta_pct": result.delta_pct, "trials": result.n_trials,
        "backend": backend.name, "scope": scope,
        "space": space.space_signature(scope),
        "created": _time.time(),
        "trial_log": [tr.to_dict() for tr in result.trials],
    })
    space.apply_config(tuned)
    _publish_active(tuned)
    out = AutotuneOutcome(mode, "search", key=key,
                          backend=backend.name, config=tuned,
                          trials=result.n_trials,
                          delta_pct=result.delta_pct,
                          score=result.best_score,
                          default_score=result.default_score)
    _OUTCOMES.append(out)
    _LOG.info("autotune[%s]: searched %d trials, tuned=%r "
              "(delta %s%%), persisted %s", scope, result.n_trials,
              tuned, result.delta_pct, key[:12])
    return out


def tune_step(step, args, kwargs=None, batch_size: Optional[int] = None,
              mode: Optional[str] = None, budget: Optional[int] = None,
              db: Optional[AutotuneCache] = None) -> AutotuneOutcome:
    """Tune one ``CompiledTrainStep`` for the shape bucket ``args``
    pins. Called by the step itself on its first ``__call__`` when
    ``compile_step(autotune=)``/``MXNET_AUTOTUNE`` arms it; callable
    directly for explicit offline tuning. Applies (and, after a
    search, persists) the winning config as tuned overrides; returns
    the :class:`AutotuneOutcome`."""
    mode = autotune_mode(mode)
    if mode == "off":
        return AutotuneOutcome("off", "off")
    space.ensure_registered()
    kwargs = kwargs or {}
    key = step_signature(step, args, kwargs)
    tunables = space.tunables("train")

    def make_backend():
        return measure.select_step_backend(
            step, args, kwargs, batch_size=batch_size,
            tunables=tunables)

    def snapshot_state():
        # timed trials EXECUTE real steps: capture the full train
        # state (params, fused/zero optimizer state, counters, RNG)
        # and hand back the restore thunk — tuning must not move the
        # model (docs/PERF_NOTES.md "Autotuner")
        from ..checkpoint.state import (apply_train_state,
                                        capture_train_state)
        st = capture_train_state(trainer=step._trainer)

        def restore():
            apply_train_state(st, trainer=step._trainer)
        return restore

    return _tune("train", key, make_backend, mode, budget, db,
                 snapshot_state=snapshot_state)


def tune_predictor(pred, example, mode: Optional[str] = None,
                   budget: Optional[int] = None,
                   db: Optional[AutotuneCache] = None) -> AutotuneOutcome:
    """Tune one ``CompiledPredictor`` deployment's serving knobs from
    an example request. Called by ``warmup(autotune=)``; the tuned
    overrides govern any :class:`~mxnet_tpu.serving.DynamicBatcher`
    constructed afterwards."""
    mode = autotune_mode(mode)
    if mode == "off":
        return AutotuneOutcome("off", "off")
    space.ensure_registered()
    key = predictor_signature(pred, example)
    tunables = space.tunables("serving")

    def make_backend():
        return measure.select_predictor_backend(pred, example,
                                                tunables=tunables)

    return _tune("serving", key, make_backend, mode, budget, db)
