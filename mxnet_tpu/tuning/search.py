"""Budget-bounded coordinate descent with successive halving.

The joint space is small-dimensional (a handful of knobs, each with a
short grid) but measurements are expensive, so the search is

- **coordinate descent** over the registered tunables in name order:
  sweep one knob's grid with every other knob pinned at the incumbent,
  adopt a strictly-better winner, move on; repeat passes until a full
  pass improves nothing (or the budget runs out);
- **successive halving** inside each sweep when the backend is NOISY
  (``deterministic=False``): measure every candidate at fidelity 1,
  keep the better half, re-measure the survivors at doubled fidelity —
  cheap trials eliminate, expensive trials decide. Deterministic
  backends measure each candidate exactly once (re-measuring the same
  number wastes budget);
- **budget-bounded**: ``MXNET_AUTOTUNE_BUDGET_TRIALS`` caps TOTAL
  measurements (the default-config baseline is trial #1); the search
  returns its best-so-far when the budget runs dry, never raises.

Every measurement goes through :func:`measure.guarded_measure`, so a
faulting candidate (OOM, device loss, lowering error) is an infeasible
SCORE, not a dead search. Invalid candidates (the tunable's validity
predicate says no — block bytes over the physical VMEM, a batch over
the largest bucket) are filtered before measuring and cost no budget.
"""
from __future__ import annotations

import logging
import math
from typing import Any, Callable, Dict, List, Optional

from .measure import MeasureResult, guarded_measure

__all__ = ["Trial", "SearchResult", "coordinate_search"]

_LOG = logging.getLogger("mxnet_tpu.tuning")

#: relative improvement a candidate must clear to replace the
#: incumbent — ties keep the default (stability beats churn)
MIN_REL_IMPROVEMENT = 1e-9


class Trial:
    """One measurement: the candidate config, its verdict, and which
    rung (fidelity) it ran at."""

    def __init__(self, number: int, config: Dict[str, Any],
                 result: MeasureResult, fidelity: int = 1):
        self.number = number
        self.config = dict(config)
        self.result = result
        self.fidelity = fidelity

    def to_dict(self) -> dict:
        return {"number": self.number, "config": self.config,
                "score": None if not math.isfinite(self.result.score)
                else self.result.score,
                "feasible": self.result.feasible,
                "reason": self.result.reason,
                "fidelity": self.fidelity}


class SearchResult:
    """The search's verdict: the winning config (FULL config — every
    swept tunable pinned, defaults included), its score, the
    default-config baseline score, and the full trial log."""

    def __init__(self, best_config: Dict[str, Any], best_score: float,
                 default_config: Dict[str, Any], default_score: float,
                 trials: List[Trial], budget: int, exhausted: bool):
        self.best_config = dict(best_config)
        self.best_score = best_score
        self.default_config = dict(default_config)
        self.default_score = default_score
        self.trials = list(trials)
        self.budget = budget
        self.exhausted = exhausted

    @property
    def n_trials(self) -> int:
        return len(self.trials)

    @property
    def improved(self) -> bool:
        return (math.isfinite(self.best_score)
                and math.isfinite(self.default_score)
                and self.best_score
                < self.default_score * (1 - MIN_REL_IMPROVEMENT))

    @property
    def delta_pct(self) -> Optional[float]:
        """Win over the defaults, percent of the default score (None
        when either side is unmeasurable)."""
        if not (math.isfinite(self.best_score)
                and math.isfinite(self.default_score)
                and self.default_score > 0):
            return None
        return round((self.default_score - self.best_score)
                     / self.default_score * 100.0, 3)

    def tuned_overrides(self) -> Dict[str, Any]:
        """The non-default slice of the winner — what actually gets
        applied/persisted (a knob tuned TO its default needs no
        override)."""
        return {k: v for k, v in self.best_config.items()
                if v != self.default_config.get(k)}

    def to_dict(self) -> dict:
        return {"best_config": self.best_config,
                "tuned": self.tuned_overrides(),
                "best_score": None if not math.isfinite(self.best_score)
                else self.best_score,
                "default_score":
                    None if not math.isfinite(self.default_score)
                    else self.default_score,
                "delta_pct": self.delta_pct,
                "n_trials": self.n_trials, "budget": self.budget,
                "exhausted": self.exhausted}


def coordinate_search(tunables, backend, budget: int,
                      max_passes: int = 3,
                      on_trial: Optional[Callable[[Trial], None]]
                      = None) -> SearchResult:
    """Coordinate-descent + successive-halving search over
    ``tunables`` scored by ``backend`` (``measure.guarded_measure``
    wraps every call). Returns the best feasible config found within
    ``budget`` total measurements."""
    tunables = tuple(tunables)
    budget = max(1, int(budget))
    trials: List[Trial] = []
    measured: Dict[tuple, MeasureResult] = {}
    exhausted = [False]

    def cfg_key(config):
        return tuple(sorted(config.items()))

    def run(config, fidelity=1) -> Optional[MeasureResult]:
        key = cfg_key(config)
        if backend.deterministic and key in measured:
            return measured[key]           # free: same score by design
        if len(trials) >= budget:
            exhausted[0] = True
            return None
        res = guarded_measure(backend, config, fidelity=fidelity)
        t = Trial(len(trials) + 1, config, res, fidelity)
        trials.append(t)
        measured[key] = res
        if on_trial is not None:
            try:
                on_trial(t)
            except Exception:    # pragma: no cover - telemetry guard
                pass
        return res

    default_config = {t.name: t.default for t in tunables}
    base = run(default_config)
    default_score = base.score if base is not None else float("inf")
    best_config = dict(default_config)
    best_score = default_score

    for _pass in range(max(1, int(max_passes))):
        improved = False
        for t in tunables:
            if exhausted[0]:
                break
            cands = []
            for v in t.grid:
                if v == best_config[t.name]:
                    continue
                cand = dict(best_config, **{t.name: v})
                if not t.valid(v, cand):
                    continue
                cands.append(cand)
            if not cands:
                continue
            # rung 0: everyone at fidelity 1
            fidelity = 1
            ring = []
            for cand in cands:
                res = run(cand, fidelity)
                if res is None:
                    break
                if res.feasible:
                    ring.append((cand, res.score))
            # successive halving (noisy backends only): survivors
            # re-measured at doubled fidelity until one remains
            while (not backend.deterministic and len(ring) > 1
                   and not exhausted[0]):
                ring.sort(key=lambda cs: cs[1])
                ring = ring[:max(1, len(ring) // 2)]
                if len(ring) == 1:
                    break
                fidelity *= 2
                nxt = []
                for cand, _old in ring:
                    res = run(cand, fidelity)
                    if res is None:
                        break
                    if res.feasible:
                        nxt.append((cand, res.score))
                if not nxt:
                    break
                ring = nxt
            if not ring:
                continue
            ring.sort(key=lambda cs: cs[1])
            cand, score = ring[0]
            if math.isfinite(score) and (
                    not math.isfinite(best_score)
                    or score < best_score * (1 - MIN_REL_IMPROVEMENT)):
                best_config, best_score = dict(cand), score
                improved = True
        if not improved or exhausted[0]:
            break

    _LOG.info("autotune search: %d/%d trials, default=%.3e best=%.3e "
              "tuned=%r", len(trials), budget, default_score,
              best_score,
              {k: v for k, v in best_config.items()
               if v != default_config.get(k)})
    return SearchResult(best_config, best_score, default_config,
                        default_score, trials, budget, exhausted[0])
