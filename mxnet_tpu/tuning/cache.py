"""Persistent autotune-config DB — winners keyed by program identity.

A tuned config is only worth its trials if a *restarted* job replays it
for free: the DB maps a signature key — compile-cache-style program
identity (param/input shapes+dtypes, step mode), mesh shape, jax
backend, and the tunable-space version — to the winning config plus its
provenance (trial count, score vs default, backend, timestamp). Keys
are content hashes, so any drift in what was tuned (a model edit, a
different dp size, a grid change in the space) is a MISS, never a
silently-wrong replay.

Storage is one JSON file (``MXNET_AUTOTUNE_CACHE``) written atomically
(tmp + fsync + rename — the checkpoint stack's
:func:`~mxnet_tpu.checkpoint.atomic.atomic_write_bytes`); with the env
unset the DB is process-local memory, which still de-duplicates
repeated tuning inside one job. Concurrent writers last-write-win at
file granularity — each ``put`` re-reads, merges, and rewrites, so two
jobs tuning DIFFERENT programs into one shared file both land.
"""
from __future__ import annotations

import json
import hashlib
import logging
import os
from typing import Any, Dict, Optional

from ..analysis.threads import mx_lock

__all__ = ["AutotuneCache", "cache_path", "default_cache",
           "signature_key", "step_signature", "predictor_signature",
           "CACHE_SCHEMA"]

_LOG = logging.getLogger("mxnet_tpu.tuning")

CACHE_SCHEMA = 1


def cache_path() -> Optional[str]:
    """``MXNET_AUTOTUNE_CACHE`` — path of the persistent config DB
    (None = in-memory only)."""
    p = os.environ.get("MXNET_AUTOTUNE_CACHE", "").strip()
    return p or None


class AutotuneCache:
    """Atomic JSON config DB. ``path=None`` = memory-only."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._mem: Dict[str, dict] = {}
        self._lock = mx_lock("tuning.cache")

    # ------------- file half -------------
    def _read_file(self) -> Dict[str, dict]:
        if not self.path or not os.path.exists(self.path):
            return {}
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            if doc.get("schema") != CACHE_SCHEMA:
                _LOG.warning("autotune cache %s: schema %r != %d; "
                             "ignoring", self.path, doc.get("schema"),
                             CACHE_SCHEMA)
                return {}
            entries = doc.get("entries")
            return entries if isinstance(entries, dict) else {}
        except (OSError, ValueError) as e:
            # a corrupt/truncated DB costs a re-tune, never a crash
            _LOG.warning("autotune cache %s unreadable (%s: %s); "
                         "treating as empty", self.path,
                         type(e).__name__, e)
            return {}

    def _write_file(self, entries: Dict[str, dict]):
        from ..checkpoint.atomic import atomic_write_bytes
        data = json.dumps({"schema": CACHE_SCHEMA, "entries": entries},
                          indent=1, sort_keys=True).encode()
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        atomic_write_bytes(self.path, data, fault="autotune.cache")

    # ------------- API -------------
    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            if key in self._mem:
                return dict(self._mem[key])
        rec = self._read_file().get(key)
        if rec is not None:
            with self._lock:
                self._mem[key] = dict(rec)
            return dict(rec)
        return None

    def put(self, key: str, record: dict):
        """Persist one winner (read-merge-rewrite when file-backed)."""
        rec = dict(record)
        with self._lock:
            self._mem[key] = dict(rec)
        if not self.path:
            return
        with self._lock:
            entries = self._read_file()
            entries[key] = rec
            try:
                self._write_file(entries)
            except OSError as e:   # pragma: no cover - fs-dependent
                _LOG.warning("autotune cache write failed (%s: %s); "
                             "config kept in-memory only",
                             type(e).__name__, e)

    def keys(self):
        entries = self._read_file()
        with self._lock:
            return sorted(set(entries) | set(self._mem))


_DEFAULT: Optional[AutotuneCache] = None
_DEFAULT_PATH: Optional[str] = None
_DLOCK = mx_lock("tuning.cache.default")


def default_cache() -> AutotuneCache:
    """Process-default cache bound to the CURRENT
    ``MXNET_AUTOTUNE_CACHE`` (re-bound when the env changes — tests
    monkeypatch it per case)."""
    global _DEFAULT, _DEFAULT_PATH
    p = cache_path()
    with _DLOCK:
        if _DEFAULT is None or p != _DEFAULT_PATH:
            _DEFAULT = AutotuneCache(p)
            _DEFAULT_PATH = p
    return _DEFAULT


# ---------------------------------------------------------------------------
# signature keys
# ---------------------------------------------------------------------------

def signature_key(program_sig: str, mesh_shape: Any, backend: str,
                  space_sig: str) -> str:
    """The DB key: (compile-cache-style program signature, mesh shape,
    jax backend, tunable-space version) content-hashed."""
    raw = f"{program_sig}|mesh={mesh_shape!r}|{backend}|{space_sig}"
    return hashlib.sha1(raw.encode()).hexdigest()


def _backend_and_mesh(mesh=None):
    import jax
    backend = jax.default_backend()
    shape = None
    if mesh is not None:
        try:
            shape = tuple(sorted(dict(mesh.shape).items()))
        except Exception:
            shape = repr(getattr(mesh, "shape", None))
    return backend, shape


def step_signature(step, args, kwargs=None, scope: str = "train") -> str:
    """Stable-across-processes identity of one ``CompiledTrainStep``
    program + its input-shape bucket: every parameter's (shape, dtype)
    in binding order, the traced input leaves' (shape, dtype), the
    train/numerics/zero configuration, mesh shape and jax backend, and
    the space signature. Anything that would compile a different
    program (or change which seams exist) changes the key."""
    from . import space as _space
    kwargs = kwargs or {}
    parts = ["step"]
    for p in step._all_params:
        d = p._data._data if p._data is not None else None
        parts.append(f"p:{None if d is None else (tuple(d.shape), str(d.dtype))}")
    try:
        traced, _treedef, static_spec, _mask = step._flatten(args, kwargs)
        for l in traced:
            d = l._data if hasattr(l, "_data") else l
            parts.append(f"x:{tuple(d.shape)}:{d.dtype}")
        parts.append(f"static:{static_spec!r}")
    except Exception:            # pragma: no cover - defensive
        parts.append(f"x:<unflattenable:{len(args)},{sorted(kwargs)}>")
    parts.append(f"train:{step._train}")
    parts.append(f"numerics:{step._numerics}")
    parts.append(f"zero:{step._zero_requested}:{step._zero_axis}")
    opt = step._trainer._optimizer
    parts.append(f"opt:{type(opt).__name__}")
    mesh = step._zero_mesh
    if mesh is None:
        try:
            from ..parallel.mesh import current_mesh
            mesh = current_mesh()
        except Exception:        # pragma: no cover - defensive
            mesh = None
    backend, mesh_shape = _backend_and_mesh(mesh)
    return signature_key("|".join(parts), mesh_shape, backend,
                         _space.space_signature(scope))


def predictor_signature(pred, example, scope: str = "serving") -> str:
    """Identity of one ``CompiledPredictor`` deployment: param
    (shape, dtype)s, the example request's leaf shapes (minus the
    bucketed leading dim), the bucket ladder, backend, space."""
    from . import space as _space
    parts = ["predict"]
    for p in pred._params:
        d = p._data._data
        parts.append(f"p:{tuple(d.shape)}:{d.dtype}")
    for l in example:
        d = getattr(l, "_data", l)
        shp = tuple(getattr(d, "shape", ()))
        parts.append(f"x:{shp[1:] if shp else ()}:"
                     f"{getattr(d, 'dtype', type(l).__name__)}")
    parts.append(f"buckets:{pred.bucket_sizes}")
    backend, mesh_shape = _backend_and_mesh(None)
    return signature_key("|".join(parts), mesh_shape, backend,
                         _space.space_signature(scope))
