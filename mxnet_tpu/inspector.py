"""Tensor inspector: value dumping, checkers, checksums, NaN guard.

Reference analog: ``src/common/tensor_inspector.h`` (TensorInspector with
interactive_print/check_value/dump_to_file and the CheckerType zoo) — the
debugging utility the reference compiles into every build. TPU-native
additions: checks run as one jitted reduction on device (no host transfer
until a failure is found), and an env-gated invoke-funnel guard
(``MXNET_INSPECT_NAN=1``) validates every imperative op's outputs, naming
the producing op — the eager analog of jax's debug_nans.
"""
from __future__ import annotations

import io
import os
import zlib
from typing import Callable, List, Optional, Tuple, Union

import numpy as onp

import jax.numpy as jnp

from .base import MXNetError
from .ops import registry as _registry

__all__ = ["TensorInspector", "CheckerType", "install_nan_guard",
           "remove_nan_guard"]


class CheckerType:
    """Value checkers (reference tensor_inspector.h:71 CheckerType)."""
    NegativeChecker = "negative"
    PositiveChecker = "positive"
    ZeroChecker = "zero"
    NaNChecker = "nan"
    InfChecker = "inf"
    NegativeInfChecker = "neg_inf"
    PositiveInfChecker = "pos_inf"
    FiniteChecker = "finite"
    AbnormalChecker = "abnormal"   # nan or inf


_CHECKS = {
    CheckerType.NegativeChecker: lambda d: d < 0,
    CheckerType.PositiveChecker: lambda d: d > 0,
    CheckerType.ZeroChecker: lambda d: d == 0,
    CheckerType.NaNChecker: lambda d: jnp.isnan(d),
    CheckerType.InfChecker: lambda d: jnp.isinf(d),
    CheckerType.NegativeInfChecker: lambda d: jnp.isneginf(d),
    CheckerType.PositiveInfChecker: lambda d: jnp.isposinf(d),
    CheckerType.FiniteChecker: lambda d: ~jnp.isfinite(d),
    CheckerType.AbnormalChecker: lambda d: ~jnp.isfinite(d),
}


def _raw(t):
    return t._data if hasattr(t, "_data") else jnp.asarray(t)


class TensorInspector:
    """Inspect one tensor (reference TensorInspector)."""

    def __init__(self, tensor, tag: str = ""):
        self._t = _raw(tensor)
        self._tag = tag

    # -- printing ----------------------------------------------------------
    def to_string(self) -> str:
        arr = onp.asarray(self._t)
        head = (f"Tensor{f' <{self._tag}>' if self._tag else ''} "
                f"shape={tuple(arr.shape)} dtype={arr.dtype}")
        return head + "\n" + onp.array2string(arr, threshold=200)

    def interactive_print(self, tag: str = ""):
        """Non-interactive environments get the plain dump (the reference
        prompts on a terminal; under a driver we just print)."""
        if tag:
            self._tag = tag
        print(self.to_string())

    # -- value checking ----------------------------------------------------
    def check_value(self, checker: Union[str, Callable],
                    interactive: bool = False,
                    tag: str = "") -> List[Tuple[int, ...]]:
        """Return coordinates of violating values. The ANY-violation test is
        one jitted device reduction; coordinates are computed on host only
        when a violation exists (keeps the common clean path transfer-free).
        """
        fn = _CHECKS.get(checker, checker)
        if not callable(fn):
            raise MXNetError(f"unknown checker {checker!r}")
        mask = fn(self._t)
        if not bool(jnp.any(mask)):
            return []
        coords = [tuple(int(i) for i in idx)
                  for idx in zip(*onp.nonzero(onp.asarray(mask)))]
        if interactive or tag:
            print(f"check_value <{tag or self._tag}>: "
                  f"{len(coords)} violations, first at {coords[0]}")
        return coords

    # -- checksums / dumping ----------------------------------------------
    def checksum(self) -> int:
        """CRC32 of the raw bytes (reference dump checksum usage)."""
        return zlib.crc32(onp.ascontiguousarray(onp.asarray(self._t)))

    def dump_to_file(self, tag: str, directory: str = ".") -> str:
        """Write .npy named <tag>_<n>.npy (reference dump_to_file naming
        with a per-tag visit counter). The write is crash-safe — staged
        to a temp file, fsynced, and os.replace'd via the same atomic
        helper ``nd.save`` and the telemetry dump writers use — so a
        kill mid-dump never leaves a torn .npy; the sequence number
        advances only on a durable write (a failed attempt retries
        under the same name)."""
        from .checkpoint.atomic import atomic_write_bytes
        count = _dump_counters.get(tag, 0) + 1
        path = os.path.join(directory, f"{tag}_{count}.npy")
        buf = io.BytesIO()
        onp.save(buf, onp.asarray(self._t))
        atomic_write_bytes(path, buf.getvalue(), fault="inspector.dump")
        _dump_counters[tag] = count
        return path


_dump_counters: dict = {}

# ---------------------------------------------------------------------------
# Invoke-funnel NaN guard
# ---------------------------------------------------------------------------

_guard_installed = False
#: output-check hook that was active before install (restored on remove)
_prev_output_check: Optional[Callable] = None


def _numerics_monitor():
    """The telemetry numerics monitor (lazy: the guard must work even
    if telemetry failed to import) — eager non-finite hits feed the
    SAME anomaly channel as the compiled-step numerics watchdog, one
    ``nonfinite_eager`` event per episode."""
    try:
        from .telemetry import numerics
        return numerics.monitor()
    except Exception:            # pragma: no cover - defensive
        return None


def _check_concrete_outputs(name, outs):
    """Shared checker for both funnels: raise (naming the op) on the
    first non-finite float output, and report/arm the telemetry
    episode. Tracers are skipped — inside a trace values are unknown."""
    import jax
    checked = False
    for i, o in enumerate(outs):
        d = _raw(o)
        if isinstance(d, jax.core.Tracer):
            continue
        if hasattr(d, "dtype") and jnp.issubdtype(d.dtype, jnp.floating):
            checked = True
            if not bool(jnp.all(jnp.isfinite(d))):
                mon = _numerics_monitor()
                if mon is not None:
                    mon.eager_nonfinite(name, i)
                raise MXNetError(
                    f"MXNET_INSPECT_NAN: op {name!r} produced a "
                    f"non-finite value in output {i}")
    if checked:
        mon = _numerics_monitor()
        if mon is not None:
            mon.eager_clean()       # a clean op re-arms the episode


def _nan_guard_wrapper(name, fn):
    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        _check_concrete_outputs(
            name, out if isinstance(out, (tuple, list)) else (out,))
        return out
    return wrapped


def install_nan_guard():
    """Check every imperative op's outputs for NaN/Inf, raising with the op
    name (reference check_value NaNChecker wired through the invoke funnel;
    enabled at import when MXNET_INSPECT_NAN=1). Covers both plain eager
    ops (invoke wrapper) and ops under autograd.record (tape hook on the
    concrete vjp primals — inside record the kernel itself only sees
    Tracers). Each violation also emits one ``nonfinite_eager`` anomaly
    per episode on the telemetry watchdog channel (a clean checked op
    re-arms). Idempotent: calling it twice never double-wraps.
    Synchronizes per op — debugging tool, not a production mode."""
    global _guard_installed, _prev_output_check
    if _guard_installed:
        return
    from . import _tape
    # defensive de-dup before add: even if install state was corrupted
    # (e.g. a prior exception), the funnel carries at most one wrapper
    _registry.remove_invoke_wrapper(_nan_guard_wrapper)
    _registry.add_invoke_wrapper(_nan_guard_wrapper)
    try:
        _prev_output_check = _tape.set_output_check(
            _check_concrete_outputs)
    except BaseException:        # pragma: no cover - defensive
        _registry.remove_invoke_wrapper(_nan_guard_wrapper)
        raise
    _guard_installed = True


def remove_nan_guard():
    """Uninstall the guard (idempotent) and RESTORE whatever output
    check was active before install — never clobbers another
    subsystem's hook, and restores cleanly even if the unwrap path
    raises."""
    global _guard_installed, _prev_output_check
    if not _guard_installed:
        return
    from . import _tape
    try:
        _registry.remove_invoke_wrapper(_nan_guard_wrapper)
    finally:
        _tape.set_output_check(_prev_output_check)
        _prev_output_check = None
        _guard_installed = False


if os.environ.get("MXNET_INSPECT_NAN", "0") == "1":
    install_nan_guard()
