"""Detection data pipeline (reference python/mxnet/image/detection.py):
label-aware augmenters that transform bounding boxes together with the
image, and ``ImageDetIter`` batching variable-object labels.

Label convention (reference ImageDetIter): per image a float array of
shape (num_objects, width>=5) whose rows are
``[class_id, xmin, ymin, xmax, ymax, ...]`` with coordinates normalized
to [0, 1]. Batches pad the object axis with -1 rows (class_id < 0 means
"no object" — the same sentinel MultiBoxTarget consumes).

TPU-native notes: augmentation is host-side numpy (it is per-image,
branchy, and cheap next to decode); everything the accelerator touches is
the final fixed-shape (B, C, H, W) / (B, max_obj, width) pair, so the
compiled training step never sees a dynamic shape.
"""
from __future__ import annotations

import random as pyrandom
from math import sqrt
from typing import List, Optional, Sequence

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ndarray.ndarray import array as nd_array
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    ForceResizeAug, HueJitterAug, LightingAug, RandomGrayAug,
                    ResizeAug, _as_np, fixed_crop, imdecode_or_raw,
                    imresize_np)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateMultiRandCropAugmenter", "CreateDetAugmenter",
           "ImageDetIter"]


def _box_areas(boxes: onp.ndarray) -> onp.ndarray:
    """Areas of normalized [x1, y1, x2, y2] rows (clipped at 0)."""
    return (onp.maximum(0.0, boxes[:, 2] - boxes[:, 0])
            * onp.maximum(0.0, boxes[:, 3] - boxes[:, 1]))


class DetAugmenter:
    """Base detection augmenter: ``aug(src, label) -> (src, label)``
    (reference DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift a plain image Augmenter into the detection pipeline: it
    touches pixels only, labels pass through (reference DetBorrowAug).
    Only geometry-preserving augmenters are safe to borrow."""

    def __init__(self, augmenter: Augmenter):
        super().__init__(augmenter=augmenter._kwargs)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick ONE augmenter from a list, or skip entirely with
    ``skip_prob`` (reference DetRandomSelectAug)."""

    def __init__(self, aug_list: Sequence[DetAugmenter],
                 skip_prob: float = 0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and boxes horizontally with probability p (reference
    DetHorizontalFlipAug)."""

    def __init__(self, p: float = 0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = nd_array(_as_np(src)[:, ::-1].copy())
            label = label.copy()
            x1 = label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - x1
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop that re-expresses boxes in crop
    coordinates (reference DetRandomCropAug): the crop must have aspect
    ratio and relative area within range, must cover at least
    ``min_object_covered`` of some object, and objects keeping less than
    ``min_eject_coverage`` of their area are ejected. ``max_attempts``
    failed proposals -> return the input unchanged."""

    def __init__(self, min_object_covered: float = 0.1,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.05, 1.0),
                 min_eject_coverage: float = 0.3, max_attempts: int = 50):
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts
        self.enabled = (0 < area_range[0] <= area_range[1]
                        and 0 < aspect_ratio_range[0]
                        <= aspect_ratio_range[1])

    def __call__(self, src, label):
        img = _as_np(src)
        height, width = img.shape[0], img.shape[1]
        prop = self._propose(label, height, width)
        if prop is None:
            return src, label
        x, y, w, h, new_label = prop
        return fixed_crop(src, x, y, w, h, None), new_label

    def _covered_enough(self, boxes, x1, y1, x2, y2) -> bool:
        """Does the crop cover > min_object_covered of some object?"""
        areas = _box_areas(boxes)
        valid = areas > 0
        if not valid.any():
            return False
        b = boxes[valid]
        ix1 = onp.maximum(b[:, 0], x1)
        iy1 = onp.maximum(b[:, 1], y1)
        ix2 = onp.minimum(b[:, 2], x2)
        iy2 = onp.minimum(b[:, 3], y2)
        inter = (onp.maximum(0.0, ix2 - ix1)
                 * onp.maximum(0.0, iy2 - iy1))
        cov = inter / areas[valid]
        cov = cov[cov > 0]
        return cov.size > 0 and cov.min() > self.min_object_covered

    def _shift_labels(self, label, x1, y1, cw, ch) -> Optional[onp.ndarray]:
        """Re-express boxes in crop coordinates; eject shrunken objects."""
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] - x1) / cw
        out[:, (2, 4)] = (out[:, (2, 4)] - y1) / ch
        out[:, 1:5] = onp.clip(out[:, 1:5], 0.0, 1.0)
        old = _box_areas(label[:, 1:5])
        new = _box_areas(out[:, 1:5]) * cw * ch
        with onp.errstate(divide="ignore", invalid="ignore"):
            coverage = onp.where(old > 0, new / old, 0.0)
        keep = (out[:, 3] > out[:, 1]) & (out[:, 4] > out[:, 2]) \
            & (coverage > self.min_eject_coverage)
        if not keep.any():
            return None
        return out[keep]

    def _propose(self, label, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return None
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h_lo = int(round(sqrt(min_area / ratio)))
            h_hi = min(int(round(sqrt(max_area / ratio))), height,
                       int(width / ratio))
            if h_lo > h_hi or h_hi <= 0:
                continue
            h = pyrandom.randint(max(1, h_lo), h_hi)
            w = min(int(round(h * ratio)), width)
            if not (min_area * 0.99 <= w * h <= max_area * 1.01):
                continue
            if w * h < 2:
                continue
            y = pyrandom.randint(0, height - h)
            x = pyrandom.randint(0, width - w)
            nx1, ny1 = x / width, y / height
            nx2, ny2 = (x + w) / width, (y + h) / height
            if not self._covered_enough(label[:, 1:5], nx1, ny1, nx2, ny2):
                continue
            new_label = self._shift_labels(label, nx1, ny1,
                                           nx2 - nx1, ny2 - ny1)
            if new_label is not None:
                return x, y, w, h, new_label
        return None


class DetRandomPadAug(DetAugmenter):
    """Random expansion padding: place the image on a larger canvas and
    shrink boxes accordingly (reference DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts: int = 50,
                 pad_val=(128, 128, 128)):
        if not isinstance(pad_val, (tuple, list)):
            pad_val = (pad_val,) * 3
        if not isinstance(aspect_ratio_range, (tuple, list)):
            aspect_ratio_range = (aspect_ratio_range, aspect_ratio_range)
        if not isinstance(area_range, (tuple, list)):
            area_range = (area_range, area_range)
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.pad_val = pad_val
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.enabled = (area_range[1] > 1.0
                        and 0 < aspect_ratio_range[0]
                        <= aspect_ratio_range[1])

    def __call__(self, src, label):
        img = _as_np(src)
        height, width = img.shape[0], img.shape[1]
        prop = self._propose(height, width)
        if prop is None:
            return src, label
        x, y, w, h = prop
        c = img.shape[2]
        pv = onp.asarray(self.pad_val, img.dtype)
        if pv.size != c:  # e.g. 3-tuple pad on a grayscale image
            pv = pv.flat[0]
        canvas = onp.empty((h, w, c), img.dtype)
        canvas[...] = pv
        canvas[y:y + height, x:x + width] = img
        out = label.copy()
        out[:, (1, 3)] = (out[:, (1, 3)] * width + x) / w
        out[:, (2, 4)] = (out[:, (2, 4)] * height + y) / h
        return nd_array(canvas), out

    def _propose(self, height, width):
        if not self.enabled or height <= 0 or width <= 0:
            return None
        min_area = self.area_range[0] * height * width
        max_area = self.area_range[1] * height * width
        for _ in range(self.max_attempts):
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            if ratio <= 0:
                continue
            h_lo = max(height, int(round(sqrt(min_area / ratio))),
                       int(round(width / ratio)))
            h_hi = int(round(sqrt(max_area / ratio)))
            if h_lo > h_hi:
                continue
            h = pyrandom.randint(h_lo, h_hi)
            w = int(round(h * ratio))
            if (h - height) < 2 or (w - width) < 2:
                continue
            y = pyrandom.randint(0, h - height)
            x = pyrandom.randint(0, w - width)
            return x, y, w, h
        return None


def CreateMultiRandCropAugmenter(min_object_covered=0.1,
                                 aspect_ratio_range=(0.75, 1.33),
                                 area_range=(0.05, 1.0),
                                 min_eject_coverage=0.3, max_attempts=50,
                                 skip_prob=0.0) -> DetRandomSelectAug:
    """One DetRandomCropAug per element when the constraint arguments are
    lists (SSD-style multi-constraint sampling), randomly selected per
    image (reference CreateMultiRandCropAugmenter)."""
    def as_list(v):
        return list(v) if isinstance(v, (list, tuple)) \
            and isinstance(v[0], (list, tuple)) else None

    covered = min_object_covered if isinstance(min_object_covered,
                                               (list, tuple)) \
        else [min_object_covered]
    ratios = as_list(aspect_ratio_range) or [aspect_ratio_range]
    areas = as_list(area_range) or [area_range]
    ejects = min_eject_coverage if isinstance(min_eject_coverage,
                                              (list, tuple)) \
        else [min_eject_coverage]
    attempts = max_attempts if isinstance(max_attempts, (list, tuple)) \
        else [max_attempts]
    n = max(len(covered), len(ratios), len(areas), len(ejects),
            len(attempts))

    def pick(lst, i):
        return lst[i] if i < len(lst) else lst[-1]

    augs = [DetRandomCropAug(pick(covered, i), pick(ratios, i),
                             pick(areas, i), pick(ejects, i),
                             pick(attempts, i)) for i in range(n)]
    return DetRandomSelectAug(augs, skip_prob=skip_prob)


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50,
                       pad_val=(127, 127, 127)) -> List[DetAugmenter]:
    """Standard detection augmenter stack (reference CreateDetAugmenter):
    resize -> constrained random crop -> mirror -> random pad -> force
    resize -> cast -> color jitter/hue/PCA/gray -> normalize, with boxes
    transformed wherever geometry changes."""
    augs: List[DetAugmenter] = []
    if resize > 0:
        augs.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        augs.append(CreateMultiRandCropAugmenter(
            min_object_covered, aspect_ratio_range,
            (area_range[0], min(1.0, area_range[1])), min_eject_coverage,
            max_attempts, skip_prob=1 - rand_crop))
    if rand_mirror:
        augs.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        augs.append(DetRandomSelectAug(
            [DetRandomPadAug(aspect_ratio_range,
                             (1.0, max(1.0 + 1e-6, area_range[1])),
                             max_attempts, pad_val)],
            skip_prob=1 - rand_pad))
    augs.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    augs.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        augs.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        augs.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = onp.array([55.46, 4.794, 1.148])
        eigvec = onp.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        augs.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        augs.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53], "float32")
    if std is True:
        std = onp.array([58.395, 57.12, 57.375], "float32")
    if mean is not None or std is not None:
        augs.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return augs


class ImageDetIter:
    """Detection batch iterator (reference ImageDetIter): variable-object
    labels padded with -1 rows into a fixed (batch, max_obj, width)
    tensor so the compiled step sees static shapes.

    Sources: ``imglist`` — a list of ``(label, image)`` pairs where label
    is an (N, >=5) float array (or the reference's flat header form
    ``[header_width, obj_width, ...]``) and image is an HWC uint8 numpy
    array or a file path under ``path_root`` — or ``path_imgrec``, a
    RecordIO file whose headers carry the flat label form.
    """

    def __init__(self, batch_size: int, data_shape, path_imgrec=None,
                 imglist=None, path_root: str = "", shuffle: bool = False,
                 aug_list: Optional[List[DetAugmenter]] = None,
                 label_shape=None, last_batch_handle: str = "pad",
                 **kwargs):
        if (path_imgrec is None) == (imglist is None):
            raise MXNetError(
                "ImageDetIter needs exactly one of path_imgrec / imglist")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.path_root = path_root
        self.shuffle = shuffle
        self.auglist = aug_list if aug_list is not None \
            else CreateDetAugmenter(data_shape, **kwargs)
        self._samples = []
        if imglist is not None:
            for label, img in imglist:
                self._samples.append((self._parse_label(label), img))
        else:
            from .. import recordio as rio
            reader = rio.MXRecordIO(path_imgrec, "r")
            while True:
                rec = reader.read()
                if rec is None:
                    break
                header, payload = rio.unpack(rec)
                self._samples.append(
                    (self._parse_label(onp.asarray(header.label)), payload))
            reader.close()
        if not self._samples:
            raise MXNetError("ImageDetIter: empty data source")
        self.label_width = self._samples[0][0].shape[1]
        if label_shape is None:
            max_obj = max(s[0].shape[0] for s in self._samples)
            label_shape = (max_obj, self.label_width)
        self.label_shape = tuple(label_shape)
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError(f"last_batch_handle must be pad/discard/"
                             f"roll_over, got {last_batch_handle!r}")
        self._order = list(range(len(self._samples)))
        self._cursor = 0
        self._leftover: List[int] = []
        self._last_batch_handle = last_batch_handle
        self.reset()

    # ---------------- label plumbing ----------------
    @staticmethod
    def _parse_label(label) -> onp.ndarray:
        """Accept (N, >=5) arrays or the reference flat form
        ``[header_width, obj_width, <header...>, obj fields...]``."""
        arr = onp.asarray(label, "float32")
        if arr.ndim == 2:
            if arr.shape[1] < 5:
                raise MXNetError(f"label width must be >= 5, got "
                                 f"{arr.shape[1]}")
            return arr
        raw = arr.ravel()
        if raw.size < 7:
            raise MXNetError(f"label is too short: {raw.size}")
        header_width = int(raw[0])
        obj_width = int(raw[1])
        if obj_width < 5:
            raise MXNetError(f"object width must be >= 5, got {obj_width}")
        body = raw[header_width:]
        body = body[:(body.size // obj_width) * obj_width]
        out = body.reshape(-1, obj_width)
        return out[out[:, 0] >= 0]  # drop -1 padding rows

    def _pad_label(self, label: onp.ndarray) -> onp.ndarray:
        max_obj, width = self.label_shape
        out = onp.full((max_obj, width), -1.0, "float32")
        n = min(label.shape[0], max_obj)
        out[:n, :min(width, label.shape[1])] = \
            label[:n, :min(width, label.shape[1])]
        return out

    def sync_label_shape(self, it: "ImageDetIter", verbose: bool = False):
        """Make two iterators (train/val) agree on the padded label shape
        (reference ImageDetIter.sync_label_shape)."""
        shape = (max(self.label_shape[0], it.label_shape[0]),
                 max(self.label_shape[1], it.label_shape[1]))
        self.label_shape = shape
        it.label_shape = shape
        return it

    # ---------------- iteration ----------------
    @property
    def provide_data(self):
        from ..io.io import DataDesc
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        from ..io.io import DataDesc
        return [DataDesc("label",
                         (self.batch_size,) + self.label_shape)]

    def reset(self):
        order = list(range(len(self._samples)))
        if self.shuffle:
            pyrandom.shuffle(order)
        # roll_over: the deferred tail of last epoch leads this one
        self._order = self._leftover + order
        self._leftover = []
        self._cursor = 0

    def __iter__(self):
        return self

    def _load_image(self, img):
        if isinstance(img, bytes):
            return imdecode_or_raw(img, self.data_shape)
        if isinstance(img, str):
            import os
            with open(os.path.join(self.path_root, img), "rb") as f:
                return imdecode_or_raw(f.read(), self.data_shape)
        return onp.asarray(img)

    def _augment(self, img: onp.ndarray, label: onp.ndarray):
        src: NDArray = nd_array(onp.ascontiguousarray(img))
        for aug in self.auglist:
            src, label = aug(src, label) if isinstance(aug, DetAugmenter) \
                else (aug(src), label)
        arr = _as_np(src).astype("float32")
        c, h, w = self.data_shape
        if arr.shape[0] != h or arr.shape[1] != w:
            arr = imresize_np(arr, w, h)
        return arr.transpose(2, 0, 1), self._pad_label(label)

    def next(self):
        from ..io.io import DataBatch
        remaining = len(self._order) - self._cursor
        if remaining <= 0:
            raise StopIteration
        if remaining < self.batch_size:
            if self._last_batch_handle == "discard":
                raise StopIteration
            if self._last_batch_handle == "roll_over":
                # defer the tail to the next epoch instead of padding
                self._leftover = self._order[self._cursor:]
                self._cursor = len(self._order)
                raise StopIteration
        datas, labels = [], []
        while len(datas) < self.batch_size \
                and self._cursor < len(self._order):
            label, img = self._samples[self._order[self._cursor]]
            self._cursor += 1
            d, l = self._augment(self._load_image(img), label)
            datas.append(d)
            labels.append(l)
        pad = self.batch_size - len(datas)
        while len(datas) < self.batch_size:
            datas.append(datas[-1])
            labels.append(labels[-1])
        return DataBatch([nd_array(onp.stack(datas))],
                         [nd_array(onp.stack(labels))], pad=pad)

    __next__ = next
