"""Image ops + augmenters (reference: python/mxnet/image/image.py — imdecode/
imresize/crops/jitter augmenters + CreateAugmenter, backed by OpenCV in the
reference).

TPU-native notes: JPEG decodes through the native libjpeg path
(src/native/image.cc — GIL-free, the OpenCV-decode-thread analog), other
formats through PIL, with a raw-array fallback; resize lowers to
``jax.image.resize`` (an XLA program — runs on TPU for on-device
preprocessing); augmenters are numpy/NDArray transforms applied CPU-side in
the data pipeline.
"""
from __future__ import annotations

import io as _io
import random as pyrandom
from typing import List, Optional

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array as nd_array

__all__ = ["imdecode", "imresize", "imresize_np", "imdecode_or_raw",
           "imrotate", "random_rotate",
           "resize_short", "fixed_crop", "center_crop", "random_crop",
           "color_normalize", "random_size_crop", "Augmenter",
           "SequentialAug", "ResizeAug", "ForceResizeAug", "CastAug",
           "HorizontalFlipAug", "RandomCropAug", "CenterCropAug",
           "ColorNormalizeAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "RandomGrayAug", "HueJitterAug",
           "LightingAug", "RandomOrderAug", "ColorJitterAug",
           "CreateAugmenter"]


def _as_np(img):
    return img.asnumpy() if isinstance(img, NDArray) else onp.asarray(img)


def _png_has_colorspace_chunk(payload: bytes) -> bool:
    """Walk PNG chunks up to the pixel data; True when a colorspace chunk
    (gAMA/iCCP/cHRM) is present — those files must decode through PIL."""
    import struct as _s
    pos = 8
    n = len(payload)
    while pos + 8 <= n:
        (length,) = _s.unpack(">I", payload[pos:pos + 4])
        ctype = payload[pos + 4:pos + 8]
        if ctype in (b"gAMA", b"iCCP", b"cHRM"):
            return True
        if ctype in (b"IDAT", b"IEND"):
            return False
        pos += 12 + length
    return False


def _native_jpeg_decode(payload: bytes, flag: int):
    """GIL-free libjpeg/libpng decode (src/native/image*.cc — the
    OpenCV-thread analog of the reference pipeline). Dispatches on magic
    bytes; None when unavailable or an unsupported format."""
    if payload.startswith(b"\xff\xd8"):
        info_name, dec_name = "MXTImageJPEGInfo", "MXTImageJPEGDecode"
    elif payload.startswith(b"\x89PNG\r\n\x1a\n"):
        if _png_has_colorspace_chunk(payload):
            # libpng's simplified API gamma-converts gAMA/iCCP/cHRM files
            # to sRGB; PIL ignores the tags — route to PIL for parity
            return None
        info_name, dec_name = "MXTImagePNGInfo", "MXTImagePNGDecode"
    else:
        return None
    from .. import _native
    lib = _native.get_lib()
    if lib is None or not hasattr(lib, dec_name):
        return None
    import ctypes
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    if getattr(lib, info_name)(payload, len(payload), ctypes.byref(h),
                               ctypes.byref(w), ctypes.byref(c)) != 0:
        return None
    # decompression-bomb guard (PIL's Image.MAX_IMAGE_PIXELS analog): the
    # header dims are untrusted — don't allocate for absurd claims
    if h.value * w.value > 178_956_970 or h.value <= 0 or w.value <= 0:
        return None  # PIL path applies its own bomb check / error
    out_c = 1 if flag == 0 else 3
    out = onp.empty((h.value, w.value, out_c), onp.uint8)
    rc = getattr(lib, dec_name)(payload, len(payload),
                                out.ctypes.data_as(
                                    ctypes.POINTER(ctypes.c_uint8)),
                                out_c)
    return out if rc == 0 else None


def imdecode(buf, flag: int = 1, to_rgb: bool = True) -> NDArray:
    """Decode an encoded image buffer to HWC uint8 (reference imdecode).
    JPEG rides the native libjpeg path when built; everything else (and
    the fallback) decodes with PIL."""
    payload = bytes(buf)
    arr = _native_jpeg_decode(payload, flag)
    if arr is None:
        try:
            from PIL import Image
        except ImportError as e:
            raise MXNetError(
                "imdecode requires PIL in this environment") from e
        im = Image.open(_io.BytesIO(payload))
        if flag == 0:
            arr = onp.asarray(im.convert("L"))[..., None]
        else:
            arr = onp.asarray(im.convert("RGB"))
    if flag != 0 and not to_rgb:
        arr = arr[..., ::-1]
    return nd_array(arr)


def imdecode_or_raw(payload: bytes, data_shape) -> onp.ndarray:
    """Decode via native libjpeg/PIL, else interpret payload as a raw
    CHW/HWC uint8/float32 array of ``data_shape`` (the framework's
    synthetic-record escape used by tests and im2rec-less pipelines)."""
    native = _native_jpeg_decode(payload, 1)
    if native is not None:
        return native
    try:
        from PIL import Image
        im = Image.open(_io.BytesIO(payload)).convert("RGB")
        return onp.asarray(im)
    except Exception:
        c, h, w = data_shape
        n = c * h * w
        if len(payload) == n:  # uint8 CHW
            return onp.frombuffer(payload, onp.uint8).reshape(
                c, h, w).transpose(1, 2, 0).astype("float32")
        if len(payload) == 4 * n:  # float32 CHW
            return onp.frombuffer(payload, onp.float32).reshape(
                c, h, w).transpose(1, 2, 0)
        raise MXNetError(
            f"cannot decode record payload of {len(payload)} bytes")


def imresize_np(src: onp.ndarray, w: int, h: int,
                interp: int = 1) -> onp.ndarray:
    method = "nearest" if interp == 0 else "linear"
    out = jax.image.resize(jnp.asarray(src, jnp.float32),
                           (h, w, src.shape[2]), method=method)
    return onp.asarray(out)


def imresize(src, w: int, h: int, interp: int = 1) -> NDArray:
    """Resize HWC image (reference imresize; lowers to jax.image.resize)."""
    return nd_array(imresize_np(_as_np(src).astype("float32"), w, h, interp))


def imrotate(src, rotation_degrees, zoom_in: bool = False,
             zoom_out: bool = False) -> NDArray:
    """Rotate CHW image(s) (or NCHW batch) by ``rotation_degrees``
    (reference image/image.py:618 imrotate — grid rotation around the
    image center + bilinear sampling, zero padding outside).

    TPU-native: the rotated sampling grid is built in jnp and sampled
    through the shared bilinear-grid kernel
    (``ndarray.vision_ops._grid_sample``), so the whole rotation is one
    fused, differentiable XLA program — no host round-trip, usable
    inside hybridized pipelines. ``zoom_in`` scales so no padding shows;
    ``zoom_out`` so the whole source stays visible (mutually exclusive).
    Batch inputs accept one angle per image.
    """
    import math

    from ..ops.registry import invoke_raw
    from ..ndarray.vision_ops import _grid_sample

    if zoom_in and zoom_out:
        raise ValueError("`zoom_in` and `zoom_out` cannot be both True")
    if not isinstance(src, NDArray):
        src = nd_array(src)
    if str(src.dtype) != "float32":
        raise TypeError("Only `float32` images are supported by this "
                        f"function, got {src.dtype}")
    expanded = src.ndim == 3
    if expanded:
        if isinstance(rotation_degrees, NDArray) or (
                hasattr(rotation_degrees, "ndim")
                and getattr(rotation_degrees, "ndim", 0) > 0):
            raise TypeError("When a single image is passed the rotation "
                            "angle is required to be a scalar.")
        src = src.reshape((1,) + tuple(src.shape))
    elif src.ndim != 4:
        raise ValueError("Only 3D and 4D are supported by this function")
    n = src.shape[0]
    if not isinstance(rotation_degrees, NDArray):
        deg = onp.asarray(rotation_degrees, dtype="float32").reshape(-1)
        if deg.size == 1:
            deg = onp.repeat(deg, n)
        rotation_degrees = nd_array(deg)
    if rotation_degrees.shape[0] != n:
        raise ValueError("The number of images must be equal to the "
                         "number of rotation angles")

    def fn(data, deg):
        B, C, H, W = data.shape
        rad = (jnp.pi / 180.0) * deg.astype(data.dtype)
        hs, ws = (H - 1) / 2.0, (W - 1) / 2.0
        hm = jnp.broadcast_to(
            (jnp.arange(H, dtype=data.dtype) - hs)[:, None], (H, W))
        wm = jnp.broadcast_to(
            (jnp.arange(W, dtype=data.dtype) - ws)[None, :], (H, W))
        c = jnp.cos(rad)[:, None, None]
        s = jnp.sin(rad)[:, None, None]
        # rotate, THEN normalize (keeps aspect ratio, reference :687)
        wrot = (wm * c - hm * s) / ws                       # (B, H, W)
        hrot = (wm * s + hm * c) / hs
        if zoom_in or zoom_out:
            rho = math.hypot(H, W)
            ang = math.atan2(H, W)                          # arctan(h/w)
            ar = jnp.abs(rad)                               # (B,)
            c1x = jnp.abs(rho * jnp.cos(ang + ar))
            c1y = jnp.abs(rho * jnp.sin(ang + ar))
            c2x = jnp.abs(rho * jnp.cos(ang - ar))
            c2y = jnp.abs(rho * jnp.sin(ang - ar))
            max_x = jnp.maximum(c1x, c2x)
            max_y = jnp.maximum(c1y, c2y)
            if zoom_out:
                scale = jnp.maximum(max_x / W, max_y / H)
            else:
                scale = jnp.minimum(W / max_x, H / max_y)
            scale = scale[:, None, None]
            wrot = wrot * scale
            hrot = hrot * scale
        # denormalize [-1, 1] -> fractional pixel coords
        return _grid_sample(data, (hrot + 1.0) * hs, (wrot + 1.0) * ws)

    out = invoke_raw("imrotate", fn, [src, rotation_degrees])
    return out[0] if expanded else out


def random_rotate(src, angle_limits, zoom_in: bool = False,
                  zoom_out: bool = False) -> NDArray:
    """Rotate by an angle drawn uniformly from ``angle_limits`` — per
    image for batches (reference image/image.py:727)."""
    if getattr(src, "ndim", 3) == 3:
        rotation_degrees = float(onp.random.uniform(*angle_limits))
    else:
        rotation_degrees = nd_array(onp.random.uniform(
            *angle_limits, size=src.shape[0]).astype("float32"))
    return imrotate(src, rotation_degrees, zoom_in=zoom_in,
                    zoom_out=zoom_out)


def resize_short(src, size: int, interp: int = 2) -> NDArray:
    img = _as_np(src)
    h, w = img.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0: int, y0: int, w: int, h: int, size=None,
               interp: int = 2) -> NDArray:
    img = _as_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != tuple(size):
        return imresize(img, size[0], size[1], interp)
    return nd_array(img)


def center_crop(src, size, interp: int = 2):
    img = _as_np(src)
    h, w = img.shape[:2]
    ow, oh = size
    x0 = max(0, (w - ow) // 2)
    y0 = max(0, (h - oh) // 2)
    out = fixed_crop(img, x0, y0, min(ow, w), min(oh, h), size, interp)
    return out, (x0, y0, ow, oh)


def random_crop(src, size, interp: int = 2):
    img = _as_np(src)
    h, w = img.shape[:2]
    ow, oh = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - ow)
    y0 = pyrandom.randint(0, h - oh)
    out = fixed_crop(img, x0, y0, ow, oh, size, interp)
    return out, (x0, y0, ow, oh)


def random_size_crop(src, size, area, ratio, interp: int = 2):
    img = _as_np(src)
    h, w = img.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (onp.log(ratio[0]), onp.log(ratio[1]))
        ar = onp.exp(pyrandom.uniform(*log_ratio))
        ow = int(round(onp.sqrt(target_area * ar)))
        oh = int(round(onp.sqrt(target_area / ar)))
        if ow <= w and oh <= h:
            x0 = pyrandom.randint(0, w - ow)
            y0 = pyrandom.randint(0, h - oh)
            return fixed_crop(img, x0, y0, ow, oh, size, interp), \
                (x0, y0, ow, oh)
    return center_crop(img, size, interp)


def color_normalize(src, mean, std=None) -> NDArray:
    img = _as_np(src).astype("float32") - _as_np(mean)
    if std is not None:
        img = img / _as_np(std)
    return nd_array(img)


# ---------------------------------------------------------------------------
# Augmenters (reference image.py Augmenter hierarchy)
# ---------------------------------------------------------------------------

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src: NDArray) -> NDArray:
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts: List[Augmenter]):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size: int, interp: int = 2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp: int = 2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class CastAug(Augmenter):
    def __init__(self, typ: str = "float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return nd_array(_as_np(src).astype(self.typ))


class HorizontalFlipAug(Augmenter):
    def __init__(self, p: float = 0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd_array(_as_np(src)[:, ::-1].copy())
        return src if isinstance(src, NDArray) else nd_array(src)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp: int = 2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp: int = 2):
        super().__init__(size=size)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        # either side may be None (reference color_normalize subtracts /
        # divides only what is given)
        self.mean = onp.asarray(mean, "float32") if mean is not None \
            else None
        self.std = onp.asarray(std, "float32") if std is not None else None

    def __call__(self, src):
        if self.mean is None:
            img = _as_np(src).astype("float32")
            return nd_array(img / self.std if self.std is not None else img)
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness: float):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd_array(_as_np(src).astype("float32") * alpha)


class ContrastJitterAug(Augmenter):
    _COEF = onp.array([0.299, 0.587, 0.114], "float32")

    def __init__(self, contrast: float):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        img = _as_np(src).astype("float32")
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray_mean = (img * self._COEF).sum(-1).mean()
        return nd_array(img * alpha + gray_mean * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _COEF = ContrastJitterAug._COEF

    def __init__(self, saturation: float):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        img = _as_np(src).astype("float32")
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (img * self._COEF).sum(-1, keepdims=True)
        return nd_array(img * alpha + gray * (1 - alpha))


class RandomGrayAug(Augmenter):
    _COEF = ContrastJitterAug._COEF

    def __init__(self, p: float = 0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        img = _as_np(src).astype("float32")
        if pyrandom.random() < self.p:
            gray = (img * self._COEF).sum(-1, keepdims=True)
            img = onp.broadcast_to(gray, img.shape).copy()
        return nd_array(img)


class HueJitterAug(Augmenter):
    """Random hue rotation (reference HueJitterAug): rotate the chroma
    plane in YIQ space by a random angle in [-hue, hue] (units of pi)."""

    # standard RGB<->YIQ matrices (public constants)
    _TYIQ = onp.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], "float32")
    _ITYIQ = onp.array([[1.0, 0.956, 0.621],
                        [1.0, -0.272, -0.647],
                        [1.0, -1.107, 1.705]], "float32")

    def __init__(self, hue: float):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        img = _as_np(src).astype("float32")
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u, w = onp.cos(alpha * onp.pi), onp.sin(alpha * onp.pi)
        rot = onp.array([[1.0, 0.0, 0.0],
                         [0.0, u, -w],
                         [0.0, w, u]], "float32")
        t = (self._ITYIQ @ rot @ self._TYIQ).T
        return nd_array(img @ t)


class LightingAug(Augmenter):
    """PCA-based RGB noise (reference LightingAug; AlexNet-style): add
    eigvec @ (eigval * N(0, alphastd)) to every pixel."""

    def __init__(self, alphastd: float, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = onp.asarray(eigval, "float32")
        self.eigvec = onp.asarray(eigvec, "float32")

    def __call__(self, src):
        img = _as_np(src).astype("float32")
        alpha = onp.random.normal(0, self.alphastd, size=(3,))
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd_array(img + rgb.astype("float32"))


class RandomOrderAug(Augmenter):
    """Apply a list of augmenters in random order (reference
    RandomOrderAug)."""

    def __init__(self, ts: List[Augmenter]):
        super().__init__()
        self.ts = list(ts)

    def __call__(self, src):
        order = list(range(len(self.ts)))
        pyrandom.shuffle(order)
        for i in order:
            src = self.ts[i](src)
        return src


class ColorJitterAug(RandomOrderAug):
    """Brightness/contrast/saturation jitter in random order (reference
    ColorJitterAug)."""

    def __init__(self, brightness: float, contrast: float,
                 saturation: float):
        ts: List[Augmenter] = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)
        self.brightness, self.contrast, self.saturation = \
            brightness, contrast, saturation


def CreateAugmenter(data_shape, resize: int = 0, rand_crop: bool = False,
                    rand_resize: bool = False, rand_mirror: bool = False,
                    mean=None, std=None, brightness: float = 0,
                    contrast: float = 0, saturation: float = 0,
                    rand_gray: float = 0, inter_method: int = 2
                    ) -> List[Augmenter]:
    """Build the standard augmenter list (reference CreateAugmenter)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomCropAug(crop_size, inter_method))  # simplified
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if rand_gray:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = onp.array([123.68, 116.28, 103.53], "float32")
    if std is True:
        std = onp.array([58.395, 57.12, 57.375], "float32")
    if mean is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist
