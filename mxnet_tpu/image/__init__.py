"""Image processing API (reference: python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from .image import __all__  # noqa: F401
