"""Image processing API (reference: python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .image import __all__ as _image_all
from .detection import __all__ as _det_all

__all__ = list(_image_all) + list(_det_all)
