"""Testing utilities: golden comparison + finite-difference gradient checks.

Reference analog: python/mxnet/test_utils.py (assert_almost_equal,
check_numeric_gradient, check_consistency, rand_ndarray, same). The TPU
rebuild keeps the same numerics methodology (SURVEY §4): golden values vs
NumPy plus central-difference gradient verification against the tape/vjp
backward, and cross-context consistency (cpu vs tpu).
"""
from __future__ import annotations

import numbers
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as onp

from . import autograd
from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array

__all__ = ["assert_almost_equal", "almost_equal", "same", "rand_ndarray",
           "rand_shape_2d", "rand_shape_3d", "rand_shape_nd",
           "check_numeric_gradient", "numeric_grad", "check_symbolic_forward",
           "check_consistency", "default_context", "default_rtol",
           "default_atol", "effective_dtype", "environment", "random_seed",
           # reference tail (round 4)
           "set_default_context", "default_dtype", "default_rtols",
           "default_atols", "default_numeric_eps", "get_tolerance",
           "get_tols", "get_atol", "get_rtol", "get_etol",
           "random_arrays", "random_uniform_arrays", "random_sample",
           "shuffle_csr_column_indices", "rand_sparse_ndarray",
           "create_sparse_array", "create_sparse_array_zd",
           "create_2d_tensor", "create_vector", "rand_coord_2d",
           "assert_allclose", "assert_almost_equal_with_err",
           "assert_almost_equal_ignore_nan", "assert_exception",
           "same_array", "discard_stderr", "DummyIter", "assign_each",
           "assign_each2", "np_reduce", "collapse_sum_like",
           "check_speed", "list_gpus", "is_cd_run", "has_tvm_ops",
           "is_op_runnable", "check_symbolic_backward",
           "same_symbol_structure", "gen_buckets_probs_with_ppf",
           "mean_check", "var_check", "chi_square_check",
           "verify_generator", "compare_ndarray_tuple",
           "compare_optimizer", "compare_optimizer_noise_seeded",
           "check_gluon_hybridize_consistency",
           "new_orthonormal_matrix_2d", "new_matrix_with_real_eigvals_2d",
           "new_matrix_with_real_eigvals_nd",
           "new_sym_matrix_with_real_eigvals_2d",
           "new_sym_matrix_with_real_eigvals_nd", "download", "get_mnist",
           "get_mnist_pkl", "get_mnist_ubyte", "get_cifar10",
           "get_mnist_iterator", "get_zip_data", "get_bz2_data",
           "get_im2rec_path", "checkShapes", "locationError"]

_DEFAULT_RTOL = {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-4,
                 onp.dtype(onp.float64): 1e-5}
_DEFAULT_ATOL = {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-5,
                 onp.dtype(onp.float64): 1e-7}


def default_context() -> Context:
    return current_context()


def default_rtol(dtype) -> float:
    return _DEFAULT_RTOL.get(onp.dtype(dtype), 1e-4)


def default_atol(dtype) -> float:
    return _DEFAULT_ATOL.get(onp.dtype(dtype), 1e-5)


def effective_dtype(x):
    return onp.dtype(getattr(x, "dtype", onp.float32))


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def same(a, b) -> bool:
    return onp.array_equal(_as_numpy(a), _as_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_numpy(a), _as_numpy(b)
    rtol = default_rtol(a.dtype) if rtol is None else rtol
    atol = default_atol(a.dtype) if atol is None else atol
    return onp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Assert |a-b| <= atol + rtol*|b| elementwise, with a max-error report
    (reference test_utils.assert_almost_equal)."""
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    rtol = default_rtol(a_np.dtype) if rtol is None else rtol
    atol = default_atol(a_np.dtype) if atol is None else atol
    if onp.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    a_f, b_f = a_np.astype(onp.float64), b_np.astype(onp.float64)
    err = onp.abs(a_f - b_f)
    tol = atol + rtol * onp.abs(b_f)
    bad = err > tol
    with onp.errstate(divide="ignore", invalid="ignore"):
        rel = onp.where(onp.abs(b_f) > 0, err / onp.abs(b_f), err)
    idx = onp.unravel_index(onp.argmax(onp.where(bad, err, -onp.inf)),
                            err.shape) if bad.any() else None
    raise AssertionError(
        f"{names[0]} and {names[1]} differ beyond rtol={rtol}, atol={atol}: "
        f"max abs err {err.max():.6g}, max rel err {onp.nanmax(rel):.6g}, "
        f"{int(bad.sum())}/{bad.size} elements out of tolerance, "
        f"worst at {idx}: {a_f[idx] if idx else ''} vs "
        f"{b_f[idx] if idx else ''}")


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, scale=1.0):
    data = onp.random.uniform(-scale, scale, size=shape).astype(
        dtype or onp.float32)
    arr = array(data, ctx=ctx)
    if stype != "default":
        return arr.tostype(stype)
    return arr


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim))


def numeric_grad(f: Callable, inputs: List[onp.ndarray], eps: float = 1e-4
                 ) -> List[onp.ndarray]:
    """Central-difference numeric gradient of sum(f(inputs)) w.r.t. each
    input (reference test_utils.numeric_grad)."""
    grads = []
    for i, x in enumerate(inputs):
        g = onp.zeros_like(x, dtype=onp.float64)
        flat = x.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(onp.sum(_as_numpy(f(*inputs))))
            flat[j] = orig - eps
            fm = float(onp.sum(_as_numpy(f(*inputs))))
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
        grads.append(g.astype(x.dtype))
    return grads


def check_numeric_gradient(f: Callable, inputs: Sequence, eps: float = 1e-3,
                           rtol: float = 1e-2, atol: float = 1e-3,
                           grad_nodes: Optional[Sequence[int]] = None):
    """Verify the tape/vjp backward of ``f`` against finite differences.

    ``f`` takes NDArrays and returns one NDArray; gradients of sum(f) are
    compared (reference test_utils.check_numeric_gradient methodology).
    """
    nds = [x if isinstance(x, NDArray) else array(onp.asarray(x))
           for x in inputs]
    which = list(grad_nodes) if grad_nodes is not None else list(
        range(len(nds)))
    for i in which:
        nds[i].attach_grad()
    with autograd.record():
        out = f(*nds)
        s = out.sum()
    s.backward()
    analytic = [nds[i].grad.asnumpy() for i in which]

    raws = [x.asnumpy().astype(onp.float64) for x in nds]

    def fnp(*arrays):
        return f(*[array(a.astype(onp.float32)) for a in arrays])

    numeric = numeric_grad(fnp, raws, eps=eps)
    for i, gi in zip(which, range(len(which))):
        assert_almost_equal(analytic[gi], numeric[i], rtol=rtol, atol=atol,
                            names=(f"analytic_grad[{i}]",
                                   f"numeric_grad[{i}]"))


def check_symbolic_forward(fn, inputs, expected, rtol=1e-4, atol=1e-5):
    """Run fn eagerly and hybridized (jit) and compare both to expected."""
    nds = [x if isinstance(x, NDArray) else array(onp.asarray(x))
           for x in inputs]
    out = fn(*nds)
    assert_almost_equal(out, expected, rtol=rtol, atol=atol,
                        names=("eager", "expected"))


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Cross-context consistency (reference: CPU-vs-GPU check_consistency;
    here cpu vs tpu when hardware is present)."""
    from .context import num_tpus, tpu
    if ctx_list is None:
        ctx_list = [cpu()]
        if num_tpus() > 0:
            ctx_list.append(tpu(0))
    results = []
    for ctx in ctx_list:
        nds = [array(onp.asarray(x), ctx=ctx) for x in inputs]
        results.append(_as_numpy(fn(*nds)))
    for r in results[1:]:
        assert_almost_equal(results[0], r, rtol=rtol, atol=atol,
                            names=("ctx0", "ctxN"))


class environment:
    """Context manager to scope env-var changes (reference
    test_utils.environment)."""

    def __init__(self, *args):
        import os
        self._os = os
        if len(args) == 2 and isinstance(args[0], str):
            self._vars = {args[0]: args[1]}
        else:
            self._vars = dict(args[0])
        self._saved = {}

    def __enter__(self):
        for k, v in self._vars.items():
            self._saved[k] = self._os.environ.get(k)
            if v is None:
                self._os.environ.pop(k, None)
            else:
                self._os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                self._os.environ.pop(k, None)
            else:
                self._os.environ[k] = old


class random_seed:
    """Scope with a fixed framework seed, restoring entropy after
    (reference common.py random_seed)."""

    def __init__(self, seed=None):
        self._seed = seed

    def __enter__(self):
        from .ndarray import random as _r
        import random as pyrandom
        self._next = onp.random.randint(0, 2**31)
        seed = self._seed if self._seed is not None else self._next
        _r.seed(seed)
        pyrandom.seed(seed)
        return self

    def __exit__(self, *exc):
        from .ndarray import random as _r
        _r.seed(self._next)


# ---------------------------------------------------------------------------
# reference test_utils tail (round 4): tolerance helpers, random-data
# builders, assertion variants, statistical generator checks, optimizer
# comparison, misc — same contracts as reference test_utils.py so
# reference-style test suites port unchanged. Data fetchers resolve
# local files first and fall back to deterministic synthetic fixtures
# (no egress in target environments).
# ---------------------------------------------------------------------------

def set_default_context(ctx):
    """Make ``ctx`` the ambient context (reference test_utils.py:96)."""
    Context._default_ctx.value = ctx


def default_dtype():
    return onp.float32


def default_rtols():
    """dtype -> default relative tolerance (reference :109)."""
    return {onp.dtype(t): v for t, v in
            [(onp.float16, 1e-2), (onp.float32, 1e-4),
             (onp.float64, 1e-5), (onp.bool_, 0), (onp.int8, 0),
             (onp.uint8, 0), (onp.int32, 0), (onp.int64, 0)]}


def default_atols():
    return {onp.dtype(t): v for t, v in
            [(onp.float16, 1e-1), (onp.float32, 1e-3),
             (onp.float64, 1e-20), (onp.bool_, 0), (onp.int8, 0),
             (onp.uint8, 0), (onp.int32, 0), (onp.int64, 0)]}


def default_numeric_eps():
    """dtype -> finite-difference step (reference :124)."""
    return {onp.dtype(onp.float16): 1e-1,
            onp.dtype(onp.float32): 1e-3,
            onp.dtype(onp.float64): 1e-4}


def get_tolerance(dat, tol, default_tol):
    if isinstance(tol, numbers.Number):
        return tol
    dtype = onp.dtype(effective_dtype(dat))
    tol = {} if tol is None else tol
    return tol.get(dtype, default_tol[dtype])


def get_tols(x, y, rtol, atol):
    """Tolerances for comparing x and y: the looser of the two operand
    dtypes' defaults unless explicitly given (reference :154)."""
    if isinstance(x, numbers.Number):
        x = onp.array(x)
    if isinstance(y, numbers.Number):
        y = onp.array(y)
    rtol = max(get_tolerance(x, rtol, default_rtols()),
               get_tolerance(y, rtol, default_rtols()))
    atol = max(get_tolerance(x, atol, default_atols()),
               get_tolerance(y, atol, default_atols()))
    return rtol, atol


def get_atol(atol=None, dtype=onp.dtype(onp.float64)):
    return default_atols()[onp.dtype(dtype)] if atol is None else atol


def get_rtol(rtol=None, dtype=onp.dtype(onp.float64)):
    return default_rtols()[onp.dtype(dtype)] if rtol is None else rtol


def get_etol(etol=None):
    return 0 if etol is None else etol


# ---------------- random data builders ----------------

def random_arrays(*shapes):
    """List of numpy float32 arrays (reference :176); a single shape
    returns one array."""
    arrays = [onp.array(onp.random.randn(), dtype=onp.float32)
              if len(s) == 0 else
              onp.random.randn(*s).astype(onp.float32) for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def random_uniform_arrays(*shapes, low=0.0, high=1.0, dtype=onp.float32):
    return [onp.random.uniform(low, high, size=s).astype(dtype)
            for s in shapes]


def random_sample(population, k):
    """Sample k items WITHOUT replacement, preserving order drawn
    (reference :190)."""
    population_copy = population[:]
    onp.random.shuffle(population_copy)
    return population_copy[0:k]


def shuffle_csr_column_indices(csr):
    """Shuffle column indices per row (makes them unsorted) for CSR
    robustness tests (reference :199). Accepts this framework's
    CSRNDArray or any object with numpy-able indptr/indices."""
    indptr = onp.asarray(_as_numpy(csr.indptr), dtype=onp.int64)
    indices = onp.array(_as_numpy(csr.indices))
    for i in range(len(indptr) - 1):
        sub = indices[indptr[i]:indptr[i + 1]]
        onp.random.shuffle(sub)
        indices[indptr[i]:indptr[i + 1]] = sub
    if isinstance(csr.indices, NDArray):
        csr._aux["indices"] = array(indices, dtype=indices.dtype)
    else:
        csr.indices[:] = indices
    return csr


def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        distribution="uniform"):
    """Random sparse NDArray, returning (array, (values-ish, indices))
    like the reference (:214, simplified to the uniform distribution)."""
    density = onp.random.rand() if density is None else density
    dtype = onp.float32 if dtype is None else dtype
    if stype == "row_sparse":
        idx = onp.argwhere(
            onp.random.uniform(size=shape[0]) < density).flatten()
        data = onp.zeros(shape, dtype=dtype)
        data[idx] = onp.random.uniform(-1, 1,
                                       (len(idx),) + tuple(shape[1:]))
        arr = array(data).tostype("row_sparse")
        return arr, (arr.data, arr.indices)
    if stype == "csr":
        mask = onp.random.uniform(size=shape) < density
        data = (onp.random.uniform(-1, 1, shape) * mask).astype(dtype)
        arr = array(data).tostype("csr")
        return arr, (arr.data, arr.indices, arr.indptr)
    raise MXNetError(f"unknown sparse type {stype}")


def create_sparse_array(shape, stype, data_init=None, rsp_indices=None,
                        dtype=None, modifier_func=None, density=0.5,
                        shuffle_csr_indices=False):
    """Deterministically-seeded sparse array builder (reference :260)."""
    if stype == "row_sparse":
        if rsp_indices is not None:
            data = onp.zeros(shape, dtype=dtype or onp.float32)
            v = data_init if data_init is not None else 1.0
            for i in rsp_indices:
                data[i] = v
            return array(data).tostype("row_sparse")
        arr, _ = rand_sparse_ndarray(shape, stype, density=density,
                                     dtype=dtype)
        return arr
    if stype == "csr":
        arr, _ = rand_sparse_ndarray(shape, stype, density=density,
                                     dtype=dtype)
        return arr
    raise MXNetError(f"unknown sparse type {stype}")


def create_sparse_array_zd(shape, stype, density, data_init=None,
                           rsp_indices=None, dtype=None,
                           modifier_func=None, shuffle_csr_indices=False):
    """Sparse array that may have zero-size storage (reference :300)."""
    if stype == "row_sparse" and density == 0:
        return array(onp.zeros(shape, dtype or onp.float32)) \
            .tostype("row_sparse")
    return create_sparse_array(shape, stype, data_init=data_init,
                               rsp_indices=rsp_indices, dtype=dtype,
                               density=density)


def create_2d_tensor(rows, columns, dtype=onp.int64):
    return onp.arange(rows * columns, dtype=dtype).reshape(rows, columns)


def create_vector(size, dtype=onp.int64):
    return onp.arange(size, dtype=dtype)


def rand_coord_2d(x_low, x_high, y_low, y_high):
    x = onp.random.randint(x_low, x_high, dtype=onp.int64)
    y = onp.random.randint(y_low, y_high, dtype=onp.int64)
    return x, y


# ---------------- assertion variants ----------------

def _location_error(a, b, index, names):
    return (f"Location of maximum error: {index}, "
            f"{names[0]}={a.flat[index] if hasattr(a, 'flat') else a}, "
            f"{names[1]}={b.flat[index] if hasattr(b, 'flat') else b}")


locationError = _location_error  # reference camelCase name


def checkShapes(a, b):
    if a.shape != b.shape:
        raise AssertionError(f"shape mismatch: {a.shape} vs {b.shape}")


def assert_allclose(a, b, rtol=1e-07, atol=0, equal_nan=True):
    """numpy assert_allclose over mx/onp inputs (reference re-export)."""
    onp.testing.assert_allclose(_as_numpy(a), _as_numpy(b), rtol=rtol,
                                atol=atol, equal_nan=equal_nan)


def assert_almost_equal_with_err(a, b, rtol=None, atol=None, etol=None,
                                 names=("a", "b"), equal_nan=False):
    """Like assert_almost_equal but tolerating a FRACTION ``etol`` of
    mismatched elements (reference :638)."""
    etol = get_etol(etol)
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    rtol, atol = get_tols(a_np, b_np, rtol, atol)
    if etol > 0:
        bad = ~onp.isclose(a_np, b_np, rtol=rtol, atol=atol,
                           equal_nan=equal_nan)
        rate = bad.sum() / float(onp.size(bad))
        if rate > etol:
            raise AssertionError(
                f"error fraction {rate} > etol {etol} comparing "
                f"{names[0]} and {names[1]}")
    else:
        assert_almost_equal(a_np, b_np, rtol=rtol, atol=atol,
                            names=names, equal_nan=equal_nan)


def assert_almost_equal_ignore_nan(a, b, rtol=None, atol=None,
                                   names=("a", "b")):
    """Compare after masking positions where EITHER side is NaN
    (reference :668)."""
    a_np = onp.copy(_as_numpy(a))
    b_np = onp.copy(_as_numpy(b))
    nan_mask = onp.logical_or(onp.isnan(a_np), onp.isnan(b_np))
    a_np[nan_mask] = 0
    b_np[nan_mask] = 0
    assert_almost_equal(a_np, b_np, rtol=rtol, atol=atol, names=names)


def assert_exception(f, exception_type, *args, **kwargs):
    """Assert f(*args, **kwargs) raises exception_type (reference :684)."""
    try:
        f(*args, **kwargs)
    except exception_type:
        return
    raise AssertionError(f"did not raise {exception_type.__name__}")


def same_array(array1, array2):
    """True when two NDArrays share underlying storage, verified by a
    write-probe (reference :87 same_array). Functional XLA buffers never
    alias two handles, so this reports True only for the same handle."""
    if array1 is array2:
        return True
    array1[:] = array1.asnumpy() + 1
    equal = almost_equal(array1.asnumpy(), array2.asnumpy())
    array1[:] = array1.asnumpy() - 1
    return equal


class discard_stderr:
    """Context manager silencing stderr (reference :700) — some checks
    intentionally trigger noisy warnings."""

    def __enter__(self):
        import sys
        self._old = sys.stderr
        import io as _io
        sys.stderr = _io.StringIO()
        return self

    def __exit__(self, *exc):
        import sys
        sys.stderr = self._old


class DummyIter:
    """Infinitely repeat one batch of a real iterator (benchmarking
    helper, reference :2430)."""

    def __init__(self, real_iter):
        self.real_iter = real_iter
        self.provide_data = getattr(real_iter, "provide_data", None)
        self.provide_label = getattr(real_iter, "provide_label", None)
        self.batch_size = getattr(real_iter, "batch_size", None)
        self.the_batch = next(iter(real_iter))

    def __iter__(self):
        return self

    def next(self):
        return self.the_batch

    __next__ = next

    def reset(self):
        pass


def assign_each(the_input, function):
    """Apply ``function`` elementwise via numpy (reference :2450)."""
    return onp.vectorize(function)(_as_numpy(the_input)) \
        if function is not None else _as_numpy(the_input).copy()


def assign_each2(input1, input2, function):
    return onp.vectorize(function)(_as_numpy(input1), _as_numpy(input2)) \
        if function is not None else _as_numpy(input1).copy()


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reference :380 — reduction wrapper handling axis list + keepdims."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else \
            range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def collapse_sum_like(a, shape):
    """Sum ``a`` down to ``shape`` per broadcasting rules
    (reference :2490)."""
    assert len(a.shape) >= len(shape)
    a_np = _as_numpy(a)
    for i in range(len(a.shape) - len(shape)):
        a_np = a_np.sum(axis=0)
    for i, s in enumerate(shape):
        if s == 1 and a_np.shape[i] != 1:
            a_np = a_np.sum(axis=i, keepdims=True)
    return a_np


def check_speed(f, *args, n=20, warmup=3, **kwargs):
    """Median seconds/call of f (simplified reference :2410: the
    reference times symbol executors; here any callable)."""
    import time
    out = None
    for _ in range(warmup):
        out = f(*args, **kwargs)
    if isinstance(out, NDArray):
        out.asnumpy()
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = f(*args, **kwargs)
        if isinstance(out, NDArray):
            out.asnumpy()
        times.append(time.perf_counter() - t0)
    return float(onp.median(times))


def list_gpus():
    """Indices of visible GPUs — empty on TPU builds (reference
    :2360 shells out to nvidia-smi)."""
    from .context import num_gpus
    return list(range(num_gpus()))


def is_cd_run():
    import os
    return os.environ.get("CD_JOB", 0) == "1"


def has_tvm_ops():
    """TVM-generated kernels never exist here; Pallas is the custom-
    kernel path (rtc.py)."""
    return False


def is_op_runnable():
    return True


# ---------------- symbolic checks ----------------

def check_symbolic_backward(fn, inputs, out_grads, expected, rtol=1e-4,
                            atol=1e-5):
    """Drive backward through the tape and compare input grads to
    ``expected`` (reference :1260, tape-based here)."""
    arrs = [array(x) if not isinstance(x, NDArray) else x
            for x in inputs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs)
    out.backward(array(out_grads[0]) if not isinstance(
        out_grads[0], NDArray) else out_grads[0])
    for a, e in zip(arrs, expected):
        assert_almost_equal(a.grad.asnumpy(), _as_numpy(e), rtol=rtol,
                            atol=atol)


def same_symbol_structure(sym1, sym2):
    """True when two Symbols are the same graph shape: same ops in the
    same topological order (reference :2510)."""
    n1 = sym1.get_internals()
    n2 = sym2.get_internals()
    if len(n1) != len(n2):
        return False
    for a, b in zip(n1, n2):
        if a._op != b._op:
            return False
    return True


# ---------------- statistical generator checks ----------------

def gen_buckets_probs_with_ppf(ppf, nbuckets):
    """Equal-probability buckets from a quantile function
    (reference :2003)."""
    assert nbuckets > 0
    probs = [1.0 / nbuckets for _ in range(nbuckets)]
    buckets = [(ppf(i / float(nbuckets)), ppf((i + 1) / float(nbuckets)))
               for i in range(nbuckets)]
    return buckets, probs


def mean_check(generator, mu, sigma, nsamples=1000000):
    """Sample mean within mu ± 3 sigma/sqrt(n) (reference :2027)."""
    samples = onp.array(generator(nsamples))
    sample_mean = samples.mean()
    ret = (sample_mean > mu - 3 * sigma / onp.sqrt(nsamples)) and \
          (sample_mean < mu + 3 * sigma / onp.sqrt(nsamples))
    return ret


def var_check(generator, sigma, nsamples=1000000):
    """Sample variance within 3 std errors (reference :2096)."""
    samples = onp.array(generator(nsamples))
    sample_var = samples.var(ddof=1)
    ret = (sample_var > sigma ** 2 - 3 *
           onp.sqrt(2 * sigma ** 4 / (nsamples - 1))) and \
          (sample_var < sigma ** 2 + 3 *
           onp.sqrt(2 * sigma ** 4 / (nsamples - 1)))
    return ret


def chi_square_check(generator, buckets, probs, nsamples=1000000):
    """Chi-square goodness-of-fit of generator(n) against bucket
    probabilities; returns (p, obs_freq, expected_freq)
    (reference :2135)."""
    import scipy.stats as ss
    if not isinstance(buckets, list):
        buckets = list(buckets)
    samples = onp.array(generator(nsamples)).reshape(-1)
    expected_freq = (nsamples * onp.array(probs)).astype(onp.int64)
    if isinstance(buckets[0], (list, tuple)):
        sorted_bucket_boundaries = sorted(
            {b for bucket in buckets for b in bucket})
        obs = onp.histogram(samples,
                            bins=onp.array(sorted_bucket_boundaries))[0]
        obs_freq = []
        for lo, hi in buckets:
            i = sorted_bucket_boundaries.index(lo)
            j = sorted_bucket_boundaries.index(hi)
            obs_freq.append(int(obs[i:j].sum()))
        obs_freq = onp.array(obs_freq, dtype=onp.int64)
    else:
        obs_freq = onp.array([int((samples == b).sum()) for b in buckets],
                             dtype=onp.int64)
    _, p = ss.chisquare(f_obs=obs_freq, f_exp=expected_freq)
    return p, obs_freq, expected_freq


def verify_generator(generator, buckets, probs, nsamples=1000000,
                     nrepeat=5, success_rate=0.2, alpha=0.05):
    """Repeat chi-square tests; fail if the pass rate is below
    ``success_rate`` (reference :2213)."""
    cs_ret_l = []
    for _ in range(nrepeat):
        cs_ret, _obs, _exp = chi_square_check(
            generator=generator, buckets=buckets, probs=probs,
            nsamples=nsamples)
        cs_ret_l.append(cs_ret)
    success_num = (onp.array(cs_ret_l) > alpha).sum()
    if success_num < nrepeat * success_rate:
        raise AssertionError(
            f"Generator test fails, Chi-square p={cs_ret_l}, "
            f"buckets={buckets}, probs={probs}")
    return cs_ret_l


# ---------------- optimizer comparison ----------------

def compare_ndarray_tuple(t1, t2, rtol=None, atol=None):
    """Recursively compare nested tuples of NDArrays (reference :2262)."""
    if t1 is None or t2 is None:
        return
    if isinstance(t1, tuple):
        for s1, s2 in zip(t1, t2):
            compare_ndarray_tuple(s1, s2, rtol, atol)
    else:
        assert_almost_equal(t1.asnumpy(), t2.asnumpy(), rtol=rtol,
                            atol=atol)


def compare_optimizer(opt1, opt2, shapes, dtype, w_stype="default",
                      g_stype="default", rtol=1e-4, atol=1e-5,
                      compare_states=True):
    """Run one update of each optimizer on identical weights/grads and
    compare resulting weights (and states) — reference :2274."""
    for i, shape in enumerate(shapes):
        w_np = onp.random.uniform(size=shape).astype(dtype)
        g_np = onp.random.uniform(size=shape).astype(dtype)
        w1, w2 = array(w_np.copy()), array(w_np.copy())
        g1, g2 = array(g_np.copy()), array(g_np.copy())
        if w_stype != "default":
            w1, w2 = w1.tostype(w_stype), w2.tostype(w_stype)
        if g_stype != "default":
            g1, g2 = g1.tostype(g_stype), g2.tostype(g_stype)
        s1 = opt1.create_state_multi_precision(i, w1)
        s2 = opt2.create_state_multi_precision(i, w2)
        if compare_states:
            compare_ndarray_tuple(s1, s2, rtol=rtol, atol=atol)
        opt1.update_multi_precision(i, w1, g1, s1)
        opt2.update_multi_precision(i, w2, g2, s2)
        if compare_states:
            compare_ndarray_tuple(s1, s2, rtol=rtol, atol=atol)
        assert_almost_equal(w1.asnumpy(), w2.asnumpy(), rtol=rtol,
                            atol=atol)


def compare_optimizer_noise_seeded(opt1, opt2, shapes, dtype, noise_seed,
                                   rtol=1e-4, atol=1e-5,
                                   compare_states=True):
    """compare_optimizer with the framework RNG re-seeded before each
    optimizer's update so stochastic optimizers see identical noise
    (reference :2320)."""
    from .ndarray import random as nd_random
    for i, shape in enumerate(shapes):
        w_np = onp.random.uniform(size=shape).astype(dtype)
        g_np = onp.random.uniform(size=shape).astype(dtype)
        w1, w2 = array(w_np.copy()), array(w_np.copy())
        g1, g2 = array(g_np.copy()), array(g_np.copy())
        s1 = opt1.create_state_multi_precision(i, w1)
        s2 = opt2.create_state_multi_precision(i, w2)
        if compare_states:
            compare_ndarray_tuple(s1, s2, rtol=rtol, atol=atol)
        nd_random.seed(noise_seed)
        opt1.update_multi_precision(i, w1, g1, s1)
        nd_random.seed(noise_seed)
        opt2.update_multi_precision(i, w2, g2, s2)
        if compare_states:
            compare_ndarray_tuple(s1, s2, rtol=rtol, atol=atol)
        assert_almost_equal(w1.asnumpy(), w2.asnumpy(), rtol=rtol,
                            atol=atol)


def check_gluon_hybridize_consistency(net_builder, data_l,
                                      numpy_func=None, test_grad=True,
                                      rtol=1e-4, atol=1e-4):
    """Eager vs hybridized forward/backward equivalence of a block
    (reference :2530): same seed -> same outputs and same input grads."""
    saved_out_np = None
    saved_grad_np_l = None
    for hybridize in (False, True):
        from .ndarray import random as nd_random
        nd_random.seed(0)
        net = net_builder()
        net.initialize()
        if hybridize:
            net.hybridize()
        ins = [x.copy() for x in data_l]
        for x in ins:
            x.attach_grad()
        with autograd.record():
            out = net(*ins)
        if test_grad:
            out.backward()
        out_np = out.asnumpy()
        if saved_out_np is None:
            saved_out_np = out_np
            if test_grad:
                saved_grad_np_l = [x.grad.asnumpy() for x in ins]
        else:
            assert_almost_equal(out_np, saved_out_np, rtol=rtol,
                                atol=atol)
            if test_grad:
                for x, saved in zip(ins, saved_grad_np_l):
                    assert_almost_equal(x.grad.asnumpy(), saved,
                                        rtol=rtol, atol=atol)
        if numpy_func is not None:
            assert_almost_equal(
                out_np, numpy_func(*[x.asnumpy() for x in data_l]),
                rtol=rtol, atol=atol)


# ---------------- linalg matrix generators ----------------

def new_orthonormal_matrix_2d(num_rows, num_cols):
    """Random semi-orthonormal matrix (reference :2560)."""
    q, _ = onp.linalg.qr(onp.random.uniform(
        -1, 1, (max(num_rows, num_cols), min(num_rows, num_cols))))
    return q.T if num_rows < num_cols else q


def new_matrix_with_real_eigvals_2d(n):
    """Random n x n matrix with real eigenvalues (reference :2545)."""
    shape = (n, n)
    q = new_orthonormal_matrix_2d(*shape)
    d = onp.diag(onp.random.uniform(-1.0, 1.0, n))
    return q.dot(d).dot(q.T)


def new_matrix_with_real_eigvals_nd(shape):
    """Batch of matrices with real eigenvalues for the trailing 2 dims
    (reference :2575)."""
    n = shape[-1]
    batch = int(onp.prod(shape[:-2])) if len(shape) > 2 else 1
    out = onp.stack([new_matrix_with_real_eigvals_2d(n)
                     for _ in range(batch)])
    return out.reshape(shape)


def new_sym_matrix_with_real_eigvals_2d(n):
    a = onp.random.uniform(-1.0, 1.0, (n, n))
    return (a + a.T) / 2


def new_sym_matrix_with_real_eigvals_nd(shape):
    n = shape[-1]
    batch = int(onp.prod(shape[:-2])) if len(shape) > 2 else 1
    out = onp.stack([new_sym_matrix_with_real_eigvals_2d(n)
                     for _ in range(batch)])
    return out.reshape(shape)


# ---------------- data fetchers (local-first, no egress) ----------------

def download(url, fname=None, dirname=None, overwrite=False,
             retries=5):
    """Download ``url`` (reference :1510). Target environments have no
    egress, so failures raise with that context after retrying."""
    import os
    import urllib.request
    fname = fname or url.split("/")[-1]
    if dirname:
        os.makedirs(dirname, exist_ok=True)
        fname = os.path.join(dirname, fname)
    if os.path.exists(fname) and not overwrite:
        return fname
    last = None
    tmp = fname + ".part"
    for _ in range(max(retries, 1)):
        try:
            # write to a temp name and rename on success, so a failed
            # transfer never leaves a truncated file that a retry would
            # mistake for a finished download
            urllib.request.urlretrieve(url, tmp)
            os.replace(tmp, fname)
            return fname
        except Exception as e:  # pragma: no cover - network-dependent
            last = e
            if os.path.exists(tmp):
                os.remove(tmp)
    raise MXNetError(
        f"download of {url} failed after {retries} attempts ({last}); "
        "note this environment may have no network egress — place the "
        "file at the target path manually")


def _synthetic_mnist(seed=42):
    """Deterministic MNIST-shaped fixture: 10 blob classes."""
    rng = onp.random.RandomState(seed)
    n_train, n_test = 600, 100
    def make(n):
        y = rng.randint(0, 10, n).astype(onp.int64)
        x = rng.rand(n, 1, 28, 28).astype(onp.float32) * 0.1
        for i, lbl in enumerate(y):
            x[i, 0, 2 + lbl * 2 : 4 + lbl * 2, 4:24] += 0.8
        return onp.clip(x, 0, 1), y
    xtr, ytr = make(n_train)
    xte, yte = make(n_test)
    return {"train_data": xtr, "train_label": ytr,
            "test_data": xte, "test_label": yte}


def get_mnist(path="data"):
    """MNIST as numpy arrays (reference :1560). Loads the raw IDX files
    from ``path`` when present; otherwise returns a deterministic
    synthetic fixture with the same keys/shapes/dtypes (no egress)."""
    import gzip
    import os
    import struct

    def read_data(label_url, image_url):
        with gzip.open(label_url) as flbl:
            struct.unpack(">II", flbl.read(8))
            label = onp.frombuffer(flbl.read(), dtype=onp.int8) \
                .astype(onp.int64)
        with gzip.open(image_url, "rb") as fimg:
            _, num, rows, cols = struct.unpack(">IIII", fimg.read(16))
            image = onp.frombuffer(fimg.read(), dtype=onp.uint8) \
                .reshape(len(label), rows, cols)
            image = image.reshape(image.shape[0], 1, 28, 28) \
                .astype(onp.float32) / 255
        return label, image

    files = ["train-labels-idx1-ubyte.gz", "train-images-idx3-ubyte.gz",
             "t10k-labels-idx1-ubyte.gz", "t10k-images-idx3-ubyte.gz"]
    paths = [os.path.join(path, f) for f in files]
    if all(os.path.exists(p) for p in paths):
        train_lbl, train_img = read_data(paths[0], paths[1])
        test_lbl, test_img = read_data(paths[2], paths[3])
        return {"train_data": train_img, "train_label": train_lbl,
                "test_data": test_img, "test_label": test_lbl}
    return _synthetic_mnist()


def get_mnist_pkl(path="data"):
    """mnist.pkl.gz loader (reference :1600): local file or the
    synthetic fixture reshaped to the pkl layout."""
    import gzip
    import os
    import pickle
    p = os.path.join(path, "mnist.pkl.gz")
    if os.path.exists(p):
        with gzip.open(p, "rb") as f:
            return pickle.load(f, encoding="latin1")
    m = _synthetic_mnist()
    tr = (m["train_data"].reshape(len(m["train_label"]), -1),
          m["train_label"])
    te = (m["test_data"].reshape(len(m["test_label"]), -1),
          m["test_label"])
    return tr, te, te


def get_mnist_ubyte(path="data"):
    """Ensure raw-ubyte MNIST files exist under ``path``; writes them
    from get_mnist()'s arrays when absent (reference :1620 downloads)."""
    import os
    os.makedirs(path, exist_ok=True)
    names = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]
    if all(os.path.exists(os.path.join(path, n)) for n in names):
        return
    import struct
    m = get_mnist()
    for img_name, lbl_name, x, y in [
            (names[0], names[1], m["train_data"], m["train_label"]),
            (names[2], names[3], m["test_data"], m["test_label"])]:
        with open(os.path.join(path, img_name), "wb") as f:
            f.write(struct.pack(">IIII", 2051, len(y), 28, 28))
            f.write((x.reshape(len(y), 28, 28) * 255)
                    .astype(onp.uint8).tobytes())
        with open(os.path.join(path, lbl_name), "wb") as f:
            f.write(struct.pack(">II", 2049, len(y)))
            f.write(y.astype(onp.uint8).tobytes())


def get_cifar10(path="data"):
    """CIFAR-10 recordio files must be provided locally; raises with
    instructions when absent (reference :1650 downloads the archive)."""
    import os
    if os.path.exists(os.path.join(path, "cifar", "train.rec")):
        return
    raise MXNetError(
        f"CIFAR-10 not found under {path}/cifar; this environment "
        "cannot download — place train.rec/test.rec there (im2rec.py "
        "can build them from the raw archive)")


def get_mnist_iterator(batch_size, input_shape, num_parts=1, part_index=0,
                       path="data"):
    """(train_iter, val_iter) of NDArrayIter over get_mnist()
    (reference :1680 uses MNISTIter over the ubyte files)."""
    from .io import NDArrayIter
    m = get_mnist(path=path)
    flat = len(input_shape) == 1

    def shape_of(x):
        return x.reshape(len(x), -1) if flat else x
    xtr, ytr = shape_of(m["train_data"]), m["train_label"]
    if num_parts > 1:  # disjoint contiguous shard per worker
        if not 0 <= part_index < num_parts:
            raise MXNetError(f"part_index {part_index} out of range for "
                             f"num_parts {num_parts}")
        n = len(ytr)
        lo = n * part_index // num_parts
        hi = n * (part_index + 1) // num_parts
        xtr, ytr = xtr[lo:hi], ytr[lo:hi]
    train = NDArrayIter(xtr, ytr, batch_size, shuffle=True)
    val = NDArrayIter(shape_of(m["test_data"]), m["test_label"],
                      batch_size)
    return train, val


def get_zip_data(data_dir, url, data_origin_name):
    """Extract a local zip archive (reference :1700 downloads first)."""
    import os
    import zipfile
    p = os.path.join(data_dir, data_origin_name)
    if not os.path.exists(p):
        p = download(url, fname=data_origin_name, dirname=data_dir)
    with zipfile.ZipFile(p) as zf:
        zf.extractall(data_dir)


def get_bz2_data(data_dir, data_name, url, data_origin_name):
    """Decompress a local bz2 file (reference :1720)."""
    import bz2
    import os
    import shutil
    out = os.path.join(data_dir, data_name)
    if os.path.exists(out):
        return
    p = os.path.join(data_dir, data_origin_name)
    if not os.path.exists(p):
        p = download(url, fname=data_origin_name, dirname=data_dir)
    with bz2.BZ2File(p) as fin, open(out, "wb") as fout:
        shutil.copyfileobj(fin, fout)


def get_im2rec_path(home_env="MXNET_HOME"):
    """Path to the im2rec tool (reference :2390 looks for the compiled
    binary; here it is tools/im2rec.py)."""
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    p = os.path.join(here, "tools", "im2rec.py")
    if os.path.isfile(p):
        return p
    raise MXNetError("tools/im2rec.py not found")
