"""Testing utilities: golden comparison + finite-difference gradient checks.

Reference analog: python/mxnet/test_utils.py (assert_almost_equal,
check_numeric_gradient, check_consistency, rand_ndarray, same). The TPU
rebuild keeps the same numerics methodology (SURVEY §4): golden values vs
NumPy plus central-difference gradient verification against the tape/vjp
backward, and cross-context consistency (cpu vs tpu).
"""
from __future__ import annotations

import numbers
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as onp

from . import autograd
from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, array

__all__ = ["assert_almost_equal", "almost_equal", "same", "rand_ndarray",
           "rand_shape_2d", "rand_shape_3d", "rand_shape_nd",
           "check_numeric_gradient", "numeric_grad", "check_symbolic_forward",
           "check_consistency", "default_context", "default_rtol",
           "default_atol", "effective_dtype", "environment", "random_seed"]

_DEFAULT_RTOL = {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-4,
                 onp.dtype(onp.float64): 1e-5}
_DEFAULT_ATOL = {onp.dtype(onp.float16): 1e-2, onp.dtype(onp.float32): 1e-5,
                 onp.dtype(onp.float64): 1e-7}


def default_context() -> Context:
    return current_context()


def default_rtol(dtype) -> float:
    return _DEFAULT_RTOL.get(onp.dtype(dtype), 1e-4)


def default_atol(dtype) -> float:
    return _DEFAULT_ATOL.get(onp.dtype(dtype), 1e-5)


def effective_dtype(x):
    return onp.dtype(getattr(x, "dtype", onp.float32))


def _as_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return onp.asarray(x)


def same(a, b) -> bool:
    return onp.array_equal(_as_numpy(a), _as_numpy(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False) -> bool:
    a, b = _as_numpy(a), _as_numpy(b)
    rtol = default_rtol(a.dtype) if rtol is None else rtol
    atol = default_atol(a.dtype) if atol is None else atol
    return onp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Assert |a-b| <= atol + rtol*|b| elementwise, with a max-error report
    (reference test_utils.assert_almost_equal)."""
    a_np, b_np = _as_numpy(a), _as_numpy(b)
    rtol = default_rtol(a_np.dtype) if rtol is None else rtol
    atol = default_atol(a_np.dtype) if atol is None else atol
    if onp.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        return
    a_f, b_f = a_np.astype(onp.float64), b_np.astype(onp.float64)
    err = onp.abs(a_f - b_f)
    tol = atol + rtol * onp.abs(b_f)
    bad = err > tol
    with onp.errstate(divide="ignore", invalid="ignore"):
        rel = onp.where(onp.abs(b_f) > 0, err / onp.abs(b_f), err)
    idx = onp.unravel_index(onp.argmax(onp.where(bad, err, -onp.inf)),
                            err.shape) if bad.any() else None
    raise AssertionError(
        f"{names[0]} and {names[1]} differ beyond rtol={rtol}, atol={atol}: "
        f"max abs err {err.max():.6g}, max rel err {onp.nanmax(rel):.6g}, "
        f"{int(bad.sum())}/{bad.size} elements out of tolerance, "
        f"worst at {idx}: {a_f[idx] if idx else ''} vs "
        f"{b_f[idx] if idx else ''}")


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None, scale=1.0):
    data = onp.random.uniform(-scale, scale, size=shape).astype(
        dtype or onp.float32)
    arr = array(data, ctx=ctx)
    if stype != "default":
        return arr.tostype(stype)
    return arr


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_shape_nd(ndim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=ndim))


def numeric_grad(f: Callable, inputs: List[onp.ndarray], eps: float = 1e-4
                 ) -> List[onp.ndarray]:
    """Central-difference numeric gradient of sum(f(inputs)) w.r.t. each
    input (reference test_utils.numeric_grad)."""
    grads = []
    for i, x in enumerate(inputs):
        g = onp.zeros_like(x, dtype=onp.float64)
        flat = x.reshape(-1)
        gflat = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(onp.sum(_as_numpy(f(*inputs))))
            flat[j] = orig - eps
            fm = float(onp.sum(_as_numpy(f(*inputs))))
            flat[j] = orig
            gflat[j] = (fp - fm) / (2 * eps)
        grads.append(g.astype(x.dtype))
    return grads


def check_numeric_gradient(f: Callable, inputs: Sequence, eps: float = 1e-3,
                           rtol: float = 1e-2, atol: float = 1e-3,
                           grad_nodes: Optional[Sequence[int]] = None):
    """Verify the tape/vjp backward of ``f`` against finite differences.

    ``f`` takes NDArrays and returns one NDArray; gradients of sum(f) are
    compared (reference test_utils.check_numeric_gradient methodology).
    """
    nds = [x if isinstance(x, NDArray) else array(onp.asarray(x))
           for x in inputs]
    which = list(grad_nodes) if grad_nodes is not None else list(
        range(len(nds)))
    for i in which:
        nds[i].attach_grad()
    with autograd.record():
        out = f(*nds)
        s = out.sum()
    s.backward()
    analytic = [nds[i].grad.asnumpy() for i in which]

    raws = [x.asnumpy().astype(onp.float64) for x in nds]

    def fnp(*arrays):
        return f(*[array(a.astype(onp.float32)) for a in arrays])

    numeric = numeric_grad(fnp, raws, eps=eps)
    for i, gi in zip(which, range(len(which))):
        assert_almost_equal(analytic[gi], numeric[i], rtol=rtol, atol=atol,
                            names=(f"analytic_grad[{i}]",
                                   f"numeric_grad[{i}]"))


def check_symbolic_forward(fn, inputs, expected, rtol=1e-4, atol=1e-5):
    """Run fn eagerly and hybridized (jit) and compare both to expected."""
    nds = [x if isinstance(x, NDArray) else array(onp.asarray(x))
           for x in inputs]
    out = fn(*nds)
    assert_almost_equal(out, expected, rtol=rtol, atol=atol,
                        names=("eager", "expected"))


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5):
    """Cross-context consistency (reference: CPU-vs-GPU check_consistency;
    here cpu vs tpu when hardware is present)."""
    from .context import num_tpus, tpu
    if ctx_list is None:
        ctx_list = [cpu()]
        if num_tpus() > 0:
            ctx_list.append(tpu(0))
    results = []
    for ctx in ctx_list:
        nds = [array(onp.asarray(x), ctx=ctx) for x in inputs]
        results.append(_as_numpy(fn(*nds)))
    for r in results[1:]:
        assert_almost_equal(results[0], r, rtol=rtol, atol=atol,
                            names=("ctx0", "ctxN"))


class environment:
    """Context manager to scope env-var changes (reference
    test_utils.environment)."""

    def __init__(self, *args):
        import os
        self._os = os
        if len(args) == 2 and isinstance(args[0], str):
            self._vars = {args[0]: args[1]}
        else:
            self._vars = dict(args[0])
        self._saved = {}

    def __enter__(self):
        for k, v in self._vars.items():
            self._saved[k] = self._os.environ.get(k)
            if v is None:
                self._os.environ.pop(k, None)
            else:
                self._os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                self._os.environ.pop(k, None)
            else:
                self._os.environ[k] = old


class random_seed:
    """Scope with a fixed framework seed, restoring entropy after
    (reference common.py random_seed)."""

    def __init__(self, seed=None):
        self._seed = seed

    def __enter__(self):
        from .ndarray import random as _r
        import random as pyrandom
        self._next = onp.random.randint(0, 2**31)
        seed = self._seed if self._seed is not None else self._next
        _r.seed(seed)
        pyrandom.seed(seed)
        return self

    def __exit__(self, *exc):
        from .ndarray import random as _r
        _r.seed(self._next)
