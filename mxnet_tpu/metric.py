"""Evaluation metrics (reference: python/mxnet/gluon/metric.py — 32 classes).

``update()`` accepts NDArrays or numpy arrays. Accumulation is
**sync-free on device inputs**: when a batch is device-resident
(NDArray backed by a jax.Array), the per-batch statistic is computed ON
the device and added into a device-resident running sum — ``update()``
dispatches async work and returns without any device→host transfer, so
per-batch metric updates inside a pipelined train/eval loop no longer
stall the accelerator (the reference's engine would likewise keep these
as async ops until an explicit wait). The ONE host sync happens at
``get()``, which reads the accumulated scalars. Device sums accumulate
in float32 (x64 is off under jit); pure-host inputs (numpy/lists) keep
the reference's float64 host accumulation exactly.

Metrics whose update is inherently host-side keep the sync:
``PCC`` (its confusion matrix grows from the batch's max class index — a
data-dependent host decision), ``PearsonCorrelation`` (stores raw
vectors), ``CustomMetric`` (user feval takes numpy), and ``Perplexity``
with ``ignore_label`` set (the valid-token count is data-dependent).
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as onp

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "MAE", "MSE", "RMSE", "CrossEntropy", "NegativeLogLikelihood",
           "Perplexity", "F1", "MCC", "PearsonCorrelation", "Loss",
           "Torch", "Caffe", "CustomMetric", "np", "create", "PCC",
           "Fbeta", "BinaryAccuracy", "MeanPairwiseDistance",
           "MeanCosineSimilarity"]

_registry = {}


def _register(*names):
    def deco(cls):
        for n in names:
            _registry[n.lower()] = cls
        return cls
    return deco


def _to_numpy(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return onp.asarray(x)


def _device_array(x):
    """The backing jax.Array when ``x`` is a concrete device-resident
    NDArray/jax array (not a tracer), else None."""
    d = getattr(x, "_data", x)
    if isinstance(d, jax.Array) and not isinstance(d, jax.core.Tracer):
        return d
    return None


def _device_pair(label, pred):
    """(label, pred) as jax arrays when at least one side is
    device-resident — the signal to accumulate on device with no host
    sync. Pure-host pairs return None (keep float64 host accumulation)."""
    la, pa = _device_array(label), _device_array(pred)
    if la is None and pa is None:
        return None
    if la is None:
        la = jnp.asarray(getattr(label, "_data", label))
    if pa is None:
        pa = jnp.asarray(getattr(pred, "_data", pred))
    return la, pa


def _host(v) -> float:
    """Read an accumulated scalar — THE designed sync point (get())."""
    return float(v)


def check_label_shapes(labels, preds, shape=False):
    if len(labels) != len(preds):
        raise MXNetError(
            f"labels/preds count mismatch: {len(labels)} vs {len(preds)}")


class EvalMetric:
    """Base metric (reference metric.py EvalMetric).

    ``sum_metric`` holds either a host float (numpy inputs) or a
    device-resident scalar (NDArray inputs — accumulated async, no per-
    batch sync); ``num_inst`` is always a host int derived from shapes.
    ``get()`` is the one sync point."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, _host(self.sum_metric) / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        self.update(list(label.values()), list(pred.values()))

    def __repr__(self):
        return f"EvalMetric: {dict([self.get()])}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric) if isinstance(metric, str)
                            else metric)

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@_register("accuracy", "acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        labels, preds = _as_list(labels), _as_list(preds)
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                if pd.ndim > ld.ndim:
                    pd = jnp.argmax(pd, axis=self.axis)
                eq = pd.astype(jnp.int32).reshape(-1) \
                    == ld.astype(jnp.int32).reshape(-1)
                self.sum_metric = self.sum_metric + \
                    jnp.sum(eq, dtype=jnp.float32)
                self.num_inst += int(onp.prod(ld.shape)) if ld.shape else 1
                continue
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            if pred.ndim > label.ndim:
                pred = onp.argmax(pred, axis=self.axis)
            pred = pred.astype("int64").flatten()
            label = label.astype("int64").flatten()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@_register("top_k_accuracy", "topkaccuracy")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                ld = ld.astype(jnp.int32).reshape(-1)
                topk = jnp.argsort(-pd, axis=-1)[:, :self.top_k]
                hit = jnp.any(topk == ld[:, None], axis=1)
                self.sum_metric = self.sum_metric + \
                    jnp.sum(hit, dtype=jnp.float32)
                self.num_inst += int(ld.shape[0])
                continue
            label = _to_numpy(label).astype("int64").flatten()
            pred = _to_numpy(pred)
            topk = onp.argsort(-pred, axis=-1)[:, :self.top_k]
            self.sum_metric += float((topk == label[:, None]).any(axis=1).sum())
            self.num_inst += len(label)


@_register("mae")
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                self.sum_metric = self.sum_metric + jnp.mean(
                    jnp.abs(ld.reshape(pd.shape) - pd)).astype(jnp.float32)
                self.num_inst += 1
                continue
            label, pred = _to_numpy(label), _to_numpy(pred)
            self.sum_metric += float(onp.abs(label.reshape(pred.shape)
                                             - pred).mean())
            self.num_inst += 1


@_register("mse")
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                self.sum_metric = self.sum_metric + jnp.mean(
                    (ld.reshape(pd.shape) - pd) ** 2).astype(jnp.float32)
                self.num_inst += 1
                continue
            label, pred = _to_numpy(label), _to_numpy(pred)
            self.sum_metric += float(((label.reshape(pred.shape)
                                       - pred) ** 2).mean())
            self.num_inst += 1


@_register("rmse")
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.sqrt(_host(self.sum_metric) / self.num_inst)


@_register("ce", "crossentropy", "cross-entropy")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                ld = ld.astype(jnp.int32).reshape(-1)
                prob = pd[jnp.arange(ld.shape[0]), ld]
                self.sum_metric = self.sum_metric + \
                    jnp.sum(-jnp.log(prob + self.eps)).astype(jnp.float32)
                self.num_inst += int(ld.shape[0])
                continue
            label = _to_numpy(label).astype("int64").flatten()
            pred = _to_numpy(pred)
            prob = pred[onp.arange(label.shape[0]), label]
            self.sum_metric += float((-onp.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@_register("nll_loss")
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps=eps, name=name, **kwargs)


@_register("perplexity")
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 **kwargs):
        super().__init__(name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred) \
                if self.ignore_label is None else None
            if dev is not None:
                # ignore_label needs a data-dependent valid-token count
                # (host decision) — only the unmasked case stays on device
                ld, pd = dev
                ld = ld.astype(jnp.int32).reshape(-1)
                pd = pd.reshape(ld.shape[0], -1)
                prob = pd[jnp.arange(ld.shape[0]), ld]
                self.sum_metric = self.sum_metric + jnp.sum(
                    -jnp.log(jnp.maximum(prob, 1e-10))).astype(jnp.float32)
                self.num_inst += int(ld.shape[0])
                continue
            label = _to_numpy(label).astype("int64").reshape(-1)
            pred = _to_numpy(pred).reshape(label.shape[0], -1)
            prob = pred[onp.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                ignore = label == self.ignore_label
                prob = prob[~ignore]
            self.sum_metric += float(-onp.log(onp.maximum(prob, 1e-10)).sum())
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(_host(self.sum_metric) / self.num_inst)


@_register("f1")
class F1(EvalMetric):
    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, **kwargs)
        self.average = average
        self.reset_stats()

    def reset_stats(self):
        self._tp = self._fp = self._fn = 0.0

    def reset(self):
        super().reset()
        self.reset_stats()

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                if pd.ndim > 1:
                    pd = jnp.argmax(pd, axis=-1)
                pd = pd.astype(jnp.int32).reshape(-1)
                ld = ld.astype(jnp.int32).reshape(-1)
                f32 = jnp.float32
                self._tp = self._tp + jnp.sum((pd == 1) & (ld == 1),
                                              dtype=f32)
                self._fp = self._fp + jnp.sum((pd == 1) & (ld == 0),
                                              dtype=f32)
                self._fn = self._fn + jnp.sum((pd == 0) & (ld == 1),
                                              dtype=f32)
                self.num_inst += int(ld.shape[0])
                continue
            label = _to_numpy(label).astype("int64").flatten()
            pred = _to_numpy(pred)
            if pred.ndim > 1:
                pred = onp.argmax(pred, axis=-1)
            pred = pred.astype("int64").flatten()
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self.num_inst += len(label)

    beta = 1.0  # F-beta with beta=1 is F1; Fbeta overrides

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        tp, fp, fn = _host(self._tp), _host(self._fp), _host(self._fn)
        prec = tp / max(tp + fp, 1e-12)
        rec = tp / max(tp + fn, 1e-12)
        b2 = self.beta * self.beta
        f1 = (1 + b2) * prec * rec / max(b2 * prec + rec, 1e-12)
        return self.name, f1


@_register("mcc")
class MCC(EvalMetric):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)
        self._tp = self._fp = self._fn = self._tn = 0.0

    def reset(self):
        super().reset()
        self._tp = self._fp = self._fn = self._tn = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                if pd.ndim > 1:
                    pd = jnp.argmax(pd, axis=-1)
                pd = pd.astype(jnp.int32).reshape(-1)
                ld = ld.astype(jnp.int32).reshape(-1)
                f32 = jnp.float32
                self._tp = self._tp + jnp.sum((pd == 1) & (ld == 1),
                                              dtype=f32)
                self._fp = self._fp + jnp.sum((pd == 1) & (ld == 0),
                                              dtype=f32)
                self._fn = self._fn + jnp.sum((pd == 0) & (ld == 1),
                                              dtype=f32)
                self._tn = self._tn + jnp.sum((pd == 0) & (ld == 0),
                                              dtype=f32)
                self.num_inst += int(ld.shape[0])
                continue
            label = _to_numpy(label).astype("int64").flatten()
            pred = _to_numpy(pred)
            if pred.ndim > 1:
                pred = onp.argmax(pred, axis=-1)
            pred = pred.astype("int64").flatten()
            self._tp += float(((pred == 1) & (label == 1)).sum())
            self._fp += float(((pred == 1) & (label == 0)).sum())
            self._fn += float(((pred == 0) & (label == 1)).sum())
            self._tn += float(((pred == 0) & (label == 0)).sum())
            self.num_inst += len(label)

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        tp, fp = _host(self._tp), _host(self._fp)
        fn, tn = _host(self._fn), _host(self._tn)
        den = math.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
        return self.name, (tp * tn - fp * fn) / den if den else 0.0


@_register("pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)
        self._labels, self._preds = [], []

    def reset(self):
        super().reset()
        self._labels, self._preds = [], []

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_to_numpy(label).flatten())
            self._preds.append(_to_numpy(pred).flatten())
            self.num_inst += 1

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        l = onp.concatenate(self._labels)
        p = onp.concatenate(self._preds)
        return self.name, float(onp.corrcoef(l, p)[0, 1])


@_register("pcc")
class PCC(EvalMetric):
    """Multiclass Matthews correlation from a K x K confusion matrix
    (reference metric.PCC, gluon/metric.py:1586): a discrete solution to
    the Pearson correlation, reducing to MCC for K=2. The matrix grows as
    new class indices appear."""

    def __init__(self, name="pcc", **kwargs):
        self.k = 2
        super().__init__(name, **kwargs)

    def reset(self):
        self.num_inst = 0
        self.lcm = onp.zeros((self.k, self.k), dtype="float64")

    def _grow(self, inc):
        self.lcm = onp.pad(self.lcm, ((0, inc), (0, inc)), "constant")
        self.k += inc

    def _calc_mcc(self, cmat):
        n = cmat.sum()
        x = cmat.sum(axis=1)
        y = cmat.sum(axis=0)
        cov_xx = float((x * (n - x)).sum())
        cov_yy = float((y * (n - y)).sum())
        if cov_xx == 0 or cov_yy == 0:
            return float("nan")
        i = cmat.diagonal()
        cov_xy = float((i * n - x * y).sum())
        return cov_xy / (cov_xx * cov_yy) ** 0.5

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).astype("int64").flatten()
            pred = _to_numpy(pred)
            if pred.ndim > 1 and pred.shape != tuple(label.shape):
                pred = onp.argmax(pred, axis=1)
            pred = pred.astype("int64").flatten()
            n = int(max(pred.max(), label.max()))
            if n >= self.k:
                self._grow(n + 1 - self.k)
            bcm = onp.zeros((self.k, self.k), dtype="float64")
            onp.add.at(bcm, (pred, label), 1)
            self.lcm += bcm
        self.num_inst += 1

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self._calc_mcc(self.lcm)


@_register("loss")
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            pd = _device_array(pred)
            if pd is not None:
                self.sum_metric = self.sum_metric + \
                    jnp.sum(pd).astype(jnp.float32)
                self.num_inst += int(onp.prod(pd.shape)) if pd.shape else 1
                continue
            loss = _to_numpy(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


class Torch(Loss):
    def __init__(self, name="torch", **kwargs):
        super().__init__(name, **kwargs)


class Caffe(Loss):
    def __init__(self, name="caffe", **kwargs):
        super().__init__(name, **kwargs)


@_register("custom")
class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            v = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(v, tuple):
                s, n = v
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += v
                self.num_inst += 1


@_register("fbeta")
class Fbeta(F1):
    """F-beta score (reference metric.Fbeta): shares F1's counting; beta
    weighs recall (beta=1 reduces to F1)."""

    def __init__(self, name="fbeta", beta=1.0, average="macro", **kwargs):
        super().__init__(name=name, average=average, **kwargs)
        self.beta = beta


@_register("binary_accuracy")
class BinaryAccuracy(EvalMetric):
    """Accuracy of probabilities vs binary labels at a threshold
    (reference metric.BinaryAccuracy)."""

    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        super().__init__(name, **kwargs)
        self.threshold = threshold

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                hit = (pd.reshape(-1) > self.threshold) \
                    == (ld.reshape(-1) > 0.5)
                self.sum_metric = self.sum_metric + \
                    jnp.sum(hit, dtype=jnp.float32)
                self.num_inst += int(onp.prod(ld.shape)) if ld.shape else 1
                continue
            label = _to_numpy(label).flatten()
            pred = (_to_numpy(pred).flatten() > self.threshold)
            self.sum_metric += float((pred == (label > 0.5)).sum())
            self.num_inst += len(label)


@_register("mean_pairwise_distance", "mpd")
class MeanPairwiseDistance(EvalMetric):
    """Mean p-norm distance between label and pred rows (reference
    metric.MeanPairwiseDistance)."""

    def __init__(self, name="mpd", p=2, **kwargs):
        super().__init__(name, **kwargs)
        self.p = p

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                d = (jnp.abs(pd - ld) ** self.p).sum(
                    axis=tuple(range(1, ld.ndim))) ** (1.0 / self.p)
                self.sum_metric = self.sum_metric + \
                    jnp.sum(d).astype(jnp.float32)
                self.num_inst += int(ld.shape[0])
                continue
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            d = (onp.abs(pred - label) ** self.p).sum(
                axis=tuple(range(1, label.ndim))) ** (1.0 / self.p)
            self.sum_metric += float(d.sum())
            self.num_inst += d.shape[0]


@_register("mean_cosine_similarity", "cos_sim")
class MeanCosineSimilarity(EvalMetric):
    """Mean cosine similarity along the last axis (reference
    metric.MeanCosineSimilarity)."""

    def __init__(self, name="cos_sim", eps=1e-12, **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            dev = _device_pair(label, pred)
            if dev is not None:
                ld, pd = dev
                num = (ld * pd).sum(-1)
                den = jnp.linalg.norm(ld, axis=-1) * \
                    jnp.linalg.norm(pd, axis=-1)
                sim = num / jnp.maximum(den, self.eps)
                self.sum_metric = self.sum_metric + \
                    jnp.sum(sim).astype(jnp.float32)
                self.num_inst += int(onp.prod(sim.shape)) if sim.shape \
                    else 1
                continue
            label = _to_numpy(label)
            pred = _to_numpy(pred)
            num = (label * pred).sum(-1)
            den = onp.linalg.norm(label, axis=-1) * \
                onp.linalg.norm(pred, axis=-1)
            sim = num / onp.maximum(den, self.eps)
            self.sum_metric += float(sim.sum())
            self.num_inst += sim.size


def np(numpy_feval, name="custom", allow_extra_outputs=False):
    """Wrap a numpy feval into a metric (reference metric.np)."""
    return CustomMetric(numpy_feval, name, allow_extra_outputs)


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, list):
        return CompositeEvalMetric([create(m) for m in metric])
    try:
        return _registry[metric.lower()](*args, **kwargs)
    except KeyError as e:
        raise MXNetError(f"unknown metric {metric!r}") from e
