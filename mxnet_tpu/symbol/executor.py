"""Executor: evaluate a Symbol graph.

Reference analog: python/mxnet/executor.py (:25 — thin CachedOp wrapper with
args/grads). Here forward evaluates the DAG through the ``mx.nd`` namespace
(each op an XLA kernel; wrap in jit for one fused computation) and backward
rides the autograd tape.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .. import autograd
from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["Executor", "eval_symbol"]


def _nd_namespace():
    from .. import ndarray as nd
    return nd


def _eval_node(sym, feeds: Dict[str, NDArray], cache: Dict[int, NDArray]):
    if id(sym) in cache:
        return cache[id(sym)]
    if sym._op is None:
        try:
            val = feeds[sym._name]
        except KeyError as e:
            raise MXNetError(f"missing value for variable {sym._name!r}") from e
        cache[id(sym)] = val
        return val
    if sym._op == "_stablehlo":
        arrays = [feeds[n]._data for n in sym.list_arguments()]
        out = sym._call(*arrays)
        val = NDArray(out[0] if isinstance(out, (list, tuple)) else out)
        cache[id(sym)] = val
        return val
    ins = [_eval_node(i, feeds, cache) for i in sym._inputs]
    nd = _nd_namespace()
    # None-valued attrs are "unset"; shape/dtype are real op attrs here
    # (reshape/Cast) — Variable nodes never reach this branch
    attrs = {k: v for k, v in sym._attrs.items() if v is not None}
    opname = sym._op
    # sibling outputs of one multi-output node (ONNX Split import) share a
    # _group_key: the op evaluates ONCE per forward, outputs index into it
    gk = getattr(sym, "_group_key", None)
    if gk is not None and gk in cache:
        val = cache[gk][sym._out_index]
        cache[id(sym)] = val
        return val
    if opname.endswith("_scalar"):
        base = opname[:-len("_scalar")]
        scalar = attrs.pop("scalar")
        fn = getattr(nd, _op_alias(base))
        val = fn(ins[0], scalar, **attrs)
    else:
        fn = getattr(nd, _op_alias(opname), None)
        if fn is None:
            raise MXNetError(f"symbol op {opname!r} has no nd implementation")
        val = fn(*ins, **attrs)
    if isinstance(val, (list, tuple)):
        if gk is not None:
            cache[gk] = val
        val = val[sym._out_index]
    cache[id(sym)] = val
    return val


_ALIASES = {"add": "add", "sub": "subtract", "mul": "multiply",
            "div": "divide", "pow": "power"}


def _op_alias(name: str) -> str:
    return _ALIASES.get(name, name)


def eval_symbol(sym, feeds: Dict[str, NDArray]):
    return _eval_node(sym, feeds, {})


class Executor:
    """Holds arg arrays (+grads) for repeated forward/backward
    (reference executor.py Executor)."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write"):
        self._symbol = symbol
        self._ctx = ctx
        arg_names = symbol.list_arguments()
        if isinstance(args, (list, tuple)):
            self.arg_dict = dict(zip(arg_names, args))
        else:
            self.arg_dict = dict(args or {})
        self.grad_dict: Dict[str, NDArray] = {}
        if grad_req != "null":
            for name, arr in self.arg_dict.items():
                if args_grad is not None and name not in args_grad:
                    continue
                arr.attach_grad(grad_req if isinstance(grad_req, str)
                                else grad_req.get(name, "write"))
                self.grad_dict[name] = arr.grad
        self.outputs: List[NDArray] = []

    def forward(self, is_train: bool = False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._data = v._data if isinstance(v, NDArray) \
                    else v
        if is_train:
            with autograd.record():
                out = eval_symbol(self._symbol, self.arg_dict)
        else:
            out = eval_symbol(self._symbol, self.arg_dict)
        self.outputs = [out] if isinstance(out, NDArray) else list(out)
        return self.outputs

    def backward(self, out_grads=None):
        if not self.outputs:
            raise MXNetError("run forward(is_train=True) before backward")
        heads = self.outputs
        hg = out_grads if out_grads is None or isinstance(out_grads, list) \
            else [out_grads]
        autograd.backward(heads, hg)
        # refresh grad_dict views
        for name, arr in self.arg_dict.items():
            if arr.grad is not None:
                self.grad_dict[name] = arr.grad
        return self.grad_dict
