"""``mx.sym`` namespace (reference: python/mxnet/symbol/).

Op calls like ``mx.sym.exp(x)`` / ``mx.sym.FullyConnected(...)`` build graph
nodes lazily; any ``mx.nd`` function is available symbolically (PEP 562
module __getattr__), replacing the reference's codegen from the C++ registry.
"""
from .symbol import (Symbol, Variable, var, load, load_json,
                     trace_block_to_symbol, StableHLOSymbol)
from .executor import Executor, eval_symbol
from . import symbol as _symbol_mod


def _make_sym_op(opname):
    def op(*args, name=None, attr=None, **kwargs):
        from .. import attribute as _attribute
        from .. import name as _name
        sym_inputs = [a for a in args if isinstance(a, Symbol)]
        attrs = {k: v for k, v in kwargs.items()
                 if not isinstance(v, Symbol)}
        sym_inputs += [v for v in kwargs.values() if isinstance(v, Symbol)]
        s = Symbol(opname,
                   _name.current().get(name, opname.lower()),
                   sym_inputs, attrs)
        s._user_attrs = _attribute.current().get(attr)
        return s
    op.__name__ = opname
    return op


def __getattr__(name):
    from .. import ndarray as nd
    if hasattr(nd, name) and callable(getattr(nd, name)):
        return _make_sym_op(name)
    raise AttributeError(f"module 'mxnet_tpu.symbol' has no attribute {name!r}")
