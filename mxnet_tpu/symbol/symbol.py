"""Symbol: lazy graph construction (``mx.sym``) + serialized-model export.

Reference analog: python/mxnet/symbol/ (graph building over the nnvm op
registry, saved as symbol.json) and the deferred-compute tracing behind
Gluon 2.0 export (SURVEY layer 5/6). TPU-native split:

- The *graph API* (`Variable`, op calls, `bind`) is a light Python DAG whose
  nodes name ops in the ``mx.nd`` namespace; an Executor evaluates it
  imperatively or jits the whole evaluation. Saved as portable JSON.
- The *export path* for trained models serializes the block's forward as
  StableHLO via ``jax.export`` — the XLA-native interchange format (the
  analog of the reference's symbol.json+params pair, but compiler-level and
  version-stable).
"""
from __future__ import annotations

import base64
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["Symbol", "Variable", "var", "load", "load_json",
           "trace_block_to_symbol", "StableHLOSymbol"]


class Symbol:
    """A node in the op DAG. ``op`` is the name of an ``mx.nd`` function;
    leaf nodes are variables (op=None)."""

    def __init__(self, op: Optional[str], name: str,
                 inputs: Sequence["Symbol"] = (), attrs: Optional[Dict] = None,
                 out_index: int = 0):
        self._op = op
        self._name = name
        self._inputs = list(inputs)
        self._attrs = dict(attrs or {})
        self._user_attrs: Dict[str, str] = {}  # AttrScope/attr= strings
        self._out_index = out_index

    # ---------------- introspection ----------------
    @property
    def name(self) -> str:
        return self._name

    def attr(self, key: str):
        """The string attribute ``key`` attached to this node by
        ``AttrScope`` / ``attr=`` (reference symbol.py attr()); None
        when unset."""
        return self._user_attrs.get(key)

    def attr_dict(self) -> Dict[str, Dict[str, str]]:
        """{node name: its string attrs} over the whole graph
        (reference symbol.py attr_dict())."""
        out: Dict[str, Dict[str, str]] = {}
        for node in self.get_internals():
            if getattr(node, "_user_attrs", None):
                out[node._name] = dict(node._user_attrs)
        return out

    def list_arguments(self) -> List[str]:
        seen, order = set(), []
        visited = set()

        def walk(s):
            if id(s) in visited:  # memoize: shared inputs are common
                return
            visited.add(id(s))
            if s._op is None:
                if s._name not in seen:
                    seen.add(s._name)
                    order.append(s._name)
            for i in s._inputs:
                walk(i)
        walk(self)
        return order

    def list_outputs(self) -> List[str]:
        return [f"{self._name}_output"]

    def get_internals(self) -> List["Symbol"]:
        nodes = []
        visited = set()

        def walk(s):
            if id(s) in visited:  # memoize: a diamond graph would
                return            # otherwise traverse exponentially
            visited.add(id(s))
            for i in s._inputs:
                walk(i)
            nodes.append(s)
        walk(self)
        return nodes

    # ---------------- composition ----------------
    def __call__(self, *args, **kwargs):
        raise MXNetError("composing symbols via call is not supported; "
                         "use operator functions")

    def _binary(self, other, opname):
        from .. import attribute as _attribute
        from .. import name as _name
        nm = _name.current().get(None, f"_{opname}")
        if isinstance(other, (int, float)):
            s = Symbol(opname + "_scalar", nm, [self], {"scalar": other})
        else:
            s = Symbol(opname, nm, [self, other])
        s._user_attrs = _attribute.current().get(None)
        return s

    def __add__(self, o):
        return self._binary(o, "add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "sub")

    def __mul__(self, o):
        return self._binary(o, "mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "div")

    def __pow__(self, o):
        return self._binary(o, "pow")

    def __neg__(self):
        from .. import attribute as _attribute
        from .. import name as _name
        s = Symbol("negative", _name.current().get(None, "_negative"),
                   [self])
        s._user_attrs = _attribute.current().get(None)
        return s

    # ---------------- evaluation ----------------
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None):
        from .executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req)

    def _simple_bind(self, ctx=None, grad_req="write", **shapes):
        from .executor import Executor
        from ..ndarray import zeros
        args = {name: zeros(shapes[name]) for name in self.list_arguments()
                if name in shapes}
        return Executor(self, ctx, args, None, grad_req)

    simple_bind = _simple_bind

    def eval(self, ctx=None, **kwargs):
        from .executor import eval_symbol
        return eval_symbol(self, kwargs)

    def infer_shape(self, **shapes):
        """Infer output shape by abstract evaluation (XLA's shape inference
        replaces the reference's FInferShape pass)."""
        import jax
        from .executor import _eval_node
        from ..ndarray import zeros
        feeds = {n: zeros(shapes[n]) for n in self.list_arguments()}

        def f(**kw):
            return _eval_node(self, {k: v for k, v in kw.items()}, {})._data
        out = jax.eval_shape(lambda: f(**feeds))
        arg_shapes = [shapes[n] for n in self.list_arguments()]
        return arg_shapes, [tuple(out.shape)], []

    # ---------------- serialization ----------------
    def tojson(self) -> str:
        nodes = []
        node_ids: Dict[int, int] = {}

        def visit(s: "Symbol") -> int:
            if id(s) in node_ids:
                return node_ids[id(s)]
            in_ids = [visit(i) for i in s._inputs]
            nid = len(nodes)
            node = {"op": s._op or "null", "name": s._name,
                    "attrs": _jsonable(s._attrs), "inputs": in_ids}
            if getattr(s, "_user_attrs", None):
                node["attr"] = dict(s._user_attrs)
            nodes.append(node)
            node_ids[id(s)] = nid
            return nid
        head = visit(self)
        return json.dumps({"format": "mxnet_tpu-symbol-v1",
                           "nodes": nodes, "head": head}, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    @staticmethod
    def load(fname: str) -> "Symbol":
        return load(fname)

    def __repr__(self):
        return f"<Symbol {self._name} op={self._op}>"


def _jsonable(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (list, tuple)):
            out[k] = list(v)
        else:
            out[k] = v
    return out


def Variable(name: str, shape=None, dtype=None, attr=None,
             **kwargs) -> Symbol:
    from .. import attribute as _attribute
    s = Symbol(None, name)
    s._attrs.update({"shape": shape, "dtype": dtype})
    s._user_attrs = _attribute.current().get(attr)
    return s


var = Variable


def load_json(json_str: str) -> Symbol:
    spec = json.loads(json_str)
    if spec.get("format") == "mxnet_tpu-stablehlo-v1":
        return StableHLOSymbol._from_spec(spec)
    if spec.get("format") != "mxnet_tpu-symbol-v1":
        raise MXNetError("unrecognized symbol file format")
    built: List[Symbol] = []
    for node in spec["nodes"]:
        if node["op"] == "null":
            s = Variable(node["name"])
            s._attrs.update(node.get("attrs", {}))
        else:
            s = Symbol(node["op"], node["name"],
                       [built[i] for i in node["inputs"]],
                       node.get("attrs", {}))
        s._user_attrs = dict(node.get("attr", {}))
        built.append(s)
    return built[spec["head"]]


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


class StableHLOSymbol(Symbol):
    """A trained-model graph serialized as StableHLO (jax.export) — the
    TPU-native analog of the reference's exported symbol.json. Holds the
    serialized artifact + input/param metadata; executable on any device via
    XLA without the defining Python code."""

    def __init__(self, serialized: bytes, input_names: List[str],
                 param_names: List[str], name: str = "stablehlo"):
        super().__init__("_stablehlo", name)
        self._serialized = serialized
        self._input_names = list(input_names)
        self._param_names = list(param_names)
        self._exported = None

    def list_arguments(self) -> List[str]:
        return self._input_names + self._param_names

    def _call(self, *arrays):
        from jax import export as jax_export
        if self._exported is None:
            self._exported = jax_export.deserialize(self._serialized)
        return self._exported.call(*arrays)

    def tojson(self) -> str:
        return json.dumps({
            "format": "mxnet_tpu-stablehlo-v1",
            "inputs": self._input_names,
            "params": self._param_names,
            "artifact_b64": base64.b64encode(self._serialized).decode(),
        })

    @staticmethod
    def _from_spec(spec) -> "StableHLOSymbol":
        return StableHLOSymbol(base64.b64decode(spec["artifact_b64"]),
                               spec["inputs"], spec["params"])


def trace_block_to_symbol(block) -> StableHLOSymbol:
    """Trace a HybridBlock's inference forward to StableHLO
    (reference HybridBlock.export's deferred-compute trace, block.py:1296).
    Requires the block to have run at least once (shapes known)."""
    import jax
    from jax import export as jax_export

    params = [(k, p) for k, p in block.collect_params().items()
              if p._data is not None]
    if not params and not getattr(block, "_cached_out_info", None):
        raise MXNetError("run the block once before export (shapes unknown)")
    in_avals = getattr(block, "_last_input_avals", None)
    if in_avals is None:
        raise MXNetError("run the block once before export (no traced input)")

    names = [k for k, _ in params]
    plist = [p for _, p in params]

    def fn(*arrays):
        n_in = len(in_avals)
        inputs, pvals = arrays[:n_in], arrays[n_in:]
        orig = [p._data for p in plist]
        from .. import _tape
        prev = _tape.set_recording(False)
        prev_t = _tape.set_training(False)
        try:
            for p, v in zip(plist, pvals):
                p._data = NDArray(v)
            out = block.forward(*[NDArray(a) for a in inputs])
        finally:
            for p, o in zip(plist, orig):
                p._data = o
            _tape.set_recording(prev)
            _tape.set_training(prev_t)
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data

    import jax.numpy as jnp
    args = tuple(jnp.zeros(a.shape, a.dtype) for a in in_avals) + \
        tuple(p._data._data for p in plist)
    exported = jax_export.export(jax.jit(fn))(*args)
    data = exported.serialize()
    return StableHLOSymbol(bytes(data),
                           [f"data{i}" for i in range(len(in_avals))], names)
