"""Runtime feature introspection (reference: python/mxnet/runtime.py backed
by src/libinfo.cc — ``mx.runtime.feature_list()`` / ``Features``).

Features here describe the TPU build: which backends/subsystems are live in
this process (XLA platform, Pallas, the native C++ host runtime, …).
"""
from __future__ import annotations

import collections
import logging
import os

__all__ = ["Feature", "Features", "feature_list", "setup_compile_cache",
           "compile_cache_stats"]

_LOG = logging.getLogger("mxnet_tpu.runtime")

# persistent-compilation-cache hit/miss census (setup_compile_cache)
_CACHE_STATS = {"enabled": False, "dir": None, "hits": 0, "misses": 0}


def setup_compile_cache() -> bool:
    """Arm JAX's persistent compilation cache behind
    ``MXNET_COMPILE_CACHE=<dir>`` (docs/ENV_VARS.md).

    Every compiled program — bench warmups, ``Trainer.compile_step``
    shape buckets, ``hybridize()`` traces — is keyed and written to the
    directory, so a RESTART (or the next bench leg with the same shapes)
    loads the executable from disk instead of paying the full 10–12s
    XLA recompile. Hits and misses are counted (via jax.monitoring's
    ``/jax/compilation_cache/*`` events) and logged at compile time;
    read the totals with :func:`compile_cache_stats`.

    Returns True when the cache was armed. Called once from
    ``mxnet_tpu/__init__`` — safe to call again (idempotent).
    """
    cache_dir = os.environ.get("MXNET_COMPILE_CACHE")
    if not cache_dir:
        return False
    if _CACHE_STATS["enabled"]:
        return True
    import jax
    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache EVERYTHING: the default floors (1s compile time / 4KB entry)
    # would skip exactly the many small programs eager-op dispatch and
    # tiny tests pay for repeatedly
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(knob, val)
        except Exception:       # pragma: no cover - knob renamed upstream
            pass
    try:
        from jax._src import monitoring as _mon
        from .telemetry import names as _tnames
        from .telemetry.registry import default as _treg
        _hits = _treg().counter(_tnames.COMPILE_CACHE_HITS)
        _misses = _treg().counter(_tnames.COMPILE_CACHE_MISSES)

        def _on_event(event: str, **kwargs):
            if event == "/jax/compilation_cache/cache_hits":
                _CACHE_STATS["hits"] += 1
                _hits.inc()
                _LOG.info("compile cache HIT (%d so far) [%s]",
                          _CACHE_STATS["hits"], cache_dir)
            elif event == "/jax/compilation_cache/cache_misses":
                _CACHE_STATS["misses"] += 1
                _misses.inc()
                _LOG.info("compile cache MISS (%d so far) — compiling, "
                          "will persist to %s",
                          _CACHE_STATS["misses"], cache_dir)

        _mon.register_event_listener(_on_event)
    except Exception:           # pragma: no cover - private API moved
        _LOG.warning("MXNET_COMPILE_CACHE: hit/miss telemetry "
                     "unavailable (jax.monitoring API changed); the "
                     "cache itself is still armed")
    _CACHE_STATS["enabled"] = True
    _CACHE_STATS["dir"] = cache_dir
    _LOG.info("persistent compilation cache armed at %s "
              "(MXNET_COMPILE_CACHE)", cache_dir)
    return True


def compile_cache_stats() -> dict:
    """{'enabled', 'dir', 'hits', 'misses'} for the persistent
    compilation cache (tools/diagnose.py prints this)."""
    return dict(_CACHE_STATS)


def _cache_collector(reg):
    """Pull-model refresh for the compile-cache gauge at export time
    (telemetry registers this; hits/misses increment live)."""
    from .telemetry import names as _tnames
    reg.gauge(_tnames.COMPILE_CACHE_ENABLED).set(
        1.0 if _CACHE_STATS["enabled"] else 0.0)


try:
    from .telemetry.registry import default as _telemetry_registry
    _telemetry_registry().register_collector(_cache_collector)
except Exception:       # pragma: no cover - telemetry must not block
    pass

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    import jax
    from . import _native
    backend = jax.default_backend()
    feats = {
        "TPU": backend == "tpu",
        "CUDA": False,            # by design: this build targets XLA/TPU
        "CUDNN": False,
        "NCCL": False,            # collectives ride XLA/ICI instead
        "XLA": True,
        "PALLAS": True,
        "BLAS_OPEN": True,        # XLA's CPU backend carries its own BLAS
        "MKLDNN": False,
        "OPENCV": False,
        "NATIVE_ENGINE": _native.available(),
        "RECORDIO": True,
        "DIST_KVSTORE": True,     # jax.distributed-backed
        "F16C": True,             # bf16/fp16 casts via XLA
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": False,
        "DEBUG": False,
        "TVM_OP": False,
    }
    return feats


class Features(collections.abc.Mapping):
    """Mapping of feature name → Feature (reference runtime.py:52)."""

    def __init__(self):
        self._feats = {k: Feature(k, v) for k, v in _detect().items()}

    def __getitem__(self, k):
        return self._feats[k]

    def __iter__(self):
        return iter(self._feats)

    def __len__(self):
        return len(self._feats)

    def is_enabled(self, name: str) -> bool:
        return self._feats[name].enabled

    def __repr__(self):
        on = [k for k, f in self._feats.items() if f.enabled]
        return f"[{', '.join('✔ ' + k for k in on)}]"


def feature_list():
    """List of Feature namedtuples (reference runtime.py:75)."""
    return list(Features().values())
