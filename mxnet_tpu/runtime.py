"""Runtime feature introspection (reference: python/mxnet/runtime.py backed
by src/libinfo.cc — ``mx.runtime.feature_list()`` / ``Features``).

Features here describe the TPU build: which backends/subsystems are live in
this process (XLA platform, Pallas, the native C++ host runtime, …).
"""
from __future__ import annotations

import collections

__all__ = ["Feature", "Features", "feature_list"]

Feature = collections.namedtuple("Feature", ["name", "enabled"])


def _detect():
    import jax
    from . import _native
    backend = jax.default_backend()
    feats = {
        "TPU": backend == "tpu",
        "CUDA": False,            # by design: this build targets XLA/TPU
        "CUDNN": False,
        "NCCL": False,            # collectives ride XLA/ICI instead
        "XLA": True,
        "PALLAS": True,
        "BLAS_OPEN": True,        # XLA's CPU backend carries its own BLAS
        "MKLDNN": False,
        "OPENCV": False,
        "NATIVE_ENGINE": _native.available(),
        "RECORDIO": True,
        "DIST_KVSTORE": True,     # jax.distributed-backed
        "F16C": True,             # bf16/fp16 casts via XLA
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": False,
        "DEBUG": False,
        "TVM_OP": False,
    }
    return feats


class Features(collections.abc.Mapping):
    """Mapping of feature name → Feature (reference runtime.py:52)."""

    def __init__(self):
        self._feats = {k: Feature(k, v) for k, v in _detect().items()}

    def __getitem__(self, k):
        return self._feats[k]

    def __iter__(self):
        return iter(self._feats)

    def __len__(self):
        return len(self._feats)

    def is_enabled(self, name: str) -> bool:
        return self._feats[name].enabled

    def __repr__(self):
        on = [k for k, f in self._feats.items() if f.enabled]
        return f"[{', '.join('✔ ' + k for k in on)}]"


def feature_list():
    """List of Feature namedtuples (reference runtime.py:75)."""
    return list(Features().values())
