"""Serving load generator: closed- and open-loop traffic with p50/p99.

The two canonical load shapes for latency benchmarking:

- **closed loop** (:func:`run_closed_loop`): C concurrent clients, each
  issuing its next request the moment the previous one completes —
  measures sustainable throughput (QPS) under a fixed concurrency and
  the latency the system settles into at that load.
- **open loop** (:func:`run_open_loop`): requests arrive on a Poisson
  process at a target rate regardless of completions — the honest
  latency distribution under un-coordinated traffic (closed loops hide
  queueing spikes by self-throttling: coordinated omission).

Both record each request's TERMINAL STATE — one of ``ok`` (completed;
within the deadline when one is given), ``rejected`` (shed at
admission: a typed :class:`~mxnet_tpu.serving.Overloaded`),
``deadline_missed`` (a typed :class:`~mxnet_tpu.serving
.DeadlineExceeded`, or a completion that arrived after ``deadline_s``),
or ``error`` (anything else) — and report **goodput** (ok/s) separately
from raw QPS: under overload with shedding armed, goodput is the honest
capacity number; raw QPS flatters a service that answers late.

Reports carry QPS, goodput_qps, reject_rate, deadline_miss_rate, and
exact p50/p99 latency computed from the raw per-request samples of the
``ok`` population (no histogram interpolation — bench.py puts these
next to the training legs in the BENCH json;
``mx_serving_request_seconds`` carries the live-histogram view).

Fleet targets: :func:`fleet_issue` / :func:`fleet_submit` adapt a
:class:`~mxnet_tpu.serving.FleetRouter` (or a list of per-replica
submit callables) into the loops' issue/submit shape, carrying the
``fut.replica`` routing breadcrumb through successes AND failures.
When those breadcrumbs are present, both loops add a ``replicas`` key
to the report — per-replica {qps, goodput_qps, p50/p99, outcome
census} next to the fleet aggregate — so a hot or broken replica is
visible in the same artifact as the fleet number.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as onp

__all__ = ["run_closed_loop", "run_open_loop", "percentiles",
           "classify_outcome", "streaming_summary", "fleet_issue",
           "fleet_submit"]

OUTCOMES = ("ok", "rejected", "deadline_missed", "error")


def classify_outcome(exc: BaseException) -> str:
    """Map a request failure to its terminal state: a typed
    ``Overloaded`` (anywhere in the cause chain) is ``rejected``, a
    typed ``DeadlineExceeded`` is ``deadline_missed``, anything else
    is ``error``."""
    from .resilience import DeadlineExceeded, Overloaded
    seen = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if isinstance(e, Overloaded):
            return "rejected"
        if isinstance(e, DeadlineExceeded):
            return "deadline_missed"
        e = e.__cause__ or e.__context__
    return "error"


def percentiles(latencies) -> dict:
    """{p50_ms, p99_ms, mean_ms} from raw per-request seconds."""
    if not len(latencies):
        return {"p50_ms": None, "p99_ms": None, "mean_ms": None}
    a = onp.asarray(latencies, dtype="float64") * 1e3
    return {"p50_ms": round(float(onp.percentile(a, 50)), 3),
            "p99_ms": round(float(onp.percentile(a, 99)), 3),
            "mean_ms": round(float(a.mean()), 3)}


def streaming_summary(records, wall: Optional[float] = None) -> dict:
    """Aggregate per-request STREAMING records into the token-level
    latency view request-level p50/p99 cannot express: exact TTFT
    (time to first token) and TPOT (time per output token)
    percentiles, plus token goodput. A record is a dict with
    ``ttft_s`` (float), ``tpot_s`` (inter-token gaps, seconds) and
    ``tokens`` — the shape ``DecodeStream.record()`` produces."""
    records = [r for r in records if isinstance(r, dict)]
    ttfts = [r["ttft_s"] for r in records
             if r.get("ttft_s") is not None]
    tpots = [g for r in records for g in (r.get("tpot_s") or ())]
    tokens = sum(int(r.get("tokens") or 0) for r in records)
    out = {}
    out.update({"ttft_" + k: v for k, v in percentiles(ttfts).items()})
    out.update({"tpot_" + k: v for k, v in percentiles(tpots).items()})
    out["stream_tokens"] = tokens
    out["tokens_per_sec"] = round(tokens / wall, 2) \
        if wall and wall > 0 else None
    # speculative-decode view (present only when records carry the
    # engine's per-step accounting): acceptance_rate = accepted drafts
    # / proposed drafts, and tokens_per_step percentiles over the
    # pooled per-step emitted-token counts (> 1 means a verify step
    # emitted a whole accepted block in one dispatch)
    steps = [n for r in records for n in (r.get("step_tokens") or ())]
    if steps:
        drafted = sum(int(r.get("spec_drafted") or 0) for r in records)
        accepted = sum(int(r.get("spec_accepted") or 0)
                       for r in records)
        a = onp.asarray(steps, dtype="float64")
        out["acceptance_rate"] = round(accepted / drafted, 4) \
            if drafted else None
        out["tokens_per_step"] = {
            "mean": round(float(a.mean()), 3),
            "p50": round(float(onp.percentile(a, 50)), 3),
            "p99": round(float(onp.percentile(a, 99)), 3),
            "max": int(a.max()),
        }
    return out


def _maybe_streaming(out: dict, records: list, wall: float) -> dict:
    """Attach TTFT/TPOT/goodput next to the request-level percentiles
    when the issue/wait callables returned streaming records (a dict
    carrying ``ttft_s``); plain predictors change nothing."""
    recs = [r for r in records
            if isinstance(r, dict) and "ttft_s" in r]
    if recs:
        out.update(streaming_summary(recs, wall))
    return out


def _tally_replica(by: dict, replica, outcome: str, dt):
    """Fold one terminal state into the per-replica census (no-op when
    the request carried no routing breadcrumb — plain predictors)."""
    if not replica:
        return
    rec = by.setdefault(replica, {
        "outcomes": {k: 0 for k in OUTCOMES}, "lat": []})
    rec["outcomes"][outcome] += 1
    if dt is not None:
        rec["lat"].append(dt)


def _replica_report(by: dict, wall: float) -> dict:
    out = {}
    for name in sorted(by):
        rec = by[name]
        oc = rec["outcomes"]
        done = oc["ok"] + oc["deadline_missed"] + oc["error"]
        r = {"qps": round(done / wall, 2) if wall > 0 else None,
             "goodput_qps": round(oc["ok"] / wall, 2)
             if wall > 0 else None,
             "outcomes": dict(oc)}
        r.update(percentiles(rec["lat"]))
        out[name] = r
    return out


def _report(mode: str, outcomes: dict, ok_lat, wall: float,
            extra: dict, by_replica: Optional[dict] = None) -> dict:
    total = sum(outcomes.values())
    done = outcomes["ok"] + outcomes["deadline_missed"] \
        + outcomes["error"]
    out = dict(extra)
    out.update({
        "mode": mode,
        "requests": int(outcomes["ok"]),
        "issued": int(total),
        "errors": int(outcomes["error"]),
        "outcomes": dict(outcomes),
        "wall_s": round(wall, 4),
        "qps": round(done / wall, 2) if wall > 0 else None,
        "goodput_qps": round(outcomes["ok"] / wall, 2)
        if wall > 0 else None,
        "reject_rate": round(outcomes["rejected"] / total, 4)
        if total else None,
        "deadline_miss_rate": round(outcomes["deadline_missed"] / total,
                                    4) if total else None,
    })
    out.update(percentiles(ok_lat))
    if by_replica:
        out["replicas"] = _replica_report(by_replica, wall)
    return out


def _submit_of(target) -> Callable:
    """One submit callable from a fleet target: a FleetRouter (or any
    object with ``.submit``) routes every request; a LIST of submit
    callables (one per replica) is round-robined by request index."""
    if callable(getattr(target, "submit", None)):
        return lambda i, *args, **kw: target.submit(*args, **kw)
    fns = list(target)
    if not fns or not all(callable(f) for f in fns):
        raise TypeError(
            "fleet target must be a router (with .submit) or a "
            "non-empty list of submit callables")
    return lambda i, *args, **kw: fns[i % len(fns)](*args, **kw)


def _attributed_wait(fut, timeout):
    """``fut.result`` with the routing breadcrumb carried through both
    outcomes: failures get ``e.replica`` stamped so the loops can
    attribute sheds/deadline-misses, successes return the per-replica
    record."""
    try:
        fut.result(timeout)
    except BaseException as e:
        rep = getattr(fut, "replica", None)
        if rep is not None:
            try:
                e.replica = rep
            except Exception:    # pragma: no cover - exotic exception
                pass
        raise
    return {"replica": getattr(fut, "replica", None)}


def fleet_issue(target, make_args: Callable[[int], tuple],
                deadline_ms: Optional[float] = None,
                timeout: Optional[float] = 30.0) -> Callable:
    """Adapt a fleet target into :func:`run_closed_loop`'s
    ``issue(i)``: submit ``make_args(i)`` through the router (or the
    ``i % N``-th of a list of submit callables), wait for the result,
    and return the per-replica record the loop's census groups by."""
    submit = _submit_of(target)

    def issue(i: int):
        fut = submit(i, *make_args(i), deadline_ms=deadline_ms)
        return _attributed_wait(fut, timeout)
    return issue


def fleet_submit(target, make_args: Callable[[int], tuple],
                 deadline_ms: Optional[float] = None) -> Callable:
    """Adapt a fleet target into :func:`run_open_loop`'s
    ``submit(i)``: enqueue without waiting, return the wait callable
    (which yields the per-replica record)."""
    submit = _submit_of(target)

    def submit_one(i: int):
        fut = submit(i, *make_args(i), deadline_ms=deadline_ms)
        return lambda timeout=None: _attributed_wait(fut, timeout)
    return submit_one


def run_closed_loop(issue: Callable[[int], None], concurrency: int,
                    requests: int,
                    deadline_s: Optional[float] = None) -> dict:
    """C worker threads; each calls ``issue(i)`` (submit AND wait for
    one request) back-to-back until ``requests`` total are issued.
    Latency is the full ``issue`` wall time per request; with
    ``deadline_s`` a completion slower than it counts as
    ``deadline_missed``, not ``ok`` (goodput is ok/s). An ``issue``
    that RETURNS a streaming record (a dict with ``ttft_s``/``tpot_s``
    per token — ``DecodeStream.record()``) additionally gets exact
    TTFT/TPOT percentiles and ``tokens_per_sec`` in the report; one
    that returns/raises with a ``replica`` breadcrumb
    (:func:`fleet_issue`) additionally gets the per-replica census."""
    outcomes = {k: 0 for k in OUTCOMES}
    ok_lat: list = []
    stream_recs: list = []
    by_replica: dict = {}
    # bare on purpose: load-generator harness local; leaf lock
    lock = threading.Lock()  # mx-lint: allow=MXA009
    counter = [0]

    def worker():
        while True:
            with lock:
                i = counter[0]
                if i >= requests:
                    return
                counter[0] += 1
            t0 = time.perf_counter()
            try:
                ret = issue(i)
            except Exception as e:
                with lock:
                    oc = classify_outcome(e)
                    outcomes[oc] += 1
                    _tally_replica(by_replica,
                                   getattr(e, "replica", None), oc, None)
                continue
            dt = time.perf_counter() - t0
            with lock:
                rep = ret.get("replica") if isinstance(ret, dict) \
                    else None
                if isinstance(ret, dict) and "ttft_s" in ret:
                    stream_recs.append(ret)
                if deadline_s is not None and dt > deadline_s:
                    outcomes["deadline_missed"] += 1
                    _tally_replica(by_replica, rep, "deadline_missed",
                                   None)
                else:
                    outcomes["ok"] += 1
                    ok_lat.append(dt)
                    _tally_replica(by_replica, rep, "ok", dt)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, concurrency))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return _maybe_streaming(
        _report("closed", outcomes, ok_lat, wall,
                {"concurrency": int(concurrency)}, by_replica),
        stream_recs, wall)


def run_open_loop(submit: Callable[[int], Callable[[], None]],
                  rate_qps: float, requests: int,
                  seed: int = 0,
                  timeout: Optional[float] = 120.0,
                  deadline_s: Optional[float] = None) -> dict:
    """Poisson arrivals at ``rate_qps``: ``submit(i)`` must enqueue
    request ``i`` WITHOUT waiting and return a zero-arg wait callable
    (e.g. ``DynamicBatcher.submit(...).result``). Arrival jitter is
    deterministic per ``seed``. Latency = arrival (scheduled submit)
    to completion — queueing included, no coordinated omission. A
    ``submit`` that raises (admission-control shedding) is recorded as
    that request's terminal state — the arrival clock keeps ticking,
    exactly like real un-coordinated traffic."""
    import queue as _queue
    rng = onp.random.RandomState(seed)
    gaps = rng.exponential(1.0 / max(rate_qps, 1e-9), size=requests)
    outcomes = {k: 0 for k in OUTCOMES}
    ok_lat: list = []
    stream_recs: list = []
    by_replica: dict = {}
    # bare on purpose: load-generator harness local; leaf lock
    lock = threading.Lock()  # mx-lint: allow=MXA009
    # a waiter pool records each completion AS IT HAPPENS — waiting
    # sequentially after the arrival phase would inflate every early
    # request's latency by the remaining arrival time
    work: "_queue.Queue" = _queue.Queue()

    def waiter():
        while True:
            item = work.get()
            if item is None:
                return
            t0, wait = item
            try:
                try:
                    ret = wait() if timeout is None else wait(timeout)
                except TypeError:
                    ret = wait()
            except Exception as e:
                with lock:
                    oc = classify_outcome(e)
                    outcomes[oc] += 1
                    _tally_replica(by_replica,
                                   getattr(e, "replica", None), oc, None)
                continue
            dt = time.perf_counter() - t0
            with lock:
                rep = ret.get("replica") if isinstance(ret, dict) \
                    else None
                if isinstance(ret, dict) and "ttft_s" in ret:
                    stream_recs.append(ret)
                if deadline_s is not None and dt > deadline_s:
                    outcomes["deadline_missed"] += 1
                    _tally_replica(by_replica, rep, "deadline_missed",
                                   None)
                else:
                    outcomes["ok"] += 1
                    ok_lat.append(dt)
                    _tally_replica(by_replica, rep, "ok", dt)

    n_waiters = min(32, max(4, requests // 8))
    threads = [threading.Thread(target=waiter, daemon=True)
               for _ in range(n_waiters)]
    for t in threads:
        t.start()
    t_start = time.perf_counter()
    next_t = t_start
    for i in range(requests):
        now = time.perf_counter()
        if next_t > now:
            time.sleep(next_t - now)
        t0 = time.perf_counter()
        try:
            waitfn = submit(i)
        except Exception as e:       # shed at admission
            with lock:
                oc = classify_outcome(e)
                outcomes[oc] += 1
                _tally_replica(by_replica,
                               getattr(e, "replica", None), oc, None)
        else:
            work.put((t0, waitfn))
        next_t += gaps[i]
    for _ in threads:
        work.put(None)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return _maybe_streaming(
        _report("open", outcomes, ok_lat, wall,
                {"rate_qps": float(rate_qps)}, by_replica),
        stream_recs, wall)
