"""Serving load generator: closed- and open-loop traffic with p50/p99.

The two canonical load shapes for latency benchmarking:

- **closed loop** (:func:`run_closed_loop`): C concurrent clients, each
  issuing its next request the moment the previous one completes —
  measures sustainable throughput (QPS) under a fixed concurrency and
  the latency the system settles into at that load.
- **open loop** (:func:`run_open_loop`): requests arrive on a Poisson
  process at a target rate regardless of completions — the honest
  latency distribution under un-coordinated traffic (closed loops hide
  queueing spikes by self-throttling: coordinated omission).

Both return a report dict with QPS and exact p50/p99 latency computed
from the raw per-request samples (no histogram interpolation —
bench.py puts these next to the training legs in the BENCH json;
``mx_serving_request_seconds`` carries the live-histogram view).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

import numpy as onp

__all__ = ["run_closed_loop", "run_open_loop", "percentiles"]


def percentiles(latencies) -> dict:
    """{p50_ms, p99_ms, mean_ms} from raw per-request seconds."""
    if not len(latencies):
        return {"p50_ms": None, "p99_ms": None, "mean_ms": None}
    a = onp.asarray(latencies, dtype="float64") * 1e3
    return {"p50_ms": round(float(onp.percentile(a, 50)), 3),
            "p99_ms": round(float(onp.percentile(a, 99)), 3),
            "mean_ms": round(float(a.mean()), 3)}


def run_closed_loop(issue: Callable[[int], None], concurrency: int,
                    requests: int) -> dict:
    """C worker threads; each calls ``issue(i)`` (submit AND wait for
    one request) back-to-back until ``requests`` total are done.
    Latency is the full ``issue`` wall time per request."""
    latencies: list = []
    errors = [0]
    lock = threading.Lock()
    counter = [0]

    def worker():
        while True:
            with lock:
                i = counter[0]
                if i >= requests:
                    return
                counter[0] += 1
            t0 = time.perf_counter()
            try:
                issue(i)
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, concurrency))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    out = {"mode": "closed", "concurrency": int(concurrency),
           "requests": int(len(latencies)), "errors": int(errors[0]),
           "wall_s": round(wall, 4),
           "qps": round(len(latencies) / wall, 2) if wall > 0 else None}
    out.update(percentiles(latencies))
    return out


def run_open_loop(submit: Callable[[int], Callable[[], None]],
                  rate_qps: float, requests: int,
                  seed: int = 0,
                  timeout: Optional[float] = 120.0) -> dict:
    """Poisson arrivals at ``rate_qps``: ``submit(i)`` must enqueue
    request ``i`` WITHOUT waiting and return a zero-arg wait callable
    (e.g. ``DynamicBatcher.submit(...).result``). Arrival jitter is
    deterministic per ``seed``. Latency = arrival (scheduled submit)
    to completion — queueing included, no coordinated omission."""
    import queue as _queue
    rng = onp.random.RandomState(seed)
    gaps = rng.exponential(1.0 / max(rate_qps, 1e-9), size=requests)
    latencies: list = []
    errors = [0]
    lock = threading.Lock()
    # a waiter pool records each completion AS IT HAPPENS — waiting
    # sequentially after the arrival phase would inflate every early
    # request's latency by the remaining arrival time
    work: "_queue.Queue" = _queue.Queue()

    def waiter():
        while True:
            item = work.get()
            if item is None:
                return
            t0, wait = item
            try:
                try:
                    wait() if timeout is None else wait(timeout)
                except TypeError:
                    wait()
            except Exception:
                with lock:
                    errors[0] += 1
                continue
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)

    n_waiters = min(32, max(4, requests // 8))
    threads = [threading.Thread(target=waiter, daemon=True)
               for _ in range(n_waiters)]
    for t in threads:
        t.start()
    t_start = time.perf_counter()
    next_t = t_start
    for i in range(requests):
        now = time.perf_counter()
        if next_t > now:
            time.sleep(next_t - now)
        work.put((time.perf_counter(), submit(i)))
        next_t += gaps[i]
    for _ in threads:
        work.put(None)
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    out = {"mode": "open", "rate_qps": float(rate_qps),
           "requests": int(len(latencies)), "errors": int(errors[0]),
           "wall_s": round(wall, 4),
           "qps": round(len(latencies) / wall, 2) if wall > 0 else None}
    out.update(percentiles(latencies))
    return out
