"""Paged KV cache: the pooled page allocator behind continuous batching.

The decode engine's working memory is K/V history, and its lifetime is
per-REQUEST, not per-batch: requests of wildly different lengths join
and leave the running batch every step. Contiguous per-slot buffers
sized for the worst case waste HBM proportional to (max_len − actual);
this module instead pools fixed-size pages (``page_size`` tokens each,
shared across layers in one allocation) and hands each request exactly
``ceil(tokens / page_size)`` of them — the vLLM-style discipline, on the
same accounting substrate as the rest of the framework:

- **Shape-stable programs.** The compiled decode step reads K/V through
  a (slots, max_pages) int32 page table (gather) and writes through
  scatter indices, so which physical pages a request holds never
  changes the program. Page 0 is the reserved NULL page: page-table
  padding and inactive-slot writes all target it, making masked slots
  harmless without a branch.
- **One accounting path.** The page arrays are NDArray handles
  registered in the :class:`~mxnet_tpu.telemetry.memory.BufferCensus`
  ``kvcache`` pool; :meth:`PagedKVCache.total_bytes` prices them with
  the same ``device_bytes()`` rule the census uses, so allocator bytes
  == census bytes by construction (a tier-1 test pins the equality).
  ``MXNET_MEMORY_BUDGET`` therefore covers the cache like any other
  pool, and an OOM rides the PR 7 post-mortem dump with the pages
  attributed.
- **Admission = free pages.** :meth:`can_reserve` / :meth:`reserve` are
  the decode engine's admission-control primitive: a request that
  cannot get its pages up front is shed with a typed
  ``Overloaded(reason="kvcache")`` instead of corrupting a neighbour
  mid-flight.

Donation discipline: the engine's compiled step donates the page
arrays and rebinds each handle's ``_data`` after dispatch — the census
weakrefs survive because the HANDLE survives (telemetry/memory.py's
registration contract).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as onp

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["PagedKVCache", "KV_PAGE_SIZE", "pages_needed"]

#: tokens per KV page — the shipped default behind the
#: ``decode.kv_page_size`` tunable / ``MXNET_DECODE_KV_PAGE_SIZE``
#: (consumers read the live value through ``serving.decode
#: .kv_page_size()``, never this constant directly)
KV_PAGE_SIZE = 16


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages covering ``tokens`` positions."""
    return max(1, -(-int(tokens) // max(1, int(page_size))))


class PagedKVCache:
    """Fixed-size K/V pages for ``num_layers`` attention layers plus a
    free-list allocator over them.

    Layout: one K array and one V array of shape
    ``(num_layers, num_pages, page_size, num_heads, head_dim)`` — a
    single allocation each, so the census sees two buffers, not 2·L·P.
    Page ids are shared across layers (a request's page p holds its
    tokens ``[p*page_size, (p+1)*page_size)`` in EVERY layer), which
    keeps the page table one (slots, max_pages) array.

    Page 0 is reserved as the null page and never allocated.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_pages: int, page_size: Optional[int] = None,
                 dtype: str = "float32"):
        if page_size is None:
            from . import decode as _dec
            page_size = _dec.kv_page_size()
        if num_pages < 2:
            raise MXNetError(
                f"PagedKVCache needs num_pages >= 2 (page 0 is the "
                f"reserved null page), got {num_pages}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = max(1, int(page_size))
        self.dtype = str(dtype)
        shape = (self.num_layers, self.num_pages, self.page_size,
                 self.num_heads, self.head_dim)
        # NDArray handles: _data rebinds after every donated step while
        # the handle (and its census registration) survives
        self.k_pages = NDArray(jnp.zeros(shape, dtype=self.dtype))
        self.v_pages = NDArray(jnp.zeros(shape, dtype=self.dtype))
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._owned: Dict[object, List[int]] = {}
        self._reserved: Dict[object, int] = {}
        from .. import telemetry as _t
        _t.memory.census().register("kvcache", self.k_pages)
        _t.memory.census().register("kvcache", self.v_pages)
        self._g_pages = _t.registry().gauge(_t.names.DECODE_KV_PAGES,
                                            label_key="state")
        self._publish()

    # ---------------- accounting ----------------
    @property
    def bytes_per_page(self) -> int:
        """Bytes one page costs across K+V and every layer (itemsize ·
        page_size · heads · head_dim · layers · 2)."""
        itemsize = 2 if self.dtype == "bfloat16" \
            else onp.dtype(self.dtype).itemsize
        return (2 * self.num_layers * self.page_size * self.num_heads
                * self.head_dim * itemsize)

    def total_bytes(self) -> int:
        """Allocator-side bytes of the page arrays — priced with the
        census's ``device_bytes`` rule so the two accountings cannot
        drift (one accounting path; tier-1 pins the equality)."""
        from ..telemetry.memory import device_bytes
        return device_bytes(self.k_pages) + device_bytes(self.v_pages)

    def free_pages(self) -> int:
        """Allocatable pages right now (reservations excluded)."""
        return len(self._free) - sum(self._reserved.values())

    def used_pages(self) -> int:
        return sum(len(p) for p in self._owned.values())

    def utilization(self) -> float:
        """used / allocatable (the null page is outside both)."""
        cap = self.num_pages - 1
        return self.used_pages() / cap if cap else 0.0

    # ---------------- admission ----------------
    def can_reserve(self, n: int) -> bool:
        return self.free_pages() >= int(n)

    def reserve(self, owner, n: int) -> bool:
        """Earmark ``n`` pages for ``owner`` (admission control):
        reserved pages are excluded from :meth:`free_pages` so two
        admitted requests can never race for the same page. Returns
        False (nothing reserved) when the pool cannot cover it."""
        n = int(n)
        if not self.can_reserve(n):
            return False
        self._reserved[owner] = self._reserved.get(owner, 0) + n
        self._publish()
        return True

    def unreserve(self, owner):
        self._reserved.pop(owner, None)
        self._publish()

    # ---------------- alloc / free ----------------
    def alloc(self, owner, n: int = 1) -> Optional[List[int]]:
        """Allocate ``n`` pages to ``owner``, drawing down its
        reservation first. None when the free list cannot cover it
        (an admitted request never sees this if it reserved honestly)."""
        n = int(n)
        reserved = self._reserved.get(owner, 0)
        unreserved_need = max(0, n - reserved)
        if unreserved_need > self.free_pages():
            return None
        if reserved:
            left = max(0, reserved - n)
            if left:
                self._reserved[owner] = left
            else:
                self._reserved.pop(owner, None)
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(pages)
        self._publish()
        return pages

    def pages_of(self, owner) -> List[int]:
        return list(self._owned.get(owner, ()))

    def release(self, owner):
        """Return every page ``owner`` holds (and any leftover
        reservation) to the free list — the slot-retire path."""
        pages = self._owned.pop(owner, [])
        self._free.extend(reversed(pages))
        self._reserved.pop(owner, None)
        self._publish()
        return len(pages)

    # ---------------- observability ----------------
    def _publish(self):
        try:
            self._g_pages.set(self.used_pages(), label="used")
            self._g_pages.set(self.free_pages(), label="free")
        except Exception:    # pragma: no cover - telemetry never fatal
            pass

    def stats(self) -> dict:
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "used_pages": self.used_pages(),
            "free_pages": self.free_pages(),
            "reserved_pages": sum(self._reserved.values()),
            "owners": len(self._owned),
            "bytes_per_page": self.bytes_per_page,
            "total_bytes": self.total_bytes(),
            "utilization": round(self.utilization(), 4),
        }

    def __repr__(self):
        s = self.stats()
        return (f"PagedKVCache(pages={s['used_pages']}/"
                f"{self.num_pages - 1} used, page_size={self.page_size}, "
                f"layers={self.num_layers}, "
                f"bytes={s['total_bytes']})")
