"""Paged KV cache: the pooled page allocator behind continuous batching.

The decode engine's working memory is K/V history, and its lifetime is
per-REQUEST, not per-batch: requests of wildly different lengths join
and leave the running batch every step. Contiguous per-slot buffers
sized for the worst case waste HBM proportional to (max_len − actual);
this module instead pools fixed-size pages (``page_size`` tokens each,
shared across layers in one allocation) and hands each request exactly
``ceil(tokens / page_size)`` of them — the vLLM-style discipline, on the
same accounting substrate as the rest of the framework:

- **Shape-stable programs.** The compiled decode step reads K/V through
  a (slots, max_pages) int32 page table (gather) and writes through
  scatter indices, so which physical pages a request holds never
  changes the program. Page 0 is the reserved NULL page: page-table
  padding and inactive-slot writes all target it, making masked slots
  harmless without a branch.
- **One accounting path.** The page arrays are NDArray handles
  registered in the :class:`~mxnet_tpu.telemetry.memory.BufferCensus`
  ``kvcache`` pool; :meth:`PagedKVCache.total_bytes` prices them with
  the same ``device_bytes()`` rule the census uses, so allocator bytes
  == census bytes by construction (a tier-1 test pins the equality).
  ``MXNET_MEMORY_BUDGET`` therefore covers the cache like any other
  pool, and an OOM rides the PR 7 post-mortem dump with the pages
  attributed.
- **Admission = free pages.** :meth:`can_reserve` / :meth:`reserve` are
  the decode engine's admission-control primitive: a request that
  cannot get its pages up front is shed with a typed
  ``Overloaded(reason="kvcache")`` instead of corrupting a neighbour
  mid-flight.

Donation discipline: the engine's compiled step donates the page
arrays and rebinds each handle's ``_data`` after dispatch — the census
weakrefs survive because the HANDLE survives (telemetry/memory.py's
registration contract).

**Prefix sharing + copy-on-write** (docs/SERVING.md "Speculative decode
& prefix sharing"): the allocator additionally keeps a content-hashed
registry over committed prefill pages. Because a page's K/V content is
a function of the ENTIRE token prefix up to its end (the recurrent
state threads through every position), the registry key is the full
token prefix ``prompt[:pos]`` — hashed for lookup, and byte-verified
against the stored tokens before any sharing decision (a hash
collision must never alias two different prefixes). A request whose
prompt extends a registered prefix maps the same physical pages
(:meth:`PagedKVCache.share` bumps per-page refcounts) and the engine
skips prefilling the shared region. Pages are freed refcount-exactly:
:meth:`release` returns a page to the free list only when its LAST
holder leaves, and evicts any registry entry built over it — the
registry pins nothing by itself, so allocator bytes == census bytes
keeps holding and a shed/EOS frees exactly the private tail. A write
landing on a page held by >= 2 requests first gets a private copy
(:meth:`cow` — one device-side page copy, no host sync), so divergence
after a shared prefix can never corrupt a neighbour.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as onp

import jax.numpy as jnp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["PagedKVCache", "KV_PAGE_SIZE", "pages_needed",
           "prefix_hash"]

#: tokens per KV page — the shipped default behind the
#: ``decode.kv_page_size`` tunable / ``MXNET_DECODE_KV_PAGE_SIZE``
#: (consumers read the live value through ``serving.decode
#: .kv_page_size()``, never this constant directly)
KV_PAGE_SIZE = 16


def pages_needed(tokens: int, page_size: int) -> int:
    """Pages covering ``tokens`` positions."""
    return max(1, -(-int(tokens) // max(1, int(page_size))))


def prefix_hash(tokens) -> int:
    """Registry key for a committed token prefix: a stable content hash
    over the int32 token bytes. Lookups ALWAYS byte-verify against the
    stored tokens afterwards — tests monkeypatch this to a constant to
    pin that a hash collision alone can never alias two prefixes."""
    b = onp.ascontiguousarray(tokens, onp.int32).tobytes()
    return int.from_bytes(hashlib.blake2b(b, digest_size=8).digest(),
                          "little")


class _PrefixEntry:
    """One registered prefix: ``pages`` hold the K/V of
    ``tokens[:pos]`` (last page possibly partial), ``state`` is the
    engine's opaque recurrent-state snapshot at ``pos``."""

    __slots__ = ("tokens", "pages", "pos", "state")

    def __init__(self, tokens, pages, pos, state):
        self.tokens = onp.ascontiguousarray(tokens, onp.int32)
        self.pages = tuple(int(p) for p in pages)
        self.pos = int(pos)
        self.state = state


class PagedKVCache:
    """Fixed-size K/V pages for ``num_layers`` attention layers plus a
    free-list allocator over them.

    Layout: one K array and one V array of shape
    ``(num_layers, num_pages, page_size, num_heads, head_dim)`` — a
    single allocation each, so the census sees two buffers, not 2·L·P.
    Page ids are shared across layers (a request's page p holds its
    tokens ``[p*page_size, (p+1)*page_size)`` in EVERY layer), which
    keeps the page table one (slots, max_pages) array.

    Page 0 is reserved as the null page and never allocated.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_pages: int, page_size: Optional[int] = None,
                 dtype: str = "float32"):
        if page_size is None:
            from . import decode as _dec
            page_size = _dec.kv_page_size()
        if num_pages < 2:
            raise MXNetError(
                f"PagedKVCache needs num_pages >= 2 (page 0 is the "
                f"reserved null page), got {num_pages}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = max(1, int(page_size))
        self.dtype = str(dtype)
        shape = (self.num_layers, self.num_pages, self.page_size,
                 self.num_heads, self.head_dim)
        # NDArray handles: _data rebinds after every donated step while
        # the handle (and its census registration) survives
        self.k_pages = NDArray(jnp.zeros(shape, dtype=self.dtype))
        self.v_pages = NDArray(jnp.zeros(shape, dtype=self.dtype))
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._owned: Dict[object, List[int]] = {}
        self._reserved: Dict[object, int] = {}
        # prefix sharing: per-page holder counts (only pages held by
        # >= 2 owners appear), the content-hash registry, and the
        # page -> registry-keys index driving refcount-exact eviction
        self._refcnt: Dict[int, int] = {}
        self._prefix: Dict[int, List[_PrefixEntry]] = {}
        self._page_keys: Dict[int, set] = {}
        self.cow_copies = 0
        self.prefix_hits = 0
        from .. import telemetry as _t
        _t.memory.census().register("kvcache", self.k_pages)
        _t.memory.census().register("kvcache", self.v_pages)
        self._g_pages = _t.registry().gauge(_t.names.DECODE_KV_PAGES,
                                            label_key="state")
        self._m_prefix_hits = _t.registry().counter(
            _t.names.DECODE_PREFIX_HITS)
        self._m_cow = _t.registry().counter(_t.names.DECODE_COW_COPIES)
        self._publish()

    # ---------------- accounting ----------------
    @property
    def bytes_per_page(self) -> int:
        """Bytes one page costs across K+V and every layer (itemsize ·
        page_size · heads · head_dim · layers · 2)."""
        itemsize = 2 if self.dtype == "bfloat16" \
            else onp.dtype(self.dtype).itemsize
        return (2 * self.num_layers * self.page_size * self.num_heads
                * self.head_dim * itemsize)

    def total_bytes(self) -> int:
        """Allocator-side bytes of the page arrays — priced with the
        census's ``device_bytes`` rule so the two accountings cannot
        drift (one accounting path; tier-1 pins the equality)."""
        from ..telemetry.memory import device_bytes
        return device_bytes(self.k_pages) + device_bytes(self.v_pages)

    def free_pages(self) -> int:
        """Allocatable pages right now (reservations excluded)."""
        return len(self._free) - sum(self._reserved.values())

    def used_pages(self) -> int:
        """PHYSICAL pages allocated (a page shared by N requests
        counts once — that is the whole point of sharing)."""
        return self.num_pages - 1 - len(self._free)

    def logical_pages(self) -> int:
        """Request-side page holdings summed over owners (a shared
        page counts once PER holder); logical - used = pages saved by
        prefix sharing."""
        return sum(len(p) for p in self._owned.values())

    def shared_pages(self) -> int:
        """Physical pages currently mapped by >= 2 owners."""
        return sum(1 for n in self._refcnt.values() if n >= 2)

    def utilization(self) -> float:
        """used / allocatable (the null page is outside both)."""
        cap = self.num_pages - 1
        return self.used_pages() / cap if cap else 0.0

    # ---------------- admission ----------------
    def can_reserve(self, n: int) -> bool:
        return self.free_pages() >= int(n)

    def reserve(self, owner, n: int) -> bool:
        """Earmark ``n`` pages for ``owner`` (admission control):
        reserved pages are excluded from :meth:`free_pages` so two
        admitted requests can never race for the same page. Returns
        False (nothing reserved) when the pool cannot cover it."""
        n = int(n)
        if not self.can_reserve(n):
            return False
        self._reserved[owner] = self._reserved.get(owner, 0) + n
        self._publish()
        return True

    def unreserve(self, owner):
        self._reserved.pop(owner, None)
        self._publish()

    def trim_reservation(self, owner, keep: int):
        """Lower ``owner``'s reservation to at most ``keep`` pages —
        the seat-time correction when a prefix-cache hit means the
        submit-time worst-case pricing over-reserved."""
        keep = max(0, int(keep))
        have = self._reserved.get(owner, 0)
        if have > keep:
            if keep:
                self._reserved[owner] = keep
            else:
                self._reserved.pop(owner, None)
            self._publish()

    # ---------------- alloc / free ----------------
    def alloc(self, owner, n: int = 1) -> Optional[List[int]]:
        """Allocate ``n`` pages to ``owner``, drawing down its
        reservation first. None when the free list cannot cover it
        (an admitted request never sees this if it reserved honestly)."""
        n = int(n)
        reserved = self._reserved.get(owner, 0)
        unreserved_need = max(0, n - reserved)
        if unreserved_need > self.free_pages():
            return None
        if reserved:
            left = max(0, reserved - n)
            if left:
                self._reserved[owner] = left
            else:
                self._reserved.pop(owner, None)
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(owner, []).extend(pages)
        self._publish()
        return pages

    def pages_of(self, owner) -> List[int]:
        return list(self._owned.get(owner, ()))

    def release(self, owner):
        """Return every page ``owner`` holds (and any leftover
        reservation) to the free list — the slot-retire path. A SHARED
        page only leaves ``owner``'s holdings: it goes back to the
        free list (and its registry entries are evicted) exactly when
        the last holder releases it — refcount-exact frees, so a
        mid-stream shed or EOS returns precisely the private tail."""
        pages = self._owned.pop(owner, [])
        freed = []
        for p in reversed(pages):
            n = self._refcnt.get(p)
            if n is not None and n >= 2:
                if n == 2:
                    self._refcnt.pop(p, None)
                else:
                    self._refcnt[p] = n - 1
                continue
            self._refcnt.pop(p, None)
            self._evict_prefixes(p)
            self._free.append(p)
            freed.append(p)
        self._reserved.pop(owner, None)
        self._publish()
        return len(freed)

    # ---------------- prefix sharing + copy-on-write ----------------
    def page_shared(self, page: int) -> bool:
        """Whether a write to ``page`` needs a private copy first."""
        return self._refcnt.get(int(page), 1) >= 2

    def share(self, owner, pages) -> List[int]:
        """Map already-allocated ``pages`` into ``owner``'s holdings
        (the prefix-cache hit path): each page's holder count bumps and
        the page now frees only when its LAST holder releases."""
        pages = [int(p) for p in pages]
        for p in pages:
            if not 1 <= p < self.num_pages or p in self._free:
                raise MXNetError(f"share: page {p} is not allocated")
            self._refcnt[p] = self._refcnt.get(p, 1) + 1
        self._owned.setdefault(owner, []).extend(pages)
        self.prefix_hits += 1
        try:
            self._m_prefix_hits.inc()
        except Exception:    # pragma: no cover - telemetry never fatal
            pass
        self._publish()
        return pages

    def cow(self, owner, page: int) -> int:
        """Copy-on-write: give ``owner`` a private copy of ``page``
        before it writes (one device-side page copy across K, V and
        every layer — async, no host sync). Draws the copy target from
        ``owner``'s reservation/free list, swaps it into the holdings,
        and drops ``owner``'s hold on the original. Returns the new
        page id."""
        page = int(page)
        held = self._owned.get(owner, [])
        if page not in held:
            raise MXNetError(f"cow: owner does not hold page {page}")
        got = self.alloc(owner, 1)
        if got is None:
            raise MXNetError(
                "cow: no page available for a copy-on-write target "
                "(admission under-priced the unshared tail)")
        new = got[0]
        kd, vd = self.k_pages._data, self.v_pages._data
        self.k_pages._data = kd.at[:, new].set(kd[:, page])
        self.v_pages._data = vd.at[:, new].set(vd[:, page])
        held.remove(page)
        n = self._refcnt.get(page)
        if n is not None:
            if n <= 2:
                self._refcnt.pop(page, None)
            else:
                self._refcnt[page] = n - 1
        self.cow_copies += 1
        try:
            self._m_cow.inc()
        except Exception:    # pragma: no cover - telemetry never fatal
            pass
        self._publish()
        return new

    def register_prefix(self, tokens, pos: int, pages, state=None):
        """Commit ``tokens[:pos]`` -> ``pages`` into the content-hash
        registry (``state`` = the engine's recurrent-state snapshot at
        ``pos``). Entries hold no refcount of their own: they are
        evicted the moment any underlying page is freed."""
        pos = int(pos)
        if pos < 1:
            return
        toks = onp.ascontiguousarray(
            onp.asarray(tokens, onp.int32).ravel()[:pos])
        key = prefix_hash(toks)
        bucket = self._prefix.setdefault(key, [])
        for e in bucket:
            if e.pos == pos and onp.array_equal(e.tokens, toks):
                return                      # already registered
        entry = _PrefixEntry(toks, pages, pos, state)
        bucket.append(entry)
        for p in entry.pages:
            self._page_keys.setdefault(p, set()).add(key)

    def lookup_prefix(self, prompt, max_pos: Optional[int] = None):
        """Longest registered prefix of ``prompt`` (hash lookup per
        registered boundary position, then a BYTE compare against the
        stored tokens — a hash collision must never share). Returns the
        :class:`_PrefixEntry` or None; ``max_pos`` caps the usable
        prefix length (the engine keeps >= 1 prompt token to prefill)."""
        prompt = onp.asarray(prompt, onp.int32).ravel()
        cap = prompt.size if max_pos is None else min(int(max_pos),
                                                      prompt.size)
        positions = sorted({e.pos for b in self._prefix.values()
                            for e in b if e.pos <= cap}, reverse=True)
        for pos in positions:
            key = prefix_hash(onp.ascontiguousarray(prompt[:pos]))
            for e in self._prefix.get(key, ()):
                if e.pos == pos and onp.array_equal(
                        e.tokens, prompt[:pos]):
                    return e
        return None

    def prefix_entries(self) -> int:
        return sum(len(b) for b in self._prefix.values())

    def _evict_prefixes(self, page: int):
        """Drop every registry entry built over ``page`` (called when
        the page returns to the free list)."""
        for key in self._page_keys.pop(page, ()):
            bucket = self._prefix.get(key)
            if not bucket:
                continue
            bucket[:] = [e for e in bucket if page not in e.pages]
            if not bucket:
                self._prefix.pop(key, None)

    # ---------------- observability ----------------
    def _publish(self):
        try:
            self._g_pages.set(self.used_pages(), label="used")
            self._g_pages.set(self.free_pages(), label="free")
            self._g_pages.set(self.shared_pages(), label="shared")
        except Exception:    # pragma: no cover - telemetry never fatal
            pass

    def stats(self) -> dict:
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "used_pages": self.used_pages(),
            "logical_pages": self.logical_pages(),
            "shared_pages": self.shared_pages(),
            "free_pages": self.free_pages(),
            "reserved_pages": sum(self._reserved.values()),
            "owners": len(self._owned),
            "prefix_entries": self.prefix_entries(),
            "prefix_hits": self.prefix_hits,
            "cow_copies": self.cow_copies,
            "bytes_per_page": self.bytes_per_page,
            "total_bytes": self.total_bytes(),
            "utilization": round(self.utilization(), 4),
        }

    def __repr__(self):
        s = self.stats()
        return (f"PagedKVCache(pages={s['used_pages']}/"
                f"{self.num_pages - 1} used, page_size={self.page_size}, "
                f"layers={self.num_layers}, "
                f"bytes={s['total_bytes']})")
