"""Serving fleet controller: multi-replica routing + failover + rollout.

One :class:`~mxnet_tpu.serving.ServingSupervisor` keeps one replica
alive; this module runs a FLEET of them — one CompiledPredictor +
DynamicBatcher + supervisor per device group, all AOT-warmed from the
shared ``MXNET_COMPILE_CACHE`` (the first replica pays the XLA
compiles; every later spawn/restart pays cache hits) — and puts a
router in front:

- **:class:`FleetController`** — spawns ``MXNET_FLEET_REPLICAS``
  replicas, each built under ``jax.default_device(<its device>)`` so
  params and AOT executables land per-replica; owns the replica
  lifecycle state machine (``serving`` → ``draining``/``recovering``
  → ``retired``).
- **:class:`FleetRouter`** — ``submit()`` picks the serving replica
  with the lowest projected queue wait (each batcher's admission EWMA
  × queued batches), routing around open breakers, draining, and dead
  replicas. When NO replica can take traffic the caller gets a typed
  :class:`~mxnet_tpu.serving.Overloaded` (``reason="fleet"``) —
  never a hang.
- **Replica-loss failover** — a ``device_lost`` at any replica's
  dispatch/retire seam moves that replica's in-flight AND queued
  requests onto the surviving replicas EXACTLY ONCE (the same
  ``requeues`` budget the single-replica supervisor enforces; their
  :class:`~mxnet_tpu.serving.ServingFuture`\\ s re-arm, so a client
  already blocked in ``result()`` rides through), then restarts the
  replica on a spare device with bounded backoff. ``fatal``/``oom``
  causes propagate — a bigger fleet cannot cure a shape bug.
- **Autoscaling** — ``maybe_scale()`` grows the fleet when the fleet
  queue-wait EWMA exceeds ``MXNET_FLEET_SCALE_UP_WAIT_MS`` (and a
  device is free), and drain-then-retires the emptiest replica when
  the fleet is idle below ``MXNET_FLEET_SCALE_DOWN_WAIT_MS``, bounded
  by ``MXNET_FLEET_MIN_REPLICAS``/``MXNET_FLEET_MAX_REPLICAS``.
- **Drain-then-retire** — a scoped preemption notice
  (``elastic.notice("fleet/replica-N")``) drains exactly that replica
  (flush accepted, reject new, retire); the process-global notice
  still drains every replica.
- **Zero-downtime weight rollout** — :meth:`FleetController
  .swap_weights` walks the replicas ONE AT A TIME: drain (accepted
  requests finish on the old weights), load the CRC-verified
  checkpoint (``checkpoint.atomic``), swap params in place (the AOT
  executables take params by handle — no recompile), warm-probe,
  return to rotation. The checkpoint is validated BEFORE any replica
  drains, so a corrupt checkpoint aborts typed
  (:class:`~mxnet_tpu.checkpoint.CheckpointCorruptError`) with the
  fleet still serving the OLD weights; at most one weight version of
  skew is ever in flight.

Telemetry: ``mx_fleet_replicas{state}``,
``mx_fleet_routed_requests_total{replica}``,
``mx_fleet_replica_restarts_total``, ``mx_fleet_weight_swaps_total``,
``mx_fleet_scale_events_total{direction}``,
``mx_fleet_queue_wait_seconds`` (docs/OBSERVABILITY.md). Every
lifecycle transition is a structured :class:`FleetEvent` in
``controller.events`` (the ``tools/diagnose.py --fleet`` panel).

Deterministic testing: ``start=False`` runs every batcher in
manual-drive mode — drive :meth:`FleetController.pump` with an
injected ``clock=``; replica restarts then run inline (no background
thread, no wall-clock backoff). The chaos harness targets one replica
via ``point@ctx`` fault rules (``testing/faults.py``), e.g.
``serving.dispatch@replica-1:before=1:revoke:d3``.
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time
from functools import partial
from typing import Callable, List, Optional, Sequence

from ..analysis import guard as _tguard
from ..analysis.threads import mx_lock, mx_rlock
from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..testing.faults import fault_point
from .batcher import DynamicBatcher
from .resilience import (CircuitBreaker, Overloaded, ServingShutdown,
                         ServingSupervisor)

__all__ = ["FleetController", "FleetRouter", "FleetEvent",
           "fleet_replicas", "fleet_min_replicas", "fleet_max_replicas",
           "fleet_scale_up_wait_s", "fleet_scale_down_wait_s",
           "fleet_restart_retries"]

_LOG = logging.getLogger("mxnet_tpu.serving")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


# ---------------------------------------------------------------- env gates
def fleet_replicas(default: int = 1) -> int:
    """``MXNET_FLEET_REPLICAS``: initial replica count (each needs its
    own device from ``parallel.dist.available_devices()``)."""
    try:
        v = int(os.environ.get("MXNET_FLEET_REPLICAS", str(default)))
    except (TypeError, ValueError):
        return default
    return max(1, v)


def fleet_min_replicas(default: int = 1) -> int:
    """``MXNET_FLEET_MIN_REPLICAS``: scale-down floor."""
    try:
        v = int(os.environ.get("MXNET_FLEET_MIN_REPLICAS", str(default)))
    except (TypeError, ValueError):
        return default
    return max(1, v)


def fleet_max_replicas(default: int = 0) -> int:
    """``MXNET_FLEET_MAX_REPLICAS``: scale-up ceiling; <= 0 means
    "one per available device"."""
    try:
        v = int(os.environ.get("MXNET_FLEET_MAX_REPLICAS", str(default)))
    except (TypeError, ValueError):
        return default
    return v


def fleet_scale_up_wait_s(default_ms: float = 200.0) -> float:
    """``MXNET_FLEET_SCALE_UP_WAIT_MS``: fleet queue-wait EWMA above
    which ``maybe_scale()`` adds a replica (high-water mark), as
    seconds."""
    try:
        v = float(os.environ.get("MXNET_FLEET_SCALE_UP_WAIT_MS",
                                 str(default_ms)))
    except (TypeError, ValueError):
        v = default_ms
    return max(0.0, v) / 1e3


def fleet_scale_down_wait_s(default_ms: float = 5.0) -> float:
    """``MXNET_FLEET_SCALE_DOWN_WAIT_MS``: fleet queue-wait EWMA below
    which ``maybe_scale()`` drain-then-retires the emptiest replica
    (low-water mark), as seconds. <= 0 disables scale-down."""
    try:
        v = float(os.environ.get("MXNET_FLEET_SCALE_DOWN_WAIT_MS",
                                 str(default_ms)))
    except (TypeError, ValueError):
        v = default_ms
    return v / 1e3


def fleet_restart_retries(default: int = 2) -> int:
    """``MXNET_FLEET_RESTART_RETRIES``: extra attempts (beyond the
    first) to restart a lost replica before it is retired."""
    try:
        v = int(os.environ.get("MXNET_FLEET_RESTART_RETRIES",
                               str(default)))
    except (TypeError, ValueError):
        return default
    return max(0, v)


# ---------------------------------------------------------------- events
class FleetEvent:
    """One structured fleet lifecycle record: ``kind`` (spawn /
    replica_lost / failover / restart / restart_failed / replica_dead /
    drain / retire / preempt_drain / preempt_retire / scale_up /
    scale_down / swap_begin / swap_drain / swap_done / swap_abort /
    swap_complete), the replica it concerns (None = fleet-wide), the
    controller-clock timestamp, and a detail dict."""

    __slots__ = ("kind", "replica", "t", "detail")

    def __init__(self, kind: str, replica: Optional[str], t: float,
                 detail: Optional[dict] = None):
        self.kind = kind
        self.replica = replica
        self.t = t
        self.detail = dict(detail) if detail else {}

    def __repr__(self):
        who = f" {self.replica}" if self.replica else ""
        return f"<FleetEvent {self.kind}{who} t={self.t:.3f} " \
               f"{self.detail}>"


class _Replica:
    """One serving replica's bookkeeping (the supervisor does the
    work; this records identity + lifecycle state for the router)."""

    SERVING = "serving"
    DRAINING = "draining"
    RECOVERING = "recovering"
    RETIRED = "retired"
    STATES = (SERVING, DRAINING, RECOVERING, RETIRED)

    __slots__ = ("name", "index", "device", "sup", "scope", "version",
                 "state", "error", "_managed")

    def __init__(self, name, index, device, sup, scope, version):
        self.name = name
        self.index = index
        self.device = device
        self.sup = sup
        self.scope = scope
        self.version = version
        self.state = self.SERVING
        self.error: Optional[BaseException] = None
        self._managed = False    # a fleet op (swap/scale) owns it now

    def routable(self) -> bool:
        if self.state != self.SERVING:
            return False
        b = self.sup.batcher
        if b._draining or b._stop.is_set() or b._dead is not None:
            return False
        br = self.sup.breaker
        return br is None or br.state != CircuitBreaker.OPEN


# ---------------------------------------------------------------- router
class FleetRouter:
    """Least-projected-wait router over a :class:`FleetController`'s
    serving replicas. ``submit()`` mirrors the single-replica
    ``ServingSupervisor.submit`` contract (same typed errors, same
    :class:`~mxnet_tpu.serving.ServingFuture`), plus ``fut.replica`` /
    ``fut.version`` breadcrumbs naming who served it."""

    def __init__(self, controller: "FleetController"):
        self._c = controller

    def submit(self, *args, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = None):
        """Route one request to the serving replica with the lowest
        projected queue wait; a replica that sheds at admission
        (:class:`~mxnet_tpu.serving.Overloaded`) is skipped and the
        next-emptiest tried. Raises ``Overloaded(reason="fleet")``
        when no replica is available or every one rejected — an
        accepted request lands on exactly one replica; a rejected one
        fails typed, never hangs."""
        c = self._c
        c.poll()
        rows = DynamicBatcher._rows_of(args)
        cands = []
        with c._lock:
            for rep in c._replicas:
                if not rep.routable():
                    continue
                est = rep.sup.batcher.estimated_wait_s(rows)
                cands.append((est if est is not None else 0.0,
                              rep.index, rep))
        cands.sort(key=lambda t: (t[0], t[1]))
        if not cands:
            c.stats["rejected_fleet"] += 1
            raise Overloaded(
                "fleet: no replica can take traffic (all draining, "
                "recovering, retired, or breaker-open) — retry after "
                "backoff", reason="fleet")
        last: Optional[BaseException] = None
        for est, _idx, rep in cands:
            # chaos-harness seam: routing-decision fault injection
            # (error/delay/revoke), targetable per replica via @ctx
            fault_point("serving.route", "before", ctx=rep.name)
            try:
                fut = rep.sup.submit(*args, deadline_ms=deadline_ms,
                                     timeout=timeout)
            except (Overloaded, ServingShutdown) as e:
                last = e
                continue
            fut.replica = rep.name
            fut.version = rep.version
            c.stats["routed"] += 1
            c._m_routed.inc(label=rep.name)
            c._m_queue_wait.observe(est)
            c._note_wait(est)
            if c.autoscale:
                c.maybe_scale()
            return fut
        c.stats["rejected_fleet"] += 1
        raise Overloaded(
            f"fleet: every serving replica rejected the request "
            f"(last: {type(last).__name__}: {last})",
            reason="fleet") from last


# ---------------------------------------------------------------- controller
class FleetController:
    """Run N independent serving replicas behind one router::

        def build():                          # deterministic!
            net = make_net()                  # params materialized
            return mx.serving.CompiledPredictor(net,
                                               bucket_sizes=(1, 2, 4))

        fleet = mx.serving.FleetController(build, example=(x_row,),
                                           replicas=3, max_batch=4)
        fut = fleet.router.submit(x)          # least-wait routing
        out = fut.result(30)
        fleet.swap_weights(ckpt_root)         # zero-downtime rollout
        fleet.close()

    ``build()`` must construct a FRESH CompiledPredictor; the
    controller wraps it in ``jax.default_device(<replica device>)`` so
    each replica's params land on its own device, and every replica
    after the first warms its AOT buckets from the shared
    ``MXNET_COMPILE_CACHE``.

    ``start=False`` puts every batcher in manual-drive mode (tests):
    drive :meth:`pump`, inject ``clock=``; failover restarts run
    inline with no backoff sleep.
    """

    def __init__(self, build: Callable,
                 example: Optional[Sequence] = None, *,
                 replicas: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 depth: Optional[int] = None,
                 inflight: Optional[int] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 autoscale: bool = False,
                 backoff_base: float = 0.05, backoff_max: float = 2.0,
                 clock: Callable[[], float] = time.perf_counter,
                 start: bool = True):
        from ..elastic import detect as _detect
        from ..parallel import dist as _dist
        self._build = build
        self._example = tuple(example) if example is not None else None
        self._batcher_kwargs = dict(max_batch=max_batch,
                                    timeout_ms=timeout_ms, depth=depth,
                                    inflight=inflight)
        self._clock = clock
        self._start = bool(start)
        self._detect = _detect
        self._dist = _dist
        self._backoff_base = float(backoff_base)
        self._backoff_max = float(backoff_max)
        self._lock = mx_rlock("serving.fleet")
        self._scale_lock = mx_lock("serving.fleet.scale")
        self._replicas: List[_Replica] = []
        self._next_idx = 0
        self.version = 0         # current weight version (swaps bump it)
        self.autoscale = bool(autoscale)
        self.queue_wait_ewma: Optional[float] = None
        self.events: List[FleetEvent] = []
        self.stats = {"routed": 0, "rejected_fleet": 0, "failovers": 0,
                      "requeued": 0, "failed_requeues": 0, "restarts": 0,
                      "swaps": 0, "scale_ups": 0, "scale_downs": 0,
                      "drains": 0}
        t = _telemetry()
        reg = t.registry()
        self._m_replicas = reg.gauge(t.names.FLEET_REPLICAS,
                                     label_key="state")
        self._m_routed = reg.counter(t.names.FLEET_ROUTED,
                                     label_key="replica")
        self._m_restarts = reg.counter(t.names.FLEET_RESTARTS)
        self._m_swaps = reg.counter(t.names.FLEET_SWAPS)
        self._m_scale = reg.counter(t.names.FLEET_SCALE_EVENTS,
                                    label_key="direction")
        self._m_queue_wait = reg.histogram(t.names.FLEET_QUEUE_WAIT)
        n = fleet_replicas() if replicas is None else max(1, int(replicas))
        devs = _dist.available_devices()
        if n > len(devs):
            raise MXNetError(
                f"fleet: {n} replicas requested but only {len(devs)} "
                "device(s) available (MXNET_FLEET_REPLICAS)")
        self.min_replicas = fleet_min_replicas() if min_replicas is None \
            else max(1, int(min_replicas))
        mx_r = fleet_max_replicas() if max_replicas is None \
            else int(max_replicas)
        self.max_replicas = mx_r if mx_r > 0 else len(devs)
        for _ in range(n):
            dev = self._pick_device()
            if dev is None:      # pragma: no cover - guarded above
                raise MXNetError("fleet: ran out of devices mid-spawn")
            self._spawn(dev)
        self.router = FleetRouter(self)

    # ---------------- introspection ----------------
    @property
    def replicas(self) -> List[_Replica]:
        return list(self._replicas)

    def live(self) -> List[_Replica]:
        """Replicas currently able to take routed traffic."""
        with self._lock:
            return [r for r in self._replicas if r.routable()]

    def describe(self) -> dict:
        """Structured fleet snapshot (the ``diagnose --fleet``
        panel)."""
        with self._lock:
            reps = [{
                "name": r.name, "state": r.state,
                "device": str(r.device), "version": r.version,
                "breaker": r.sup.breaker.state
                if r.sup.breaker else None,
                "queued": r.sup.batcher._queue.qsize()
                + len(r.sup.batcher._forming),
                "inflight": len(r.sup.batcher._window),
                "est_wait_s": r.sup.batcher.estimated_wait_s(1),
                "error": f"{type(r.error).__name__}: {r.error}"
                if r.error else None,
            } for r in self._replicas]
        return {"replicas": reps, "version": self.version,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "autoscale": self.autoscale,
                "queue_wait_ewma_s": self.queue_wait_ewma,
                "stats": dict(self.stats),
                "events": [repr(e) for e in self.events[-16:]]}

    # ---------------- lifecycle plumbing ----------------
    def _event(self, kind: str, replica: Optional[str],
               detail: Optional[dict] = None):
        ev = FleetEvent(kind, replica, self._clock(), detail)
        if len(self.events) < 1024:
            self.events.append(ev)
        _LOG.info("fleet: %s%s %s", kind,
                  f" [{replica}]" if replica else "", ev.detail)

    def _update_gauge(self):
        counts = {s: 0 for s in _Replica.STATES}
        for r in self._replicas:
            counts[r.state] += 1
        for s, c in counts.items():
            self._m_replicas.set(float(c), label=s)

    def _note_wait(self, est: float):
        w = max(0.0, float(est))
        self.queue_wait_ewma = w if self.queue_wait_ewma is None \
            else 0.2 * w + 0.8 * self.queue_wait_ewma

    def _pick_device(self, exclude: Optional[_Replica] = None):
        """A device no live replica occupies (revoked devices are
        already excluded by ``available_devices()``)."""
        used = {r.device for r in self._replicas
                if r is not exclude and r.state != _Replica.RETIRED}
        for d in self._dist.available_devices():
            if d not in used:
                return d
        return None

    def _pinned_build(self, device) -> Callable:
        base = self._build
        def build():
            import jax
            with jax.default_device(device):
                return base()
        return build

    def _make_supervisor(self, device, scope) -> ServingSupervisor:
        return ServingSupervisor(
            self._pinned_build(device), example=self._example,
            drain_on_preemption=scope, clock=self._clock,
            start=self._start, **self._batcher_kwargs)

    def _wire(self, rep: _Replica):
        """Point the replica's failure handling at the FLEET (device
        loss fails over to survivors instead of rebuilding in place)
        and tag its chaos-fault context with the replica name."""
        b = rep.sup.batcher
        b.on_batch_failure = partial(self._on_replica_failure, rep)
        b.fault_ctx = rep.name

    def _spawn(self, device) -> _Replica:
        idx = self._next_idx
        self._next_idx += 1
        name = f"replica-{idx}"
        scope = f"fleet/{name}"
        self._detect.notice(scope).clear()
        sup = self._make_supervisor(device, scope)
        rep = _Replica(name, idx, device, sup, scope, self.version)
        self._wire(rep)
        with self._lock:
            self._replicas.append(rep)
            self._update_gauge()
        self._event("spawn", name, {"device": str(device)})
        return rep

    # ---------------- replica-loss failover ----------------
    def _on_replica_failure(self, rep: _Replica, reqs, exc,
                            seam: str) -> bool:
        """Batcher hook (runs on that replica's dispatcher thread).
        ``transient`` retries in place via the replica's own
        supervisor; ``device_lost`` fails over to the survivors;
        ``fatal``/``oom``/``stall`` propagate to the futures."""
        cause = self._detect.classify(exc)
        if cause == "transient":
            return rep.sup._retry_transient(list(reqs), exc, seam)
        if cause != "device_lost":
            return False
        self._failover(rep, list(reqs), exc, seam)
        return True

    def _failover(self, rep: _Replica, reqs, exc, seam: str):
        """Move the lost replica's riders + queue onto the survivors
        exactly once, stop its batcher, and restart it on a spare
        device (background thread in threaded mode; inline with no
        backoff in manual mode)."""
        with self._lock:
            rep.state = _Replica.RECOVERING
            rep.error = exc
            self._update_gauge()
            self._event("replica_lost", rep.name, {
                "seam": seam, "error": f"{type(exc).__name__}: {exc}"})
            rep.sup.breaker.trip("fleet failover")
            self._detect.maybe_record_device_lost(exc, f"fleet {seam}")
            b = rep.sup.batcher
            riders = list(reqs) + b.abandon_inflight()
            # the handler runs on the dispatcher thread — the single
            # owner of _forming — so stealing the backlog here is safe
            b._drain_queue()
            riders += b._forming
            b._forming = []
            seen, uniq = set(), []
            for r in riders:
                if id(r) not in seen:
                    seen.add(id(r))
                    uniq.append(r)
            uniq.sort(key=lambda r: r.t_submit)
            b._stop.set()        # the dispatch loop exits after we return
            moved = failed = 0
            for r in uniq:
                if r.future.done():
                    continue
                if r.requeues >= 1:
                    self.stats["failed_requeues"] += 1
                    r.future._fail(MXNetError(
                        f"serving request lost to repeated device "
                        f"failure (re-enqueued {r.requeues}x): "
                        f"{type(exc).__name__}: {exc}"))
                    failed += 1
                    continue
                target = self._pick_target(rep, r.rows)
                if target is None:
                    self.stats["failed_requeues"] += 1
                    r.future._fail(Overloaded(
                        "fleet failover: no surviving replica could "
                        "absorb this request", reason="fleet"))
                    failed += 1
                    continue
                r.requeues += 1
                r.future._rearm()
                r.future.replica = target.name
                r.future.version = target.version
                try:
                    target.sup.batcher._queue.put_nowait(r)
                except queue.Full:
                    self.stats["failed_requeues"] += 1
                    r.future._fail(Overloaded(
                        "fleet failover: survivor queue saturated",
                        reason="fleet"))
                    failed += 1
                    continue
                moved += 1
            # belt-and-braces anti-hang: anything that raced into the
            # dead queue after the steal fails typed, like close()
            b._fail_pending(ServingShutdown(
                "replica lost; request arrived during fleet failover"))
            self.stats["failovers"] += 1
            self.stats["requeued"] += moved
            self._event("failover", rep.name, {
                "seam": seam, "moved": moved, "failed": failed})
        if self._start:
            threading.Thread(
                target=self._restart_replica, args=(rep, exc),
                name=f"mx-fleet-restart-{rep.name}",
                daemon=True).start()
        else:
            self._restart_replica(rep, exc, backoff=False)

    def _pick_target(self, rep: _Replica, rows: int) -> \
            Optional[_Replica]:
        """Surviving replica with the lowest projected wait (failover
        bypasses the router: the riders were already admitted once)."""
        best, best_w = None, None
        for r in self._replicas:
            if r is rep or not r.routable():
                continue
            w = r.sup.batcher.estimated_wait_s(rows)
            w = 0.0 if w is None else w
            if best_w is None or w < best_w:
                best, best_w = r, w
        return best

    def _restart_replica(self, rep: _Replica, exc,
                         backoff: bool = True):
        """Bounded-retry restart on a spare device: a fresh supervisor
        (fresh predictor, AOT buckets warm from the compile cache,
        fresh breaker). ``fatal``/``oom`` build failures retire the
        replica with the error recorded — they propagate, not loop."""
        attempts = max(1, fleet_restart_retries() + 1)
        delay = self._backoff_base
        last = exc
        for i in range(attempts):
            try:
                dev = self._pick_device(exclude=rep)
                if dev is None:
                    raise MXNetError(
                        "fleet: no spare device to restart "
                        f"{rep.name} on (world shrank)")
                self._detect.notice(rep.scope).clear()
                with _tguard.allow_transfers("fleet replica restart"):
                    sup = self._make_supervisor(dev, rep.scope)
                with self._lock:
                    rep.sup = sup
                    rep.device = dev
                    rep.version = self.version
                    rep.error = None
                    self._wire(rep)
                    rep.state = _Replica.SERVING
                    self.stats["restarts"] += 1
                    self._m_restarts.inc()
                    self._update_gauge()
                    self._event("restart", rep.name, {
                        "device": str(dev), "attempt": i + 1})
                return
            except Exception as e:   # noqa: BLE001 - classify below
                last = e
                cause = self._detect.classify(e)
                _LOG.warning(
                    "fleet: restart of %s attempt %d/%d failed "
                    "(%s: %s; cause=%s)", rep.name, i + 1, attempts,
                    type(e).__name__, e, cause)
                if cause in ("fatal", "oom"):
                    break        # propagate: a retry cannot cure these
                if backoff and delay > 0:
                    time.sleep(delay)
                    delay = min(self._backoff_max, delay * 2)
        with self._lock:
            rep.state = _Replica.RETIRED
            rep.error = last
            self._update_gauge()
            self._event("restart_failed", rep.name, {
                "error": f"{type(last).__name__}: {last}",
                "attempts": attempts})

    # ---------------- drain / retire / preemption ----------------
    def drain_then_retire(self, rep: _Replica,
                          cause: str = "manual"):
        """Flush the replica's accepted requests (old weights keep
        serving them), reject new, retire it from the rotation."""
        with self._lock:
            if rep.state == _Replica.RETIRED:
                return
            rep.state = _Replica.DRAINING
            rep._managed = True
            self._update_gauge()
            self._event("drain", rep.name, {"cause": cause})
        try:
            rep.sup.drain()
            self.stats["drains"] += 1
        finally:
            with self._lock:
                rep.state = _Replica.RETIRED
                rep._managed = False
                self._update_gauge()
                self._event("retire", rep.name, {"cause": cause})

    def poll(self):
        """Cheap housekeeping (the router calls it per submit): notice
        replicas whose dispatcher self-drained on a scoped preemption
        notice or died, and — in manual-drive mode — run the scoped
        drain on the calling thread."""
        to_drain: List[_Replica] = []
        with self._lock:
            for rep in self._replicas:
                if rep._managed:
                    continue
                b = rep.sup.batcher
                if rep.state == _Replica.SERVING:
                    if b._dead is not None:
                        rep.state = _Replica.RETIRED
                        rep.error = b._dead
                        self._update_gauge()
                        self._event("replica_dead", rep.name, {
                            "error": f"{type(b._dead).__name__}: "
                                     f"{b._dead}"})
                    elif b._stop.is_set():
                        rep.state = _Replica.RETIRED
                        self._update_gauge()
                        self._event("preempt_retire", rep.name, {})
                    elif b._draining:
                        rep.state = _Replica.DRAINING
                        self._update_gauge()
                        self._event("preempt_drain", rep.name, {})
                    elif not self._start and \
                            self._detect.notice(rep.scope).requested():
                        to_drain.append(rep)
                elif rep.state == _Replica.DRAINING and \
                        b._stop.is_set():
                    rep.state = _Replica.RETIRED
                    self._update_gauge()
                    self._event("preempt_retire", rep.name, {})
        for rep in to_drain:
            self.drain_then_retire(rep, cause="preemption")

    # ---------------- autoscaling ----------------
    def maybe_scale(self) -> Optional[str]:
        """One autoscale decision from the fleet queue-wait EWMA:
        ``"up"`` (spawned a replica), ``"down"`` (drained + retired
        the emptiest), or None. Never blocks the caller on a
        concurrent scale op (try-lock)."""
        ewma = self.queue_wait_ewma
        if ewma is None:
            return None
        if not self._scale_lock.acquire(blocking=False):
            return None
        try:
            with self._lock:
                serving = [r for r in self._replicas
                           if r.state == _Replica.SERVING]
            n = len(serving)
            if ewma >= fleet_scale_up_wait_s() and \
                    n < self.max_replicas:
                dev = self._pick_device()
                if dev is None:
                    return None
                rep = self._spawn(dev)
                self.stats["scale_ups"] += 1
                self._m_scale.inc(label="up")
                self._event("scale_up", rep.name, {
                    "queue_wait_ewma_s": ewma, "serving": n + 1})
                return "up"
            down = fleet_scale_down_wait_s()
            if down > 0 and ewma <= down and n > self.min_replicas:
                empt = min(
                    serving,
                    key=lambda r:
                    (r.sup.batcher.estimated_wait_s(0) or 0.0,
                     -r.index))
                self.stats["scale_downs"] += 1
                self._m_scale.inc(label="down")
                self._event("scale_down", empt.name, {
                    "queue_wait_ewma_s": ewma, "serving": n - 1})
                self.drain_then_retire(empt, cause="scale_down")
                return "down"
            return None
        finally:
            self._scale_lock.release()

    # ---------------- zero-downtime weight rollout ----------------
    def swap_weights(self, checkpoint: str) -> dict:
        """Rolling weight swap: validate the checkpoint FIRST (a
        corrupt one aborts typed with every replica still serving the
        OLD weights), then walk the serving replicas one at a time —
        drain (accepted requests finish on the old weights), swap the
        params in place (the AOT executables take params by handle: no
        recompile), warm-probe, return to rotation. At most one weight
        version of skew is in flight at any instant; zero accepted
        requests are dropped.

        ``checkpoint`` — a committed step directory, or a checkpoint
        root (its newest VALID step is used). Raises
        :class:`~mxnet_tpu.checkpoint.CheckpointCorruptError` /
        ``MXNetError`` on a bad checkpoint; a per-replica apply
        failure rolls that replica back to the old weights and
        re-raises with the fleet still serving."""
        from ..checkpoint import atomic as _atomic
        path = self._resolve_checkpoint(checkpoint)
        _atomic.validate_checkpoint(path)    # corrupt -> typed abort
        arrays, manifest = _atomic.read_checkpoint(path)
        params = {k: v for k, v in arrays.items()
                  if k.startswith("param/")}
        if not params:
            raise MXNetError(
                f"fleet swap: checkpoint {path} holds no param/ "
                "arrays — nothing to roll out")
        array_meta = {k: v for k, v in manifest["arrays"].items()
                      if k.startswith("param/")}
        new_version = self.version + 1
        t0 = time.monotonic()
        self._event("swap_begin", None, {
            "path": path, "version": new_version})
        swapped = 0
        for rep in list(self._replicas):
            if rep.state != _Replica.SERVING:
                continue
            self._swap_one(rep, params, array_meta,
                           manifest.get("meta", {}), new_version)
            swapped += 1
        self.version = new_version
        self.stats["swaps"] += 1
        self._m_swaps.inc()
        self._event("swap_complete", None, {
            "version": new_version, "replicas": swapped,
            "duration_s": time.monotonic() - t0})
        return {"version": new_version, "replicas": swapped,
                "path": path}

    @staticmethod
    def _resolve_checkpoint(checkpoint: str) -> str:
        from ..checkpoint import atomic as _atomic
        p = os.path.abspath(checkpoint)
        if os.path.exists(os.path.join(p, _atomic.MANIFEST)):
            return p
        found = _atomic.latest_valid(p)
        if found is None:
            raise MXNetError(
                f"fleet swap: no valid checkpoint under {p}")
        return found[1]

    def _swap_one(self, rep: _Replica, params, array_meta, meta,
                  new_version: int):
        from ..checkpoint import state as _ckstate
        with self._lock:
            rep.state = _Replica.DRAINING
            rep._managed = True
            self._update_gauge()
            self._event("swap_drain", rep.name,
                        {"version": new_version})
        try:
            rep.sup.drain()      # accepted traffic finishes on OLD
            net = getattr(rep.sup.predictor, "_net", None)
            if net is None:
                raise MXNetError(
                    f"fleet swap: {rep.name}'s predictor exposes no "
                    "bound net to load weights into")
            plist = list(net.collect_params().values())
            snapshot = [(p, p._data) for p in plist]
            try:
                with _tguard.allow_transfers("fleet weight swap"):
                    st = _ckstate.TrainState(dict(params), dict(meta),
                                             dict(array_meta))
                    _ckstate.apply_train_state(st, net=net,
                                               strict=True)
            except BaseException:
                for p, d in snapshot:    # old weights, bit-exact
                    p._data = d
                raise
            self._respawn_batcher(rep)
            with _tguard.allow_transfers("fleet swap warm probe"):
                self._warm_probe(rep)
            with self._lock:
                rep.version = new_version
                rep.state = _Replica.SERVING
                rep._managed = False
                self._update_gauge()
                self._event("swap_done", rep.name,
                            {"version": new_version})
        except BaseException as e:
            try:
                self._respawn_batcher(rep)
            except Exception:    # pragma: no cover - defensive
                _LOG.warning("fleet: batcher respawn after aborted "
                             "swap failed", exc_info=True)
            with self._lock:
                rep.state = _Replica.SERVING
                rep._managed = False
                self._update_gauge()
                self._event("swap_abort", rep.name, {
                    "error": f"{type(e).__name__}: {e}"})
            raise

    def _respawn_batcher(self, rep: _Replica):
        """Fresh batcher after a drain (the drained one is closed);
        the admission EWMA carries over — same predictor, same
        service time."""
        sup = rep.sup
        old = sup._batcher
        b = DynamicBatcher(sup.predictor, clock=self._clock,
                           start=self._start, **self._batcher_kwargs)
        b.breaker = sup.breaker
        b.on_batch_retired = sup._on_batch_retired
        b.drain_check = self._detect.notice(rep.scope).requested
        if old is not None and old._ewma_service is not None:
            b._ewma_service = old._ewma_service
        sup._batcher = b
        sup._closed = False
        self._wire(rep)

    def _warm_probe(self, rep: _Replica):
        """One blocking forward through the swapped predictor before
        it rejoins the rotation — the first routed request must not
        pay a surprise, and a weight/arch mismatch surfaces HERE
        (typed, rolled back by the caller) instead of on traffic."""
        if self._example is None:
            return
        import jax
        pred = rep.sup.predictor
        padded, _rows = pred.pad_to_bucket(*self._example)
        res = pred.predict(*padded)
        jax.block_until_ready([
            l._data for l in jax.tree_util.tree_leaves(
                res, is_leaf=lambda t: isinstance(t, NDArray))
            if isinstance(l, NDArray)])

    # ---------------- manual drive + shutdown ----------------
    def pump(self, force: bool = False) -> bool:
        """Manual-drive (``start=False``): one dispatch pass + window
        retire on every serving replica, then :meth:`poll`. Returns
        whether any replica dispatched a batch."""
        did = False
        for rep in list(self._replicas):
            if rep.state != _Replica.SERVING:
                continue
            b = rep.sup.batcher
            if b._stop.is_set() or b._dead is not None:
                continue
            if b.process_once(force=force):
                did = True
            if rep.state == _Replica.SERVING and len(b._window):
                b._window.drain()
                b._m_inflight.set(0)
        self.poll()
        return did

    def drain(self):
        """Graceful fleet shutdown: drain every replica (accepted
        requests flush), retire all."""
        for rep in list(self._replicas):
            if rep.state in (_Replica.SERVING, _Replica.DRAINING):
                self.drain_then_retire(rep, cause="shutdown")

    def close(self):
        for rep in list(self._replicas):
            if rep.state != _Replica.RETIRED:
                try:
                    rep.sup.close()
                except Exception:    # pragma: no cover - defensive
                    _LOG.warning("fleet: close of %s failed", rep.name,
                                 exc_info=True)
                rep.state = _Replica.RETIRED
        with self._lock:
            self._update_gauge()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
