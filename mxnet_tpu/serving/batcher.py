"""Dynamic request batching (``serving.DynamicBatcher``).

The request-scheduler half of the serving engine (the dispatch
discipline of arXiv:1605.08695 applied to inference): concurrent
single-request traffic is coalesced into the bucketed batch shapes the
compile cache keys on, so N clients hit one compiled program per bucket
instead of N one-row dispatches.

Mechanics:

- **Bounded queue.** ``submit()`` enqueues a request (any leading-dim
  row count) into a bounded queue (``MXNET_SERVING_QUEUE_DEPTH``) and
  returns a :class:`ServingFuture`; a full queue blocks the caller —
  backpressure, not unbounded memory.
- **Coalesce until full or stale.** The dispatcher gathers requests
  until ``MXNET_SERVING_MAX_BATCH`` rows are waiting or the OLDEST
  waiting request has aged ``MXNET_SERVING_BATCH_TIMEOUT_MS`` — the
  classic batching-delay/latency trade. The coalesced rows are padded
  to the predictor's next shape bucket (zero rows; the valid-row count
  is the mask) and dispatched as ONE program call.
- **Pipelined decode.** Each micro-batch's async outputs ride a
  bounded :class:`~mxnet_tpu.engine.DispatchWindow` — the host keeps
  forming + dispatching batch N+1 while the device runs batch N, and
  only blocks on the OLDEST in-flight batch when the window fills; the
  device never idles between micro-batches. The window retire is the
  ONE blessed host sync of the serving hot loop (request latency is
  recorded there); client-side ``future.result()`` reads are the
  response sync, outside the hot region.
- **Observability.** ``mx_serving_*`` series through the telemetry
  catalog: requests/batches counters, queue-depth and in-flight
  gauges, batch-occupancy and request-latency histograms
  (docs/OBSERVABILITY.md).

Deterministic testing: inject ``clock=`` and construct with
``start=False``, then drive :meth:`process_once` by hand — the
timeout/full flush decisions consult only the injected clock
(tests/test_serving.py pins the semantics with a fake clock).
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time
from functools import partial
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ..analysis import guard as _tguard
from ..base import MXNetError
from ..engine import DispatchWindow
from ..ndarray.ndarray import NDArray

__all__ = ["DynamicBatcher", "ServingFuture", "max_batch_rows",
           "batch_timeout_s", "queue_depth"]

_LOG = logging.getLogger("mxnet_tpu.serving")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


def max_batch_rows(default: int = 32) -> int:
    """Max coalesced rows per dispatch: autotune override >
    ``MXNET_SERVING_MAX_BATCH`` > ``default`` (the
    ``serving.max_batch`` tunable — tuning/space.py)."""
    from ..tuning import space as _tspace
    found, v = _tspace.get_override("serving.max_batch")
    if not found:
        v = os.environ.get("MXNET_SERVING_MAX_BATCH", str(default))
    try:
        return max(1, int(v))
    except (TypeError, ValueError):
        return default


def batch_timeout_s(default_ms: float = 2.0) -> float:
    """How long the oldest waiting request may age before a partial
    batch flushes, as SECONDS: autotune override >
    ``MXNET_SERVING_BATCH_TIMEOUT_MS`` (milliseconds) > ``default_ms``
    (the ``serving.batch_timeout_ms`` tunable — tuning/space.py)."""
    from ..tuning import space as _tspace
    found, v = _tspace.get_override("serving.batch_timeout_ms")
    if not found:
        v = os.environ.get("MXNET_SERVING_BATCH_TIMEOUT_MS",
                           str(default_ms))
    try:
        v = float(v)
    except (TypeError, ValueError):
        v = default_ms
    return max(0.0, v) / 1e3


def _register_tunables():
    """Serving coalescing tunables, declared next to the env knobs they
    share a seam with: the batch cap trades occupancy against padding
    waste, the linger trades batching delay against fill. Both are
    dispatch policy — per-request RESULTS are bit-identical at any
    setting (batched-vs-single parity is pinned in tests) — so the
    autotuner may sweep them freely."""
    from ..tuning.space import Tunable, register
    register(Tunable(
        "serving.max_batch", default=32, grid=(8, 16, 32, 64),
        env="MXNET_SERVING_MAX_BATCH", parse=int,
        valid=lambda v, _c: int(v) >= 1,
        seam="serving.batcher.max_batch_rows() -> DynamicBatcher "
             "coalescing cap (must fit the predictor's bucket ladder)",
        scope="serving",
        doc="max coalesced request rows per serving micro-batch"))
    register(Tunable(
        "serving.batch_timeout_ms", default=2.0,
        grid=(0.5, 1.0, 2.0, 5.0, 10.0),
        env="MXNET_SERVING_BATCH_TIMEOUT_MS", parse=float,
        valid=lambda v, _c: float(v) >= 0.0,
        seam="serving.batcher.batch_timeout_s() -> oldest-request "
             "linger before a partial flush",
        scope="serving",
        doc="max age (ms) of the oldest waiting request before a "
            "partial micro-batch flushes"))


try:
    _register_tunables()
except Exception:    # pragma: no cover - tuning must never break serving
    _LOG.debug("serving tunable registration failed", exc_info=True)


def queue_depth(default: int = 1024) -> int:
    """``MXNET_SERVING_QUEUE_DEPTH``: bounded request-queue capacity
    (a full queue blocks ``submit`` — backpressure)."""
    try:
        v = int(os.environ.get("MXNET_SERVING_QUEUE_DEPTH", str(default)))
    except ValueError:
        return default
    return max(1, v)


@partial(jax.jit, static_argnums=2)
def _row_slice(x, off, n):
    """One compiled slicer per (shape, n): the offset is traced, so
    slicing responses out of a batch costs no per-offset compiles."""
    return jax.lax.dynamic_slice_in_dim(x, off, n, axis=0)


def _build_response(out_leaves, out_tree, off, rows, bucket):
    """Client-side response materialization (``ServingFuture.result``):
    block on the micro-batch's outputs — the response sync, on the
    client's own thread — then slice this request's rows out. Leaves
    without the batch's leading dim (scalars, per-model aux) pass
    through whole."""
    jax.block_until_ready([l._data for l in out_leaves
                           if isinstance(l, NDArray)])
    sliced = [
        NDArray(_row_slice(l._data, off, rows))
        if isinstance(l, NDArray) and getattr(l._data, "ndim", 0) >= 1
        and int(l._data.shape[0]) == bucket else l
        for l in out_leaves]
    return jax.tree_util.tree_unflatten(out_tree, sliced)


class ServingFuture:
    """Handle for one submitted request's result.

    Resolves when its micro-batch DISPATCHES (with a lazy builder over
    the batch's async outputs); :meth:`result` blocks until the device
    finished the batch — the response-side sync, on the client's
    thread, outside the serving hot region — then slices this
    request's rows out. The per-request slice dispatch happens on the
    CLIENT thread, keeping the dispatcher's hot loop to one program
    call per micro-batch."""

    __slots__ = ("_ev", "_build", "_out", "_err")

    def __init__(self):
        self._ev = threading.Event()
        self._build = None
        self._out = None
        self._err = None

    def _resolve(self, build):
        self._build = build
        self._ev.set()

    def _fail(self, err):
        self._err = err
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the response is computed and return it (the
        net's output structure, NDArray leaves, this request's rows
        only). Raises the dispatch error if its batch failed."""
        if not self._ev.wait(timeout):
            raise MXNetError(
                f"serving request not completed within {timeout}s "
                "(batcher stopped? queue saturated?)")
        if self._err is not None:
            raise self._err
        if self._out is None:
            self._out = self._build()
        return self._out


class _Request:
    __slots__ = ("args", "rows", "t_submit", "future")

    def __init__(self, args, rows, t_submit, future):
        self.args = args
        self.rows = rows
        self.t_submit = t_submit
        self.future = future


class DynamicBatcher:
    """Coalesce concurrent requests into one predictor's shape buckets.

        pred = mx.serving.CompiledPredictor(net)
        with mx.serving.DynamicBatcher(pred) as b:
            futs = [b.submit(x_i) for x_i in requests]
            outs = [f.result() for f in futs]

    Thread-safe ``submit``; one background dispatcher thread owns the
    hot loop (``start=False`` for manual :meth:`process_once` driving).
    """

    def __init__(self, predictor, max_batch: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 depth: Optional[int] = None,
                 inflight: Optional[int] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 start: bool = True):
        self._predictor = predictor
        self.max_batch = max_batch_rows() if max_batch is None \
            else max(1, int(max_batch))
        if self.max_batch > predictor.bucket_sizes[-1]:
            raise MXNetError(
                f"max_batch={self.max_batch} exceeds the predictor's "
                f"largest shape bucket ({predictor.bucket_sizes[-1]})")
        self._timeout_s = batch_timeout_s() if timeout_ms is None \
            else max(0.0, float(timeout_ms)) / 1e3
        self._clock = clock
        self._queue: "queue.Queue[_Request]" = queue.Queue(
            maxsize=queue_depth() if depth is None else max(1, int(depth)))
        self._forming: List[_Request] = []
        self._inflight: dict = {}   # tag -> (futures, t_submits)
        self._window = DispatchWindow(max_inflight=inflight,
                                      what="serving micro-batch",
                                      sync_fn=self._retire_sync)
        self._batch_no = 0
        self._stop = threading.Event()
        self._thread = None
        self.stats = {"requests": 0, "batches": 0, "rows": 0,
                      "padded_rows": 0, "flush_full": 0,
                      "flush_timeout": 0, "flush_idle": 0,
                      "flush_force": 0, "errors": 0}
        t = _telemetry()
        reg = t.registry()
        self._m_requests = reg.counter(t.names.SERVING_REQUESTS)
        self._m_batches = reg.counter(t.names.SERVING_BATCHES)
        self._m_queue = reg.gauge(t.names.SERVING_QUEUE_DEPTH)
        self._m_inflight = reg.gauge(t.names.SERVING_INFLIGHT)
        self._m_occupancy = reg.histogram(t.names.SERVING_OCCUPANCY)
        self._m_latency = reg.histogram(t.names.SERVING_LATENCY)
        if start:
            self._thread = threading.Thread(
                target=self._serve_loop, name="mx-serving-batcher",
                daemon=True)
            self._thread.start()

    # ---------------- client surface ----------------
    def submit(self, *args, timeout: float = 120.0) -> ServingFuture:
        """Enqueue one request (array leaves with a leading row dim,
        typically one row) and return its future. Blocks when the
        bounded queue is full (backpressure)."""
        if self._stop.is_set():
            raise MXNetError("DynamicBatcher is closed")
        rows = self._rows_of(args)
        if rows > self.max_batch:
            raise MXNetError(
                f"request of {rows} rows exceeds max_batch="
                f"{self.max_batch} (MXNET_SERVING_MAX_BATCH)")
        fut = ServingFuture()
        req = _Request(args, rows, self._clock(), fut)
        try:
            self._queue.put(req, timeout=timeout)
        except queue.Full:
            raise MXNetError(
                f"serving queue saturated ({self._queue.maxsize} "
                "requests) — the service is overloaded "
                "(MXNET_SERVING_QUEUE_DEPTH)")
        self.stats["requests"] += 1
        self._m_requests.inc()
        self._m_queue.set(self._queue.qsize() + len(self._forming))
        return fut

    @property
    def batch_fill(self) -> Optional[float]:
        """Valid rows / dispatched bucket rows — the padding waste
        ratio (1.0 = every dispatched row was a real request)."""
        total = self.stats["rows"] + self.stats["padded_rows"]
        return self.stats["rows"] / total if total else None

    def flush(self):
        """Dispatch whatever is waiting (regardless of age/size) and
        retire every in-flight micro-batch."""
        while self.process_once(force=True):
            pass
        self._window.drain()
        self._m_inflight.set(0)

    def close(self):
        """Stop the dispatcher thread, flush remaining requests, drain
        the window. Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        self.flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---------------- batching core ----------------
    @staticmethod
    def _rows_of(args) -> int:
        for l in jax.tree_util.tree_leaves(
                args, is_leaf=lambda t: isinstance(t, NDArray)):
            d = l._data if isinstance(l, NDArray) else l
            if getattr(d, "ndim", 0) >= 1:
                return int(d.shape[0])
        raise MXNetError("serving request has no array leaf with a "
                         "leading batch dim")

    def _forming_rows(self) -> int:
        return sum(r.rows for r in self._forming)

    def _drain_queue(self, cap: Optional[int] = None):
        while cap is None or self._forming_rows() < cap:
            try:
                self._forming.append(self._queue.get_nowait())
            except queue.Empty:
                break

    def _take_batch(self) -> List[_Request]:
        batch, rows = [], 0
        while self._forming and rows + self._forming[0].rows \
                <= self.max_batch:
            r = self._forming.pop(0)
            batch.append(r)
            rows += r.rows
        return batch

    def process_once(self, force: bool = False) -> bool:
        """Manual-drive: pull waiting requests and dispatch ONE batch
        if the flush condition holds (>= max_batch rows waiting, the
        oldest request older than the batch timeout, or ``force``).
        Returns whether a batch was dispatched. Uses only the injected
        clock — fake-clock tests drive the semantics deterministically."""
        self._drain_queue()
        if not self._forming:
            return False
        reason = None
        if self._forming_rows() >= self.max_batch:
            reason = "full"
        elif self._clock() - self._forming[0].t_submit >= self._timeout_s:
            reason = "timeout"
        elif force:
            reason = "force"
        if reason is None:
            return False
        self._dispatch(self._take_batch(), reason)
        return True

    def _serve_loop(self):
        """Work-conserving coalescing: requests gather until the batch
        is full or the oldest waiting request has aged past the
        timeout — but an IDLE device short-circuits the linger (when
        nothing is queued and nothing is in flight, batching delay
        buys no occupancy, it only adds latency), and the linger
        itself is spent draining the in-flight window, so the device
        never idles between micro-batches."""
        idle_poll = max(self._timeout_s, 0.005)
        while not self._stop.is_set():
            try:
                if not self._forming:
                    # idle: retire finished in-flight batches so their
                    # latencies are recorded and errors surface, then
                    # block for the next request
                    if len(self._window):
                        self._window.drain()
                        self._m_inflight.set(0)
                    try:
                        self._forming.append(
                            self._queue.get(timeout=idle_poll))
                    except queue.Empty:
                        continue
                # coalesce until full, stale, or device-idle
                deadline = self._forming[0].t_submit + self._timeout_s
                while self._forming_rows() < self.max_batch:
                    try:
                        self._forming.append(self._queue.get_nowait())
                        continue
                    except queue.Empty:
                        pass
                    if not len(self._window):
                        break    # device idle: ship what we have NOW
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        break
                    # the device is busy with an in-flight batch: spend
                    # the linger retiring it (the retire IS the wait)
                    self._window.drain()
                    self._m_inflight.set(0)
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        break
                    try:
                        self._forming.append(
                            self._queue.get(timeout=remaining))
                    except queue.Empty:
                        break
                if self._forming_rows() >= self.max_batch:
                    reason = "full"
                elif self._clock() - self._forming[0].t_submit \
                        >= self._timeout_s:
                    reason = "timeout"
                else:
                    reason = "idle"   # device idle cut the linger short
                self._dispatch(self._take_batch(), reason)
            except Exception as e:   # keep serving after a bad batch
                _LOG.warning("serving dispatch failed (%s: %s)",
                             type(e).__name__, e, exc_info=True)
                self.stats["errors"] += 1

    # ---------------- dispatch ----------------
    def _dispatch(self, reqs: List[_Request], reason: str):
        """One micro-batch: concatenate + pad to bucket, ONE predictor
        call, resolve each request's future with its (lazy) row slice,
        push the async outputs into the pipeline window. The whole body
        is a transfer-guard hot region — nothing in here may sync; the
        window retire is the one blessed wait."""
        if not reqs:
            return
        try:
            with _tguard.hot_scope("DynamicBatcher.dispatch"):
                self._dispatch_inner(reqs, reason)
        except BaseException as e:
            for r in reqs:
                if not r.future.done():
                    r.future._fail(e)
            raise

    def _dispatch_inner(self, reqs: List[_Request], reason: str):
        pred = self._predictor
        rows = sum(r.rows for r in reqs)
        bucket = pred.bucket_for(rows)
        n_pos = len(reqs[0].args)
        if any(len(r.args) != n_pos for r in reqs):
            raise MXNetError("coalesced requests disagree on argument "
                             "count — one model signature per batcher")
        batch_args = tuple(
            self._concat_pad([r.args[i] for r in reqs], rows, bucket)
            for i in range(n_pos))
        outs = pred.predict(*batch_args)
        out_leaves, out_tree = jax.tree_util.tree_flatten(
            outs, is_leaf=lambda t: isinstance(t, NDArray))
        off = 0
        for r in reqs:
            r.future._resolve(partial(
                _build_response, out_leaves, out_tree, off, r.rows,
                bucket))
            off += r.rows
        self._batch_no += 1
        tag = self._batch_no
        self._inflight[tag] = tuple(r.t_submit for r in reqs)
        payload = (tag, tuple(l._data for l in out_leaves
                              if isinstance(l, NDArray)))
        self.stats["batches"] += 1
        self.stats["rows"] += rows
        self.stats["padded_rows"] += bucket - rows
        self.stats["flush_" + reason] += 1
        self._m_batches.inc()
        self._m_occupancy.observe(rows / bucket)
        self._window.push(payload, tag=tag)
        self._m_inflight.set(len(self._window))
        self._m_queue.set(self._queue.qsize() + len(self._forming))

    @staticmethod
    def _concat_pad(leaves, rows: int, bucket: int):
        """Concatenate one argument position across requests and pad
        to the bucket — async device ops only, no host sync."""
        datas = [l._data if isinstance(l, NDArray) else jnp.asarray(l)
                 for l in leaves]
        if bucket > rows:
            datas.append(jnp.zeros((bucket - rows,)
                                   + tuple(datas[0].shape[1:]),
                                   datas[0].dtype))
        out = datas[0] if len(datas) == 1 else jnp.concatenate(datas,
                                                               axis=0)
        return NDArray(out)

    def _retire_sync(self, payload):
        """Window sync hook: block on the micro-batch's outputs (the
        blessed retire), then record each rider request's end-to-end
        latency."""
        tag, datas = payload
        jax.block_until_ready(list(datas))
        t_submits = self._inflight.pop(tag, ())
        now = self._clock()
        for t0 in t_submits:
            self._m_latency.observe(max(0.0, now - t0))
