"""Dynamic request batching (``serving.DynamicBatcher``).

The request-scheduler half of the serving engine (the dispatch
discipline of arXiv:1605.08695 applied to inference): concurrent
single-request traffic is coalesced into the bucketed batch shapes the
compile cache keys on, so N clients hit one compiled program per bucket
instead of N one-row dispatches.

Mechanics:

- **Bounded queue.** ``submit()`` enqueues a request (any leading-dim
  row count) into a bounded queue (``MXNET_SERVING_QUEUE_DEPTH``) and
  returns a :class:`ServingFuture`; a full queue blocks the caller up
  to ``MXNET_SERVING_QUEUE_TIMEOUT_MS`` and then sheds with a typed
  :class:`~mxnet_tpu.serving.Overloaded` — backpressure, not unbounded
  memory, and never a bare ``queue.Full``.
- **Deadlines + admission control.** ``submit(deadline_ms=)`` (default
  ``MXNET_SERVING_DEADLINE_MS``) rides the queue with the request;
  expired requests are dropped AT DEQUEUE (never padded/dispatched)
  with a typed :class:`~mxnet_tpu.serving.DeadlineExceeded`, and under
  ``MXNET_SERVING_SHED=deadline`` a request whose projected queue wait
  (EWMA micro-batch service time x batches ahead) already exceeds its
  deadline is rejected at ``submit`` — accepted requests keep their
  p99 instead of everyone timing out (docs/SERVING.md "Resilient
  serving").
- **Coalesce until full or stale.** The dispatcher gathers requests
  until ``MXNET_SERVING_MAX_BATCH`` rows are waiting or the OLDEST
  waiting request has aged ``MXNET_SERVING_BATCH_TIMEOUT_MS`` — the
  classic batching-delay/latency trade. The coalesced rows are padded
  to the predictor's next shape bucket (zero rows; the valid-row count
  is the mask) and dispatched as ONE program call.
- **Pipelined decode.** Each micro-batch's async outputs ride a
  bounded :class:`~mxnet_tpu.engine.DispatchWindow` — the host keeps
  forming + dispatching batch N+1 while the device runs batch N, and
  only blocks on the OLDEST in-flight batch when the window fills; the
  device never idles between micro-batches. The window retire is the
  ONE blessed host sync of the serving hot loop (request latency is
  recorded there); client-side ``future.result()`` reads are the
  response sync, outside the hot region.
- **Failure containment.** A dispatch or retire failure reaches the
  ``on_batch_failure`` hook (a :class:`~mxnet_tpu.serving
  .ServingSupervisor` classifies and recovers — device loss rebuilds
  the predictor and re-enqueues the affected requests exactly once);
  without a handler the affected futures fail with the error. A dead
  dispatcher thread or a ``close()`` with requests still pending fails
  every pending future with a typed :class:`~mxnet_tpu.serving
  .ServingShutdown` — an accepted request NEVER hangs. :meth:`drain`
  is the graceful path: reject new, flush forming + in-flight, close.
- **Observability.** ``mx_serving_*`` series through the telemetry
  catalog: requests/batches/rejected/deadline-missed counters,
  queue-depth and in-flight gauges, batch-occupancy/request-latency/
  drain-duration histograms (docs/OBSERVABILITY.md).

Deterministic testing: inject ``clock=`` and construct with
``start=False``, then drive :meth:`process_once` by hand — the
timeout/full flush decisions AND the deadline/admission arithmetic
consult only the injected clock (tests/test_serving.py and
tests/test_serving_resilience.py pin the semantics with a fake clock).
"""
from __future__ import annotations

import logging
import os
import queue
import threading
import time
from functools import partial
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ..analysis import guard as _tguard
from ..analysis.threads import mx_condition, mx_lock, register_queue
from ..base import MXNetError
from ..engine import DispatchWindow
from ..ndarray.ndarray import NDArray
from ..testing.faults import fault_point
from .resilience import (DeadlineExceeded, Overloaded, ServingShutdown,
                         default_deadline_ms, queue_timeout_s, shed_mode)

__all__ = ["DynamicBatcher", "ServingFuture", "max_batch_rows",
           "batch_timeout_s", "queue_depth"]

_LOG = logging.getLogger("mxnet_tpu.serving")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


def max_batch_rows(default: int = 32) -> int:
    """Max coalesced rows per dispatch: autotune override >
    ``MXNET_SERVING_MAX_BATCH`` > ``default`` (the
    ``serving.max_batch`` tunable — tuning/space.py)."""
    from ..tuning import space as _tspace
    found, v = _tspace.get_override("serving.max_batch")
    if not found:
        v = os.environ.get("MXNET_SERVING_MAX_BATCH", str(default))
    try:
        return max(1, int(v))
    except (TypeError, ValueError):
        return default


def batch_timeout_s(default_ms: float = 2.0) -> float:
    """How long the oldest waiting request may age before a partial
    batch flushes, as SECONDS: autotune override >
    ``MXNET_SERVING_BATCH_TIMEOUT_MS`` (milliseconds) > ``default_ms``
    (the ``serving.batch_timeout_ms`` tunable — tuning/space.py)."""
    from ..tuning import space as _tspace
    found, v = _tspace.get_override("serving.batch_timeout_ms")
    if not found:
        v = os.environ.get("MXNET_SERVING_BATCH_TIMEOUT_MS",
                           str(default_ms))
    try:
        v = float(v)
    except (TypeError, ValueError):
        v = default_ms
    return max(0.0, v) / 1e3


def _register_tunables():
    """Serving coalescing tunables, declared next to the env knobs they
    share a seam with: the batch cap trades occupancy against padding
    waste, the linger trades batching delay against fill. Both are
    dispatch policy — per-request RESULTS are bit-identical at any
    setting (batched-vs-single parity is pinned in tests) — so the
    autotuner may sweep them freely."""
    from ..tuning.space import Tunable, register
    register(Tunable(
        "serving.max_batch", default=32, grid=(8, 16, 32, 64),
        env="MXNET_SERVING_MAX_BATCH", parse=int,
        valid=lambda v, _c: int(v) >= 1,
        seam="serving.batcher.max_batch_rows() -> DynamicBatcher "
             "coalescing cap (must fit the predictor's bucket ladder)",
        scope="serving",
        doc="max coalesced request rows per serving micro-batch"))
    register(Tunable(
        "serving.batch_timeout_ms", default=2.0,
        grid=(0.5, 1.0, 2.0, 5.0, 10.0),
        env="MXNET_SERVING_BATCH_TIMEOUT_MS", parse=float,
        valid=lambda v, _c: float(v) >= 0.0,
        seam="serving.batcher.batch_timeout_s() -> oldest-request "
             "linger before a partial flush",
        scope="serving",
        doc="max age (ms) of the oldest waiting request before a "
            "partial micro-batch flushes"))


try:
    _register_tunables()
except Exception:    # pragma: no cover - tuning must never break serving
    _LOG.debug("serving tunable registration failed", exc_info=True)


def queue_depth(default: int = 1024) -> int:
    """``MXNET_SERVING_QUEUE_DEPTH``: bounded request-queue capacity
    (a full queue blocks ``submit`` up to the queue timeout, then
    sheds — backpressure)."""
    try:
        v = int(os.environ.get("MXNET_SERVING_QUEUE_DEPTH", str(default)))
    except ValueError:
        return default
    return max(1, v)


@partial(jax.jit, static_argnums=2)
def _row_slice(x, off, n):
    """One compiled slicer per (shape, n): the offset is traced, so
    slicing responses out of a batch costs no per-offset compiles."""
    return jax.lax.dynamic_slice_in_dim(x, off, n, axis=0)


def _build_response(out_leaves, out_tree, off, rows, bucket):
    """Client-side response materialization (``ServingFuture.result``):
    block on the micro-batch's outputs — the response sync, on the
    client's own thread — then slice this request's rows out. Leaves
    without the batch's leading dim (scalars, per-model aux) pass
    through whole."""
    jax.block_until_ready([l._data for l in out_leaves
                           if isinstance(l, NDArray)])
    sliced = [
        NDArray(_row_slice(l._data, off, rows))
        if isinstance(l, NDArray) and getattr(l._data, "ndim", 0) >= 1
        and int(l._data.shape[0]) == bucket else l
        for l in out_leaves]
    return jax.tree_util.tree_unflatten(out_tree, sliced)


class ServingFuture:
    """Handle for one submitted request's result.

    Resolves when its micro-batch DISPATCHES (with a lazy builder over
    the batch's async outputs); :meth:`result` blocks until the device
    finished the batch — the response-side sync, on the client's
    thread, outside the serving hot region — then slices this
    request's rows out. The per-request slice dispatch happens on the
    CLIENT thread, keeping the dispatcher's hot loop to one program
    call per micro-batch.

    Under a :class:`~mxnet_tpu.serving.ServingSupervisor` the future
    is RE-ARMABLE: when the request's micro-batch is lost to a device
    failure, recovery re-enqueues the request and the future resolves
    again against the re-dispatched batch (the ``_epoch`` counter
    disambiguates); a client already blocked in :meth:`result` rides
    through the recovery instead of observing the poisoned buffers.
    Terminal failures arrive as typed errors — never a hang.
    """

    __slots__ = ("_cv", "_build", "_out", "_err", "_done", "_epoch",
                 "_supervised", "replica", "version")

    def __init__(self):
        self._cv = mx_condition("serving.future")
        self._build = None
        self._out = None
        self._err = None
        self._done = False
        self._epoch = 0
        self._supervised = False
        # routing breadcrumbs (FleetRouter tags these): which replica
        # served the request and that replica's weight version
        self.replica: Optional[str] = None
        self.version: Optional[int] = None

    def _resolve(self, build):
        with self._cv:
            self._build, self._err, self._done = build, None, True
            self._cv.notify_all()

    def _fail(self, err):
        with self._cv:
            if self._done and self._err is None and self._out is not None:
                return           # a delivered result is final
            self._err, self._done = err, True
            self._cv.notify_all()

    def _rearm(self):
        """Recovery: put the future back in flight (pending its
        re-dispatched micro-batch)."""
        with self._cv:
            self._build = self._err = self._out = None
            self._done = False
            self._epoch += 1
            self._cv.notify_all()

    def done(self) -> bool:
        with self._cv:
            return self._done

    def _cv_wait(self, deadline) -> bool:
        """One bounded wait tick under the cv; False when the client
        timeout passed."""
        if deadline is None:
            self._cv.wait()
            return True
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        self._cv.wait(remaining)
        return True

    def result(self, timeout: Optional[float] = None):
        """Block until the response is computed and return it (the
        net's output structure, NDArray leaves, this request's rows
        only). Raises the typed serving error
        (:class:`~mxnet_tpu.serving.DeadlineExceeded` /
        :class:`~mxnet_tpu.serving.Overloaded` /
        :class:`~mxnet_tpu.serving.ServingShutdown`) or the dispatch
        error if its batch failed terminally."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cv:
                while not self._done:
                    if not self._cv_wait(deadline):
                        raise MXNetError(
                            f"serving request not completed within "
                            f"{timeout}s (batcher stopped? queue "
                            "saturated?)")
                if self._err is not None:
                    raise self._err
                if self._out is not None:
                    return self._out
                epoch, build = self._epoch, self._build
            try:
                out = build()
            except BaseException as e:
                if self._await_redispatch(epoch, e, deadline):
                    continue
                raise
            with self._cv:
                if self._epoch == epoch and self._err is None:
                    self._out = out
            return out

    def _await_redispatch(self, epoch, exc, deadline) -> bool:
        """The resolved response's builder failed on the client thread.
        When the batcher is supervised and the failure is
        recovery-class, the supervisor is seeing the SAME failure at
        the retire seam — wait (bounded by the client timeout) for it
        to either re-arm this future or fail it typed, instead of
        surfacing the poisoned-buffer error."""
        if not self._supervised:
            return False
        try:
            from ..elastic import detect
            if detect.classify(exc) not in ("device_lost", "transient"):
                return False
        except Exception:        # pragma: no cover - defensive
            return False
        with self._cv:
            while self._epoch == epoch and self._done \
                    and self._err is None:
                if not self._cv_wait(deadline):
                    return False
            return True


class _Request:
    __slots__ = ("args", "rows", "t_submit", "future", "deadline",
                 "retries", "requeues")

    def __init__(self, args, rows, t_submit, future, deadline=None):
        self.args = args
        self.rows = rows
        self.t_submit = t_submit
        self.future = future
        self.deadline = deadline   # absolute, on the batcher clock
        self.retries = 0           # transient re-dispatches so far
        self.requeues = 0          # device-loss re-enqueues so far


class DynamicBatcher:
    """Coalesce concurrent requests into one predictor's shape buckets.

        pred = mx.serving.CompiledPredictor(net)
        with mx.serving.DynamicBatcher(pred) as b:
            futs = [b.submit(x_i) for x_i in requests]
            outs = [f.result() for f in futs]

    Thread-safe ``submit``; one background dispatcher thread owns the
    hot loop (``start=False`` for manual :meth:`process_once` driving).

    Resilience hooks (wired by :class:`~mxnet_tpu.serving
    .ServingSupervisor`; all default off): ``breaker`` (a
    :class:`~mxnet_tpu.serving.CircuitBreaker` consulted at admission),
    ``on_batch_failure(reqs, exc, seam) -> bool`` (classify + recover;
    True = requests were re-enqueued/failed by the handler),
    ``on_batch_retired()`` (success feedback closing a half-open
    breaker), ``drain_check()`` (polled by the dispatch loop; True
    initiates a graceful drain — the preemption-notice bridge).
    """

    def __init__(self, predictor, max_batch: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 depth: Optional[int] = None,
                 inflight: Optional[int] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 start: bool = True):
        self._predictor = predictor
        self.max_batch = max_batch_rows() if max_batch is None \
            else max(1, int(max_batch))
        if self.max_batch > predictor.bucket_sizes[-1]:
            raise MXNetError(
                f"max_batch={self.max_batch} exceeds the predictor's "
                f"largest shape bucket ({predictor.bucket_sizes[-1]})")
        self._timeout_s = batch_timeout_s() if timeout_ms is None \
            else max(0.0, float(timeout_ms)) / 1e3
        self._clock = clock
        self._queue: "queue.Queue[_Request]" = queue.Queue(
            maxsize=queue_depth() if depth is None else max(1, int(depth)))
        register_queue("serving.batcher", self._queue)  # thread dumps
        self._forming: List[_Request] = []
        self._inflight: dict = {}   # tag -> (requests, t_dispatch)
        self._window = DispatchWindow(max_inflight=inflight,
                                      what="serving micro-batch",
                                      sync_fn=self._retire_sync)
        self._batch_no = 0
        self._stop = threading.Event()
        self._drain_now = threading.Event()
        self._thread = None
        self._draining = False
        self._dead: Optional[BaseException] = None
        # seed the admission EWMA from the predictor's warmup() timing
        # (when it ran): deadline shedding projects from request 1
        # instead of admitting blindly until the first retire lands
        self._ewma_service: Optional[float] = self._service_seed(predictor)
        # resilience hooks (ServingSupervisor wires these)
        self.breaker = None
        self.on_batch_failure = None
        self.on_batch_retired = None
        self.drain_check = None
        # chaos-harness context tag: the FleetController sets this to
        # the replica name so point@ctx fault rules target one replica
        self.fault_ctx: Optional[str] = None
        self.stats = {"requests": 0, "batches": 0, "rows": 0,
                      "padded_rows": 0, "flush_full": 0,
                      "flush_timeout": 0, "flush_idle": 0,
                      "flush_force": 0, "errors": 0, "rejected": 0,
                      "deadline_missed": 0, "requeued": 0,
                      "recovered_batches": 0, "shutdown_failed": 0}
        # stats is written from both the client surface (submit/reject)
        # and the dispatcher thread; every mutation holds this lock so
        # concurrent submits never lose increments
        self._stats_mu = mx_lock("serving.batcher.stats")
        t = _telemetry()
        reg = t.registry()
        self._m_requests = reg.counter(t.names.SERVING_REQUESTS)
        self._m_batches = reg.counter(t.names.SERVING_BATCHES)
        self._m_queue = reg.gauge(t.names.SERVING_QUEUE_DEPTH)
        self._m_inflight = reg.gauge(t.names.SERVING_INFLIGHT)
        self._m_occupancy = reg.histogram(t.names.SERVING_OCCUPANCY)
        self._m_latency = reg.histogram(t.names.SERVING_LATENCY)
        self._m_rejected = reg.counter(t.names.SERVING_REJECTED,
                                       label_key="reason")
        self._m_deadline = reg.counter(t.names.SERVING_DEADLINE_MISSED)
        self._m_drain = reg.histogram(t.names.SERVING_DRAIN_SECONDS)
        if start:
            self._thread = threading.Thread(
                target=self._serve_loop, name="mx-serving-batcher",
                daemon=True)
            self._thread.start()

    # ---------------- client surface ----------------
    def _reject(self, reason: str, msg: str):
        with self._stats_mu:
            self.stats["rejected"] += 1
        self._m_rejected.inc(label=reason)
        raise Overloaded(msg, reason=reason)

    def submit(self, *args, deadline_ms: Optional[float] = None,
               timeout: Optional[float] = None) -> ServingFuture:
        """Enqueue one request (array leaves with a leading row dim,
        typically one row) and return its future.

        ``deadline_ms`` — this request's latency budget (default
        ``MXNET_SERVING_DEADLINE_MS``; <= 0 disables): expired-in-queue
        requests fail with :class:`~mxnet_tpu.serving.DeadlineExceeded`
        and are never dispatched, and ``MXNET_SERVING_SHED=deadline``
        sheds at admission when the projected wait already exceeds it.
        ``timeout`` — max blocking wait on a full queue (default
        ``MXNET_SERVING_QUEUE_TIMEOUT_MS``); a still-full queue sheds
        with :class:`~mxnet_tpu.serving.Overloaded` (reason
        ``queue``). Never raises a bare ``queue.Full``."""
        fault_point("serving.admit", "before", ctx=self.fault_ctx)
        if self._dead is not None:
            raise ServingShutdown(
                f"serving dispatcher thread died "
                f"({type(self._dead).__name__}: {self._dead}); "
                "the batcher cannot accept requests")
        if self._stop.is_set():
            raise ServingShutdown("DynamicBatcher is closed")
        if self._draining:
            self._reject("draining",
                         "serving drain in progress (preemption/"
                         "shutdown) — new requests are rejected while "
                         "accepted ones flush")
        if self.breaker is not None and not self.breaker.allow():
            self._reject("breaker",
                         "serving circuit breaker is open (recovery in "
                         "progress) — fast-failing instead of queueing "
                         "into a dead device")
        rows = self._rows_of(args)
        if rows > self.max_batch:
            raise MXNetError(
                f"request of {rows} rows exceeds max_batch="
                f"{self.max_batch} (MXNET_SERVING_MAX_BATCH)")
        if deadline_ms is None:
            deadline_ms = default_deadline_ms()
        elif deadline_ms <= 0:
            deadline_ms = None
        now = self._clock()
        deadline = None if deadline_ms is None \
            else now + deadline_ms / 1e3
        mode = shed_mode()
        if mode == "deadline" and deadline is not None:
            est = self.estimated_wait_s(rows)
            if est is not None and now + est > deadline:
                self._reject(
                    "deadline",
                    f"projected queue wait {est * 1e3:.1f} ms exceeds "
                    f"the request deadline ({deadline_ms:.0f} ms) — "
                    "shedding at admission so accepted requests keep "
                    "their p99 (MXNET_SERVING_SHED=deadline)")
        fut = ServingFuture()
        fut._supervised = self.on_batch_failure is not None
        req = _Request(args, rows, now, fut, deadline=deadline)
        block_s = queue_timeout_s() if timeout is None \
            else max(0.0, float(timeout))
        try:
            if mode == "queue" or block_s <= 0:
                self._queue.put_nowait(req)
            else:
                self._queue.put(req, timeout=block_s)
        except queue.Full:
            self._reject(
                "queue",
                f"serving queue saturated ({self._queue.maxsize} "
                "requests) — the service is overloaded "
                "(MXNET_SERVING_QUEUE_DEPTH / "
                "MXNET_SERVING_QUEUE_TIMEOUT_MS)")
        if self._stop.is_set() and not fut.done():
            # the batcher closed the instant we enqueued: the drain's
            # final fail-pending sweep may already have run, so nobody
            # will ever pop this request. Fail the future (typed, for
            # any holder) and raise like the up-front closed check —
            # an accepted request can never hang, and a router retries
            # the next replica (the sched-harness submit-vs-drain
            # invariant).
            err = ServingShutdown(
                "serving closed while this request was being accepted "
                "— it was never dispatched")
            fut._fail(err)
            raise err
        with self._stats_mu:
            self.stats["requests"] += 1
        self._m_requests.inc()
        self._m_queue.set(self._queue.qsize() + len(self._forming))
        return fut

    @staticmethod
    def _service_seed(predictor) -> Optional[float]:
        seed = getattr(predictor, "service_time_seed_s", None)
        try:
            seed = float(seed) if seed is not None else None
        except (TypeError, ValueError):
            return None
        return seed if seed and seed > 0 else None

    def estimated_wait_s(self, rows: int = 0) -> Optional[float]:
        """Projected wait until a request submitted NOW would retire:
        (waiting rows incl. its own, bucketed at ``max_batch``) plus
        the in-flight micro-batches, times the EWMA micro-batch
        service time. The EWMA is seeded from the predictor's
        ``warmup()`` execution timing when available; None only when
        neither a warmup seed nor a retire has happened yet (no
        estimate — admit; the queue bound still protects memory)."""
        ewma = self._ewma_service
        if ewma is None:
            return None
        waiting = self._queue.qsize() + self._forming_rows() + rows
        batches = (waiting + self.max_batch - 1) // self.max_batch \
            + len(self._window)
        return batches * ewma

    @property
    def batch_fill(self) -> Optional[float]:
        """Valid rows / dispatched bucket rows — the padding waste
        ratio (1.0 = every dispatched row was a real request)."""
        with self._stats_mu:
            total = self.stats["rows"] + self.stats["padded_rows"]
            return self.stats["rows"] / total if total else None

    def flush(self):
        """Dispatch whatever is waiting (regardless of age/size) and
        retire every in-flight micro-batch."""
        while self.process_once(force=True):
            pass
        self._window.drain()
        self._m_inflight.set(0)

    def drain(self):
        """Graceful shutdown: flip to drain mode (new submits shed with
        :class:`~mxnet_tpu.serving.Overloaded` reason ``draining``),
        flush every forming + in-flight request, then close — no
        accepted request is silently lost. The flush runs on the
        dispatcher thread when one exists (single owner of the forming
        list); duration lands in ``mx_serving_drain_seconds``.
        Idempotent."""
        t0 = self._clock()
        # monotonic latch (False -> True only, never cleared); both the
        # dispatcher's preemption drain and this public path may set it
        # concurrently and either order is correct, so the race is
        # benign by construction
        self._draining = True  # mx-lint: allow=MXA008
        if self._thread is not None:
            self._drain_now.set()
            self._thread.join(timeout=60.0)
            self._thread = None
            self._stop.set()
            # the in-loop drain flushed + failed leftovers + observed
            # the histogram; this is the belt-and-braces pass for a
            # thread that exited through a non-drain path
            self._fail_pending(ServingShutdown(
                "serving drained before this request could be "
                "dispatched"))
            return
        if self._stop.is_set():
            return               # already closed
        try:
            while self.process_once(force=True):
                pass
            self._window.drain()
            self._m_inflight.set(0)
        finally:
            self._stop.set()
            self._fail_pending(ServingShutdown(
                "serving drained before this request could be "
                "dispatched"))
            self._m_drain.observe(max(0.0, self._clock() - t0))

    def close(self):
        """Stop the dispatcher thread, flush remaining requests, drain
        the window; anything still undispatchable fails with a typed
        :class:`~mxnet_tpu.serving.ServingShutdown` (never a hung
        future). Idempotent."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        try:
            if self._dead is None:
                self.flush()
        finally:
            self._fail_pending(ServingShutdown(
                "DynamicBatcher closed with this request still "
                "pending (dispatch failed or dispatcher unavailable)"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---------------- batching core ----------------
    @staticmethod
    def _rows_of(args) -> int:
        for l in jax.tree_util.tree_leaves(
                args, is_leaf=lambda t: isinstance(t, NDArray)):
            d = l._data if isinstance(l, NDArray) else l
            if getattr(d, "ndim", 0) >= 1:
                return int(d.shape[0])
        raise MXNetError("serving request has no array leaf with a "
                         "leading batch dim")

    def _forming_rows(self) -> int:
        return sum(r.rows for r in self._forming)

    def _drain_queue(self, cap: Optional[int] = None):
        while cap is None or self._forming_rows() < cap:
            try:
                self._forming.append(self._queue.get_nowait())
            except queue.Empty:
                break

    def _expire_forming(self):
        """Drop requests whose deadline already expired while they
        queued: each fails with a typed ``DeadlineExceeded`` and is
        NEVER padded into a bucket or dispatched — the device's work
        all lands inside someone's budget."""
        if not self._forming:
            return
        now = self._clock()
        kept = []
        for r in self._forming:
            if r.deadline is not None and now >= r.deadline:
                with self._stats_mu:
                    self.stats["deadline_missed"] += 1
                self._m_deadline.inc()
                r.future._fail(DeadlineExceeded(
                    f"request deadline expired after "
                    f"{(now - r.t_submit) * 1e3:.1f} ms in queue — "
                    "dropped at dequeue, never dispatched "
                    "(MXNET_SERVING_DEADLINE_MS / submit(deadline_ms=))"))
            else:
                kept.append(r)
        self._forming = kept

    def _fail_pending(self, err: BaseException):
        """Fail every request still waiting (queue + forming) with a
        typed error — the anti-hang guarantee on shutdown/dispatcher
        death."""
        self._drain_queue()
        pending, self._forming = self._forming, []
        for r in pending:
            if not r.future.done():
                with self._stats_mu:
                    self.stats["shutdown_failed"] += 1
                r.future._fail(err)
        self._m_queue.set(0)

    def requeue(self, reqs: List[_Request]):
        """Re-enqueue recovered requests at the FRONT of the forming
        list (supervisor recovery path, dispatcher thread). Original
        submit times are preserved, so the age-based flush re-dispatches
        them promptly; original deadlines still apply."""
        if not reqs:
            return
        # dispatcher-thread-only path: the supervisor's recovery hook
        # runs on the thread that owns the forming list (the docstring
        # contract), so this is single-owner, not a cross-thread write
        self._forming[0:0] = list(reqs)  # mx-lint: allow=MXA008
        with self._stats_mu:
            self.stats["requeued"] += len(reqs)
        self._m_queue.set(self._queue.qsize() + len(self._forming))

    def rebind(self, predictor):
        """Swap in a rebuilt predictor (supervisor recovery); the
        coalescing cap must still fit the new bucket ladder."""
        if self.max_batch > predictor.bucket_sizes[-1]:
            raise MXNetError(
                f"max_batch={self.max_batch} exceeds the rebuilt "
                f"predictor's largest shape bucket "
                f"({predictor.bucket_sizes[-1]})")
        self._predictor = predictor
        if self._ewma_service is None:
            self._ewma_service = self._service_seed(predictor)

    def abandon_inflight(self) -> List[_Request]:
        """Discard every in-flight micro-batch WITHOUT syncing (work
        dispatched to a lost device would only raise again) and return
        the requests that rode them — the supervisor re-enqueues or
        fails each exactly once."""
        self._window.abandon()
        recs = list(self._inflight.values())
        self._inflight.clear()
        self._m_inflight.set(0)
        return [r for reqs, _t in recs for r in reqs]

    def _take_batch(self) -> List[_Request]:
        batch, rows = [], 0
        while self._forming and rows + self._forming[0].rows \
                <= self.max_batch:
            r = self._forming.pop(0)
            batch.append(r)
            rows += r.rows
        return batch

    def process_once(self, force: bool = False) -> bool:
        """Manual-drive: pull waiting requests, drop expired ones, and
        dispatch ONE batch if the flush condition holds (>= max_batch
        rows waiting, the oldest request older than the batch timeout,
        or ``force``). Returns whether a batch was dispatched. Uses
        only the injected clock — fake-clock tests drive the semantics
        deterministically."""
        self._drain_queue()
        self._expire_forming()
        if not self._forming:
            return False
        reason = None
        if self._forming_rows() >= self.max_batch:
            reason = "full"
        elif self._clock() - self._forming[0].t_submit >= self._timeout_s:
            reason = "timeout"
        elif force:
            reason = "force"
        if reason is None:
            return False
        self._dispatch(self._take_batch(), reason)
        return True

    def _serve_loop(self):
        """Dispatcher thread body: the work-conserving coalescing loop,
        wrapped so the thread CANNOT die silently — an escaping error
        fails every pending future with a typed ``ServingShutdown``
        instead of leaving clients blocked forever."""
        try:
            self._serve_loop_inner()
        except BaseException as e:   # noqa: BLE001 - anti-hang contract
            self._dead = e
            _LOG.error(
                "serving dispatcher thread DIED (%s: %s); failing "
                "pending futures with ServingShutdown",
                type(e).__name__, e, exc_info=True)
            try:
                self._fail_pending(ServingShutdown(
                    f"serving dispatcher thread died: "
                    f"{type(e).__name__}: {e}"))
            except Exception:    # pragma: no cover - defensive
                _LOG.warning("failing pending futures failed",
                             exc_info=True)

    def _serve_loop_inner(self):
        """Work-conserving coalescing: requests gather until the batch
        is full or the oldest waiting request has aged past the
        timeout — but an IDLE device short-circuits the linger (when
        nothing is queued and nothing is in flight, batching delay
        buys no occupancy, it only adds latency), and the linger
        itself is spent draining the in-flight window, so the device
        never idles between micro-batches."""
        idle_poll = max(self._timeout_s, 0.005)
        while not self._stop.is_set():
            if self._drain_now.is_set() or self._wants_drain():
                self._drain_in_loop()
                return
            try:
                if not self._forming:
                    # idle: retire finished in-flight batches so their
                    # latencies are recorded and errors surface, then
                    # block for the next request
                    if len(self._window):
                        self._window.drain()
                        self._m_inflight.set(0)
                    try:
                        self._forming.append(
                            self._queue.get(timeout=idle_poll))
                    except queue.Empty:
                        continue
                # coalesce until full, stale, or device-idle
                deadline = self._forming[0].t_submit + self._timeout_s
                while self._forming_rows() < self.max_batch:
                    try:
                        self._forming.append(self._queue.get_nowait())
                        continue
                    except queue.Empty:
                        pass
                    if not len(self._window):
                        break    # device idle: ship what we have NOW
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        break
                    # the device is busy with an in-flight batch: spend
                    # the linger retiring it (the retire IS the wait)
                    self._window.drain()
                    self._m_inflight.set(0)
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        break
                    try:
                        self._forming.append(
                            self._queue.get(timeout=remaining))
                    except queue.Empty:
                        break
                if self._forming_rows() >= self.max_batch:
                    reason = "full"
                elif self._clock() - self._forming[0].t_submit \
                        >= self._timeout_s:
                    reason = "timeout"
                else:
                    reason = "idle"   # device idle cut the linger short
                self._expire_forming()
                if not self._forming:
                    continue
                self._dispatch(self._take_batch(), reason)
            except Exception as e:   # keep serving after a bad batch
                # a deferred failure surfacing at a window drain (not
                # inside _retire_sync's own guard) still reaches the
                # recovery handler: the in-flight records know which
                # requests rode the poisoned batches
                if self._handle_batch_failure([], e, "dispatcher"):
                    continue
                _LOG.warning("serving dispatch failed (%s: %s)",
                             type(e).__name__, e, exc_info=True)
                with self._stats_mu:
                    self.stats["errors"] += 1

    def _wants_drain(self) -> bool:
        """Poll the drain hook (the ServingSupervisor's preemption-
        notice bridge) — never lets a hook error kill the loop."""
        if self.drain_check is None or self._draining:
            return False
        try:
            return bool(self.drain_check())
        except Exception:        # pragma: no cover - defensive
            return False

    def _drain_in_loop(self):
        """Preemption-notice drain, on the dispatcher thread: reject
        new, flush forming + in-flight, fail anything undispatchable
        typed, stop."""
        t0 = self._clock()
        self._draining = True
        _LOG.warning(
            "serving: drain requested — rejecting new requests and "
            "flushing %d waiting + %d in-flight",
            self._queue.qsize() + len(self._forming), len(self._window))
        try:
            while self.process_once(force=True):
                pass
            self._window.drain()
            self._m_inflight.set(0)
        except Exception:        # pragma: no cover - defensive
            _LOG.warning("serving drain flush failed", exc_info=True)
        self._fail_pending(ServingShutdown(
            "serving drained (preemption) before this request could "
            "be dispatched"))
        self._stop.set()
        # second sweep AFTER the stop flag: a submit that raced its
        # enqueue between the first sweep and the flag would otherwise
        # sit in a stopped batcher forever
        self._fail_pending(ServingShutdown(
            "serving drained (preemption) before this request could "
            "be dispatched"))
        self._m_drain.observe(max(0.0, self._clock() - t0))

    # ---------------- dispatch ----------------
    def _handle_batch_failure(self, reqs, exc, seam: str) -> bool:
        """Route a batch failure to the resilience handler (the
        ServingSupervisor). True = the requests were re-enqueued or
        failed by the handler; False = apply the default path."""
        handler = self.on_batch_failure
        if handler is None:
            return False
        try:
            handled = bool(handler(reqs, exc, seam))
        except Exception:        # pragma: no cover - defensive
            _LOG.error("serving failure handler raised; falling back "
                       "to failing the batch", exc_info=True)
            return False
        if handled:
            with self._stats_mu:
                self.stats["recovered_batches"] += 1
        return handled

    def _dispatch(self, reqs: List[_Request], reason: str):
        """One micro-batch: concatenate + pad to bucket, ONE predictor
        call, resolve each request's future with its (lazy) row slice,
        push the async outputs into the pipeline window. The whole body
        is a transfer-guard hot region — nothing in here may sync; the
        window retire is the one blessed wait."""
        if not reqs:
            return
        try:
            with _tguard.hot_scope("DynamicBatcher.dispatch"):
                self._dispatch_inner(reqs, reason)
        except BaseException as e:
            if self._handle_batch_failure(reqs, e, "dispatch"):
                return
            for r in reqs:
                if not r.future.done():
                    r.future._fail(e)
            raise

    def _dispatch_inner(self, reqs: List[_Request], reason: str):
        pred = self._predictor
        rows = sum(r.rows for r in reqs)
        bucket = pred.bucket_for(rows)
        n_pos = len(reqs[0].args)
        if any(len(r.args) != n_pos for r in reqs):
            raise MXNetError("coalesced requests disagree on argument "
                             "count — one model signature per batcher")
        batch_args = tuple(
            self._concat_pad([r.args[i] for r in reqs], rows, bucket)
            for i in range(n_pos))
        # chaos-harness seam: a revoked device surfaces here when the
        # loss hits at dispatch time (testing/faults.py)
        fault_point("serving.dispatch", "before", ctx=self.fault_ctx)
        outs = pred.predict(*batch_args)
        out_leaves, out_tree = jax.tree_util.tree_flatten(
            outs, is_leaf=lambda t: isinstance(t, NDArray))
        off = 0
        for r in reqs:
            r.future._resolve(partial(
                _build_response, out_leaves, out_tree, off, r.rows,
                bucket))
            off += r.rows
        self._batch_no += 1
        tag = self._batch_no
        self._inflight[tag] = (list(reqs), self._clock())
        payload = (tag, tuple(l._data for l in out_leaves
                              if isinstance(l, NDArray)))
        with self._stats_mu:
            self.stats["batches"] += 1
            self.stats["rows"] += rows
            self.stats["padded_rows"] += bucket - rows
            self.stats["flush_" + reason] += 1
        self._m_batches.inc()
        self._m_occupancy.observe(rows / bucket)
        self._window.push(payload, tag=tag)
        self._m_inflight.set(len(self._window))
        self._m_queue.set(self._queue.qsize() + len(self._forming))

    @staticmethod
    def _concat_pad(leaves, rows: int, bucket: int):
        """Concatenate one argument position across requests and pad
        to the bucket — async device ops only, no host sync."""
        datas = [l._data if isinstance(l, NDArray) else jnp.asarray(l)
                 for l in leaves]
        if bucket > rows:
            datas.append(jnp.zeros((bucket - rows,)
                                   + tuple(datas[0].shape[1:]),
                                   datas[0].dtype))
        out = datas[0] if len(datas) == 1 else jnp.concatenate(datas,
                                                               axis=0)
        return NDArray(out)

    def _retire_sync(self, payload):
        """Window sync hook: block on the micro-batch's outputs (the
        blessed retire), then record each rider request's end-to-end
        latency and fold the batch's service time into the EWMA the
        admission controller projects from. A retire FAILURE carries
        its riders to the resilience handler — device loss re-enqueues
        them through recovery instead of poisoning their futures."""
        tag, datas = payload
        try:
            # chaos-harness seam: a deferred device loss surfaces at
            # the blocking wait on the in-flight micro-batch
            fault_point("serving.retire", "before", ctx=self.fault_ctx)
            jax.block_until_ready(list(datas))
        except BaseException as e:
            rec = self._inflight.pop(tag, None)
            if rec is not None and \
                    self._handle_batch_failure(rec[0], e, "retire"):
                return           # riders re-enqueued; failure handled
            raise
        rec = self._inflight.pop(tag, None)
        now = self._clock()
        if rec is not None:
            reqs, t_dispatch = rec
            dt = max(0.0, now - t_dispatch)
            self._ewma_service = dt if self._ewma_service is None \
                else 0.3 * dt + 0.7 * self._ewma_service
            for r in reqs:
                self._m_latency.observe(max(0.0, now - r.t_submit))
        if self.on_batch_retired is not None:
            try:
                self.on_batch_retired()
            except Exception:    # pragma: no cover - defensive
                _LOG.warning("serving retire hook failed", exc_info=True)
        fault_point("serving.retire", "after", ctx=self.fault_ctx)
