"""Continuous-batching autoregressive decode engine (docs/SERVING.md).

Request-level batching (DynamicBatcher) is the wrong granularity for
autoregressive decode: requests retire after wildly different numbers
of steps, and a whole-batch scheduler holds every finished slot hostage
to the longest member (the Orca observation — iteration-level
scheduling, arXiv via vLLM/Orca lineage). This module schedules at the
STEP boundary instead:

- **Iteration-level scheduling.** Requests join and leave the running
  batch BETWEEN decode steps. The compiled step is shape-stable over a
  fixed ladder of slot-count buckets (``decode.slot_ladder`` /
  ``MXNET_DECODE_SLOTS``; AOT-compiled, warm-started from
  ``MXNET_COMPILE_CACHE``) with a per-slot active mask; a slot freed by
  EOS/max-tokens is refilled from the queue on the next iteration.
- **Paged KV cache.** K/V history lives in :class:`~mxnet_tpu.serving
  .kvcache.PagedKVCache` pages behind a (slots, max_pages) page-table
  indirection, so admission control is simply "are there free pages" —
  a request that cannot reserve its worst-case pages is shed with a
  typed ``Overloaded(reason="kvcache")`` (composing the PR 15 EWMA/
  deadline shedder, which still applies first). Deadlines are
  re-projected PER TOKEN at retire: when the inter-token (TPOT) EWMA
  says the remaining tokens cannot land inside the request's
  deadline, the stream is shed mid-flight with a typed
  ``DeadlineExceeded`` and its KV pages free immediately for streams
  that can still make their budget.
- **Chunked prefill.** Long prompts are consumed ``decode.prefill_chunk``
  tokens at a time, strictly alternating with decode iterations when
  both kinds of work exist — a long prompt can never starve the
  running batch, and a short request's TTFT never waits on a long
  prompt ahead of it.
- **Single-step decode kernel.** The per-token recurrence runs through
  :func:`~mxnet_tpu.ops.kernels.rnn_scan.rnn_decode_step` (the
  block_t=1 rnn_scan variant behind the shared ``MXNET_PALLAS`` gate)
  and attention reads K/V through the page table via
  :func:`~mxnet_tpu.ops.attention.paged_decode_attention`.

Pipelining discipline: every step is dispatched async and pushed into a
:class:`~mxnet_tpu.engine.DispatchWindow`; the retire of a step is the
ONE blessed host sync, and that is where its tokens are read back and
streamed to the per-request :class:`DecodeStream` futures. Next-step
inputs chain DEVICE-side (the sampled-token array feeds the next
iteration without a host round trip), so the hot loop stays clean under
``MXNET_TRANSFER_GUARD=raise`` — a tier-1 test pins zero unblessed
syncs over a streamed multi-request run.

Slot-reuse safety: an in-flight step dispatched before a retire
discovered EOS writes one garbage token into the finished request's
(now freed) pages. That is safe by stream order — the device executes
steps in dispatch order, so the garbage write always lands BEFORE the
next occupant's prefill overwrites those pages — and it is budgeted:
admission reserves ``pages_needed(prompt + max_new + inflight)``.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as onp

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..analysis import guard as _tguard
from ..engine import DispatchWindow
from ..ops.attention import paged_decode_attention
from ..ops.kernels.rnn_scan import rnn_decode_step
from .kvcache import KV_PAGE_SIZE, PagedKVCache, pages_needed
from .resilience import (DeadlineExceeded, Overloaded, ServingShutdown,
                         default_deadline_ms, shed_mode)
from .batcher import queue_depth

__all__ = ["DecodeEngine", "DecodeStream", "TinyDecoder", "run_decode",
           "slot_ladder", "kv_page_size", "prefill_chunk",
           "DECODE_SLOT_LADDER", "PREFILL_CHUNK"]

#: shipped slot-count ladder (``decode.slot_ladder`` / ``MXNET_DECODE_SLOTS``)
DECODE_SLOT_LADDER = (1, 2, 4, 8)
#: shipped prompt-chunk width (``decode.prefill_chunk`` /
#: ``MXNET_DECODE_PREFILL_CHUNK``)
PREFILL_CHUNK = 16


def _parse_ladder(v) -> Tuple[int, ...]:
    """'1,2,4,8' (or an int sequence) -> sorted unique positive tuple."""
    if isinstance(v, (tuple, list)):
        vals = tuple(sorted({int(x) for x in v}))
    else:
        vals = tuple(sorted({int(x) for x in
                             str(v).replace(" ", "").split(",") if x}))
    if not vals or vals[0] < 1:
        raise ValueError(f"bad slot ladder {v!r}")
    return vals


def slot_ladder() -> Tuple[int, ...]:
    """THE slot-ladder accessor: autotune override >
    ``MXNET_DECODE_SLOTS`` > the default (tuning/space.py precedence)."""
    from ..tuning import space as _tspace
    v = _tspace.value("decode.slot_ladder",
                      ",".join(str(x) for x in DECODE_SLOT_LADDER))
    try:
        return _parse_ladder(v)
    except (TypeError, ValueError):
        return DECODE_SLOT_LADDER


def kv_page_size() -> int:
    """Tokens per KV page — autotune override >
    ``MXNET_DECODE_KV_PAGE_SIZE`` > ``kvcache.KV_PAGE_SIZE``."""
    from ..tuning import space as _tspace
    try:
        return max(1, int(_tspace.value("decode.kv_page_size",
                                        KV_PAGE_SIZE)))
    except (TypeError, ValueError):
        return KV_PAGE_SIZE


def prefill_chunk() -> int:
    """Prompt tokens one prefill iteration consumes — autotune override
    > ``MXNET_DECODE_PREFILL_CHUNK`` > the default."""
    from ..tuning import space as _tspace
    try:
        return max(1, int(_tspace.value("decode.prefill_chunk",
                                        PREFILL_CHUNK)))
    except (TypeError, ValueError):
        return PREFILL_CHUNK


def _page_size_valid(v, _config) -> bool:
    """A candidate page size is valid when a nominal full cache (the
    shipped ladder's worst slot count at a 256-token context, f32,
    2 heads x 16 dims x 1 layer) stays inside ``MXNET_MEMORY_BUDGET``
    — engines re-check their REAL geometry at construction."""
    try:
        v = int(v)
    except (TypeError, ValueError):
        return False
    if not 1 <= v <= 4096:
        return False
    try:
        from ..telemetry.memory import memory_budget
        budget = memory_budget()
    except Exception:           # pragma: no cover - defensive
        return True
    if budget is None:
        return True
    slots = DECODE_SLOT_LADDER[-1]
    page_bytes = 2 * 1 * v * 2 * 16 * 4       # K+V, 1 layer, 2x16 f32
    pages = 1 + slots * pages_needed(256, v)
    return pages * page_bytes <= budget


def _register_tunables():
    """Decode-engine tunables, declared next to the constants they make
    sweepable (docs/PERF_NOTES.md "Autotuner")."""
    from ..tuning.space import Tunable, register
    register(Tunable(
        "decode.slot_ladder",
        default=",".join(str(x) for x in DECODE_SLOT_LADDER),
        grid=("1,2,4", "1,2,4,8", "1,2,4,8,16", "1,4,16"),
        env="MXNET_DECODE_SLOTS", parse=str,
        valid=lambda v, _c: bool(_parse_ladder(v)),
        seam="serving.decode.slot_ladder() -> DecodeEngine AOT "
             "slot-count buckets",
        scope="serving", affects_program=True,
        doc="slot-count buckets the decode step is compiled for "
            "(comma list; largest = physical slots)"))
    register(Tunable(
        "decode.kv_page_size", default=KV_PAGE_SIZE,
        grid=(8, 16, 32, 64),
        env="MXNET_DECODE_KV_PAGE_SIZE", parse=int,
        valid=_page_size_valid,
        seam="serving.decode.kv_page_size() -> PagedKVCache page "
             "geometry + page-table width",
        scope="serving", affects_program=True,
        doc="tokens per KV page (pages x page_bytes must fit "
            "MXNET_MEMORY_BUDGET)"))
    register(Tunable(
        "decode.prefill_chunk", default=PREFILL_CHUNK,
        grid=(8, 16, 32, 64, 128),
        env="MXNET_DECODE_PREFILL_CHUNK", parse=int,
        valid=lambda v, _c: 1 <= int(v) <= 4096,
        seam="serving.decode.prefill_chunk() -> chunked-prefill "
             "program width",
        scope="serving", affects_program=True,
        doc="prompt tokens one prefill iteration consumes (smaller = "
            "better decode-batch latency, larger = better prefill "
            "throughput)"))


try:
    _register_tunables()
except Exception:    # pragma: no cover - tuning must never break serving
    import logging
    logging.getLogger("mxnet_tpu.tuning").debug(
        "decode tunable registration failed", exc_info=True)


def _telemetry():
    from .. import telemetry
    return telemetry


# ---------------------------------------------------------------------------
# reference model
# ---------------------------------------------------------------------------

class TinyDecoder:
    """The reference autoregressive decode model — one LSTM cell through
    :func:`rnn_decode_step` plus one attention layer reading K/V through
    the page table — small enough for CPU tier-1 yet exercising BOTH
    decode kernels and the full paged-cache read/write path.

    Any model driving :class:`DecodeEngine` implements this protocol:
    ``params`` (a pytree), ``num_layers``/``num_heads``/``head_dim``/
    ``d_model``, :meth:`init_state`, :meth:`decode_step` and
    :meth:`prefill_chunk` (both pure functions of their inputs — the
    engine jits and AOT-compiles them per slot bucket).
    """

    num_layers = 1

    def __init__(self, vocab: int = 64, d_model: int = 32,
                 num_heads: int = 2, seed: int = 0):
        if d_model % num_heads:
            raise MXNetError(f"d_model={d_model} not divisible by "
                             f"num_heads={num_heads}")
        self.vocab = int(vocab)
        self.d_model = int(d_model)
        self.num_heads = int(num_heads)
        self.head_dim = self.d_model // self.num_heads
        rng = onp.random.RandomState(seed)
        H = self.d_model

        def mat(*shape, scale=0.3):
            return jnp.asarray(
                rng.normal(0.0, scale, shape).astype("float32"))

        self.params = {
            "embed": mat(self.vocab, H, scale=0.5),
            "w_ih": mat(4 * H, H), "b_ih": jnp.zeros((4 * H,), "float32"),
            "w_hh": mat(4 * H, H), "b_hh": jnp.zeros((4 * H,), "float32"),
            "wq": mat(H, H), "wk": mat(H, H), "wv": mat(H, H),
            "wo": mat(H, H),
        }

    def init_state(self, slots: int):
        H = self.d_model
        return (jnp.zeros((slots, H), "float32"),
                jnp.zeros((slots, H), "float32"))

    # -- one fused sub-step shared by decode and prefill (parity by
    #    construction: a token is processed by the same math either way)
    def _cell(self, params, tokens, h, c):
        emb = params["embed"][tokens]
        xw = emb @ params["w_ih"].T + params["b_ih"]
        return rnn_decode_step(xw, h, c, params["w_hh"], params["b_hh"],
                               "lstm")

    def _qkv(self, params, h2):
        S = h2.shape[0]
        nH, hd = self.num_heads, self.head_dim
        q = (h2 @ params["wq"]).reshape(S, nH, hd)
        k = (h2 @ params["wk"]).reshape(S, nH, hd)
        v = (h2 @ params["wv"]).reshape(S, nH, hd)
        return q, k, v

    def _logits(self, params, h2, attn):
        out = h2 + attn.reshape(h2.shape) @ params["wo"]
        return out @ params["embed"].T

    def decode_step(self, params, tokens, h, c, k_pages, v_pages,
                    pidx, poff, table, lengths, active):
        """One iteration over every slot: consume ``tokens`` (each
        slot's last token), write this position's K/V through the page
        table, attend over the slot's history, emit the next greedy
        token. Inactive slots are bit-preserved (masked carry) and
        their writes land on the null page."""
        h2, c2 = self._cell(params, tokens, h, c)
        act = active[:, None]
        h_new = jnp.where(act, h2, h)
        c_new = jnp.where(act, c2, c)
        q, k, v = self._qkv(params, h2)
        pidx = jnp.where(active, pidx, 0)
        poff = jnp.where(active, poff, 0)
        k_pages = k_pages.at[0, pidx, poff].set(k.astype(k_pages.dtype))
        v_pages = v_pages.at[0, pidx, poff].set(v.astype(v_pages.dtype))
        attn = paged_decode_attention(q, k_pages[0], v_pages[0],
                                      table, lengths)
        nxt = jnp.argmax(self._logits(params, h2, attn),
                         axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tokens)
        return nxt, h_new, c_new, k_pages, v_pages

    def prefill_chunk(self, params, tokens, h, c, k_pages, v_pages,
                      start_len, n_valid, reset, active, table,
                      page_size: int):
        """Consume up to ``tokens.shape[1]`` prompt tokens for the
        active slot(s): scan the SAME per-token cell, writing each
        position's K/V through the page table; the returned token is
        the greedy continuation of the last valid position (meaningful
        on a prompt's final chunk — the request's first token)."""
        S, C = tokens.shape
        h = jnp.where(reset[:, None], 0.0, h)
        c = jnp.where(reset[:, None], 0.0, c)

        def body(carry, t):
            h, c, kp, vp = carry
            tok = tokens[:, t]
            valid = active & (t < n_valid)
            h2, c2 = self._cell(params, tok, h, c)
            vm = valid[:, None]
            h = jnp.where(vm, h2, h)
            c = jnp.where(vm, c2, c)
            _, k, v = self._qkv(params, h2)
            pos = start_len + t
            page = jnp.take_along_axis(
                table, (pos // page_size)[:, None], axis=1)[:, 0]
            pg = jnp.where(valid, page, 0)
            off = jnp.where(valid, pos % page_size, 0)
            kp = kp.at[0, pg, off].set(k.astype(kp.dtype))
            vp = vp.at[0, pg, off].set(v.astype(vp.dtype))
            return (h, c, kp, vp), None

        (h, c, k_pages, v_pages), _ = lax.scan(
            body, (h, c, k_pages, v_pages), jnp.arange(C))
        lengths = jnp.maximum(start_len + n_valid, 1)
        q, _, _ = self._qkv(params, h)
        attn = paged_decode_attention(q, k_pages[0], v_pages[0],
                                      table, lengths)
        nxt = jnp.argmax(self._logits(params, h, attn),
                         axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, 0)
        return nxt, h, c, k_pages, v_pages


# ---------------------------------------------------------------------------
# streaming future
# ---------------------------------------------------------------------------

class DecodeStream:
    """Per-request streaming future: each generated token is delivered
    as the step that computed it retires through the dispatch window.
    Iterate for tokens as they arrive, or :meth:`result` for the full
    sequence; :meth:`record` yields the streaming-latency record
    (``ttft_s`` / ``tpot_s`` / ``tokens``) loadgen aggregates."""

    def __init__(self, t_submit: float):
        # bare on purpose: decode hot loop: per-token budget; leaf, never nests
        self._cv = threading.Condition()  # mx-lint: allow=MXA009
        self._tokens: List[int] = []
        self._times: List[float] = []
        self._cursor = 0
        self._done = False
        self._exc: Optional[BaseException] = None
        self.t_submit = t_submit

    # -- engine side (called under the engine lock)
    def _deliver(self, tok: int, t: float):
        with self._cv:
            self._tokens.append(int(tok))
            self._times.append(float(t))
            self._cv.notify_all()

    def _finish(self):
        with self._cv:
            self._done = True
            self._cv.notify_all()

    def _fail(self, exc: BaseException):
        with self._cv:
            self._exc = exc
            self._done = True
            self._cv.notify_all()

    # -- client side
    def next_token(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next token, blocking until one arrives; None at end of
        stream. Raises the request's typed failure (after any tokens
        delivered before it) once the cursor reaches it."""
        with self._cv:
            if not self._cv.wait_for(
                    lambda: self._cursor < len(self._tokens) or self._done,
                    timeout=timeout):
                raise MXNetError("DecodeStream.next_token timed out")
            if self._cursor < len(self._tokens):
                tok = self._tokens[self._cursor]
                self._cursor += 1
                return tok
            if self._exc is not None:
                raise self._exc
            return None

    def __iter__(self):
        while True:
            tok = self.next_token()
            if tok is None:
                return
            yield tok

    def result(self, timeout: Optional[float] = None) -> List[int]:
        with self._cv:
            if not self._cv.wait_for(lambda: self._done, timeout=timeout):
                raise MXNetError("DecodeStream.result timed out")
            if self._exc is not None:
                raise self._exc
            return list(self._tokens)

    @property
    def done(self) -> bool:
        with self._cv:
            return self._done

    @property
    def ttft_s(self) -> Optional[float]:
        with self._cv:
            return (self._times[0] - self.t_submit) if self._times else None

    def record(self) -> dict:
        """Streaming-latency record: the shape
        ``loadgen.streaming_summary`` aggregates."""
        with self._cv:
            times = list(self._times)
            n = len(times)
            return {
                "tokens": n,
                "ttft_s": (times[0] - self.t_submit) if n else None,
                "tpot_s": [times[i] - times[i - 1] for i in range(1, n)],
                "wall_s": (times[-1] - self.t_submit) if n else None,
                "outcome": ("error" if self._exc is not None
                            else "ok" if self._done else "pending"),
            }


class _Request:
    __slots__ = ("prompt", "max_new", "eos", "stream", "deadline",
                 "t_submit", "t_last_tok", "slot", "phase", "pos",
                 "generated", "done", "npages", "seq")

    def __init__(self, prompt, max_new, eos, stream, deadline, npages,
                 seq):
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.stream = stream
        self.deadline = deadline
        self.t_submit = stream.t_submit
        self.t_last_tok = stream.t_submit
        self.slot = -1
        self.phase = "queued"      # queued -> prefill -> decode
        self.pos = 0               # prompt tokens consumed
        self.generated = 0
        self.done = False
        self.npages = npages
        self.seq = seq


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class DecodeEngine:
    """Iteration-level scheduler over a fixed slot ladder with a paged
    KV cache (module docstring has the design).

    ``static=True`` flips ONLY the scheduling policy to the classic
    whole-batch baseline — fill every slot, prefill all prompts, decode
    until the LAST member finishes, then admit the next batch — with
    the identical compiled programs, which is what makes the bench
    ``decode`` leg an honest continuous-vs-static A/B.

    Deterministic tests drive a ``start=False`` engine manually with
    :meth:`step_once` (+ :meth:`sync` to retire in-flight steps) and an
    injected ``clock``.
    """

    def __init__(self, model, *, ladder: Optional[Sequence[int]] = None,
                 num_pages: Optional[int] = None,
                 page_size: Optional[int] = None,
                 max_context: int = 128, max_new_default: int = 16,
                 eos_id: Optional[int] = None,
                 depth: Optional[int] = None, inflight: int = 1,
                 static: bool = False, admission: bool = True,
                 dtype: str = "float32",
                 clock: Callable[[], float] = time.perf_counter,
                 start: bool = True):
        self.model = model
        self._ladder = _parse_ladder(ladder if ladder is not None
                                     else slot_ladder())
        self.slots = self._ladder[-1]
        ps = int(page_size) if page_size else kv_page_size()
        self._chunk = prefill_chunk()
        self.max_context = int(max_context)
        self.max_pages_per_slot = pages_needed(self.max_context, ps)
        if num_pages is None:
            num_pages = 1 + self.slots * self.max_pages_per_slot
        self.kv = PagedKVCache(model.num_layers, model.num_heads,
                               model.head_dim, num_pages, ps, dtype=dtype)
        self._h, self._c = model.init_state(self.slots)
        self._tokens_dev = jnp.zeros((self.slots,), jnp.int32)
        self._table = onp.zeros((self.slots, self.max_pages_per_slot),
                                onp.int32)
        self._device_len = onp.zeros(self.slots, onp.int64)
        self._occupant: List[Optional[_Request]] = [None] * self.slots
        self._queue: "deque[_Request]" = deque()
        self._depth = queue_depth() if depth is None else max(1, int(depth))
        self.max_new_default = max(1, int(max_new_default))
        self.eos_id = eos_id
        self.static = bool(static)
        self.admission = bool(admission)
        # bare on purpose: decode hot loop: per-token budget; leaf, never nests
        self._lock = threading.RLock()  # mx-lint: allow=MXA009
        # bare on purpose: decode hot loop: per-token budget; leaf, never nests
        self._work = threading.Condition(self._lock)  # mx-lint: allow=MXA009
        self._clock = clock
        self._window = DispatchWindow(max_inflight=max(0, int(inflight)),
                                      what="decode step",
                                      sync_fn=self._retire_sync)
        self._programs: Dict[tuple, dict] = {}
        self._n_traces = 0
        self._seq = 0
        self._tag = 0
        self._draining = False
        self._dead: Optional[BaseException] = None
        self._ewma_step: Optional[float] = None
        # inter-token-gap EWMA (TPOT): the per-token deadline
        # re-projection sheds a stream mid-flight when the projected
        # remaining decode time cannot land inside its deadline
        self._ewma_tpot: Optional[float] = None
        self._last_was_prefill = False
        self.stats = {"submitted": 0, "completed": 0, "rejected": 0,
                      "deadline_missed": 0, "shed_midstream": 0,
                      "steps": 0, "prefill_chunks": 0, "tokens": 0,
                      "kv_util_peak": 0.0}
        t = _telemetry()
        reg = t.registry()
        self._m_tokens = reg.counter(t.names.DECODE_TOKENS)
        self._m_active = reg.gauge(t.names.DECODE_ACTIVE_SLOTS)
        self._m_ttft = reg.histogram(t.names.DECODE_TTFT_SECONDS)
        self._m_tpot = reg.histogram(t.names.DECODE_TPOT_SECONDS)
        self._m_rejected = reg.counter(t.names.SERVING_REJECTED,
                                       label_key="reason")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._serve_loop, name="mx-decode-engine",
                daemon=True)
            self._thread.start()

    # ---------------- compiled programs ----------------
    def _entry(self, kind: str, bucket: int) -> dict:
        key = (kind, bucket)
        entry = self._programs.get(key)
        if entry is None:
            model = self.model
            ps = self.kv.page_size
            eng = self
            if kind == "decode":
                def raw(params, tokens, h, c, kp, vp, pidx, poff,
                        table, lengths, active):
                    eng._n_traces += 1
                    return model.decode_step(params, tokens, h, c, kp,
                                             vp, pidx, poff, table,
                                             lengths, active)
            else:
                def raw(params, tokens, h, c, kp, vp, start_len,
                        n_valid, reset, active, table):
                    eng._n_traces += 1
                    return model.prefill_chunk(params, tokens, h, c,
                                               kp, vp, start_len,
                                               n_valid, reset, active,
                                               table, page_size=ps)
            entry = {"fn": jax.jit(raw, donate_argnums=(4, 5)),
                     "exe": None, "analysis": None}
            self._programs[key] = entry
        return entry

    def _example_args(self, kind: str, bucket: int):
        """ShapeDtypeStruct mirrors of one bucket's runtime arguments —
        the lowering/AOT example (no device allocation)."""
        b = int(bucket)
        H = self.model.d_model
        sds = jax.ShapeDtypeStruct
        params = jax.tree_util.tree_map(
            lambda a: sds(jnp.shape(a), a.dtype), self.model.params)
        kv = sds((self.kv.num_layers, self.kv.num_pages,
                  self.kv.page_size, self.kv.num_heads,
                  self.kv.head_dim), jnp.dtype(self.kv.dtype))
        i32 = jnp.dtype("int32")
        f32 = jnp.dtype("float32")
        table = sds((b, self.max_pages_per_slot), i32)
        if kind == "decode":
            return (params, sds((b,), i32), sds((b, H), f32),
                    sds((b, H), f32), kv, kv, sds((b,), i32),
                    sds((b,), i32), table, sds((b,), i32),
                    sds((b,), jnp.dtype(bool)))
        return (params, sds((b, self._chunk), i32), sds((b, H), f32),
                sds((b, H), f32), kv, kv, sds((b,), i32),
                sds((b,), i32), sds((b,), jnp.dtype(bool)),
                sds((b,), jnp.dtype(bool)), table)

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """AOT-compile the decode + prefill program of every ladder
        bucket (``.lower().compile()``, warm-started from the
        persistent ``MXNET_COMPILE_CACHE``) so no request ever eats a
        first-iteration compile. Returns {(kind, bucket): executable}."""
        out = {}
        for b in (buckets or self._ladder):
            for kind in ("decode", "prefill"):
                entry = self._entry(kind, int(b))
                if entry["exe"] is None:
                    n_before = self._n_traces
                    try:
                        entry["exe"] = entry["fn"].lower(
                            *self._example_args(kind, int(b))).compile()
                    finally:
                        self._n_traces = n_before
                out[(kind, int(b))] = entry["exe"]
        return out

    def _call(self, entry: dict, args: tuple):
        fn = entry["exe"] if entry["exe"] is not None else entry["fn"]
        try:
            return fn(*args)
        except (TypeError, ValueError):
            if entry["exe"] is None:
                raise
            entry["exe"] = None       # AOT signature drifted: re-jit
            return entry["fn"](*args)

    # ---------------- static analysis ----------------
    @property
    def mode(self) -> str:
        return "predict"

    @property
    def n_traces(self) -> int:
        return self._n_traces

    def lower_entry(self, *args, batch_size: Optional[int] = None,
                    **kwargs):
        """Lower one slot bucket's DECODE program for static analysis —
        the same artifact contract as ``CompiledPredictor.lower_entry``
        so the program lint runs unchanged over the decode engine."""
        bucket = self._bucket_for(int(batch_size) if batch_size
                                  else self.slots)
        entry = self._entry("decode", bucket)
        if entry["analysis"] is not None:
            return entry["analysis"]
        example = self._example_args("decode", bucket)
        n_before = self._n_traces
        try:
            lowered = entry["fn"].lower(*example)
            try:
                jaxpr = jax.make_jaxpr(entry["fn"])(*example)
            except Exception:       # pragma: no cover - defensive
                jaxpr = None
        finally:
            self._n_traces = n_before
        info = dict(kind="predict", mode="predict", lowered=lowered,
                    jaxpr=jaxpr, mesh=None, axis=None,
                    expected_donated=None, unit_sizes=[],
                    n_params=len(jax.tree_util.tree_leaves(
                        self.model.params)),
                    n_state_leaves=0, blessed_dtypes=[], report=None)
        entry["analysis"] = info
        return info

    def analyze(self, batch_size: Optional[int] = None):
        """Full program lint of the decode-step program
        (:class:`~mxnet_tpu.analysis.ProgramReport`, ``predict``
        expectations: no collectives, no unblessed host transfers, no
        stranded fusables)."""
        from ..analysis.program import analyze_step
        return analyze_step(self, batch_size=batch_size)

    # ---------------- admission ----------------
    def _reject(self, reason: str, msg: str):
        self.stats["rejected"] += 1
        self._m_rejected.inc(label=reason)
        raise Overloaded(msg, reason=reason)

    def submit(self, prompt, max_new: Optional[int] = None,
               eos: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> DecodeStream:
        """Admit one request (or shed it with a typed ``Overloaded``)
        and return its token stream. Admission control, in order:
        draining, queue depth, the PR 15 EWMA deadline shedder, and KV
        page reservation (``reason="kvcache"``) — a request that cannot
        get its worst-case pages up front is shed NOW rather than
        corrupting a neighbour mid-flight."""
        prompt = onp.asarray(prompt, onp.int32).ravel()
        if prompt.size < 1:
            raise MXNetError("decode prompt must have >= 1 token")
        mn = self.max_new_default if max_new is None else max(1,
                                                              int(max_new))
        if deadline_ms is None:
            deadline_ms = default_deadline_ms()
        with self._lock:
            if self._dead is not None:
                raise ServingShutdown(
                    "DecodeEngine is shut down") from self._dead
            if self._draining:
                self._reject("draining",
                             "DecodeEngine is draining; request shed")
            if len(self._queue) >= self._depth:
                self._reject("queue",
                             f"decode queue full ({self._depth})")
            slack = max(1, self._window.max_inflight)
            need_tokens = int(prompt.size) + mn + slack
            if need_tokens > self.max_pages_per_slot * self.kv.page_size:
                raise MXNetError(
                    f"request needs {need_tokens} KV positions "
                    f"(prompt {prompt.size} + max_new {mn} + inflight "
                    f"slack {slack}) > max_context {self.max_context}")
            npages = pages_needed(need_tokens, self.kv.page_size)
            mode = shed_mode()
            if (deadline_ms is not None and mode != "off"
                    and self._ewma_step is not None):
                projected = self._ewma_step * (len(self._queue) + 1)
                if projected * 1e3 > float(deadline_ms):
                    self._reject(
                        "deadline",
                        f"projected first-token wait {projected * 1e3:.1f}"
                        f" ms exceeds deadline {deadline_ms:.1f} ms")
            now = self._clock()
            stream = DecodeStream(now)
            deadline = (now + float(deadline_ms) / 1e3
                        if deadline_ms is not None else None)
            req = _Request(prompt, mn, eos, stream, deadline, npages,
                           self._seq)
            self._seq += 1
            if self.admission and not self.kv.reserve(req, npages):
                self._reject(
                    "kvcache",
                    f"KV page pool exhausted: need {npages} page(s), "
                    f"{self.kv.free_pages()} free of "
                    f"{self.kv.num_pages - 1}")
            self._queue.append(req)
            self.stats["submitted"] += 1
            self._work.notify_all()
            return stream

    # ---------------- scheduling ----------------
    def _bucket_for(self, n: int) -> int:
        for b in self._ladder:
            if b >= n:
                return b
        return self._ladder[-1]

    def _bucket(self) -> int:
        hi = max((s + 1 for s in range(self.slots)
                  if self._occupant[s] is not None), default=1)
        return self._bucket_for(hi)

    def _refill(self):
        if self.static:
            # whole-batch barrier: admit a new batch only once every
            # slot is free (the baseline the bench A/Bs against)
            if any(o is not None for o in self._occupant):
                return
        for slot in range(self.slots):
            if not self._queue:
                break
            if self._occupant[slot] is not None:
                continue
            req = self._queue[0]
            pages = self.kv.alloc(req, req.npages)
            if pages is None:        # admission=False path: wait
                break
            self._queue.popleft()
            req.slot = slot
            req.phase = "prefill"
            self._occupant[slot] = req
            self._table[slot, :] = 0
            self._table[slot, :len(pages)] = pages
            self._device_len[slot] = 0
        self._m_active.set(sum(1 for o in self._occupant
                               if o is not None))

    def _plan(self):
        occ = self._occupant
        pre = [s for s in range(self.slots)
               if occ[s] is not None and occ[s].phase == "prefill"]
        dec = [s for s in range(self.slots)
               if occ[s] is not None and occ[s].phase == "decode"
               and not occ[s].done]
        if self.static:
            if pre:
                return "prefill", min(pre, key=lambda s: occ[s].seq)
            if dec:
                return "decode", dec
            return None, None
        # continuous: strict alternation — prefill may never run twice
        # in a row while decode work exists (the non-starvation rule)
        if pre and (not dec or not self._last_was_prefill):
            return "prefill", min(pre, key=lambda s: occ[s].seq)
        if dec:
            return "decode", dec
        return None, None

    def step_once(self) -> bool:
        """One scheduler iteration: refill free slots, dispatch ONE
        compiled program (a decode step over every active slot, or one
        prefill chunk), push it into the window. False when there is no
        work. The manual-driving hook for deterministic tests; the
        background loop calls exactly this."""
        with self._lock:
            if self._dead is not None:
                return False
            self._refill()
            kind, what = self._plan()
            if kind is None:
                return False
            try:
                if kind == "prefill":
                    self._dispatch_prefill(what)
                else:
                    self._dispatch_decode(what)
            except MXNetError as e:
                self._fail_all(e)
                return False
            return True

    def sync(self):
        """Retire every in-flight step (the blessed waits) — delivers
        all tokens computed so far to their streams."""
        with self._lock:
            if len(self._window):
                self._window.drain()

    def _stitch(self, b: int, h2, c2, nxt, kp, vp):
        """Fold one bucket's outputs back into the full-slot device
        arrays (device-side chaining: no host round trip)."""
        self.kv.k_pages._data = kp
        self.kv.v_pages._data = vp
        if b == self.slots:
            self._h, self._c = h2, c2
            return nxt
        self._h = jnp.concatenate([h2, self._h[b:]], axis=0)
        self._c = jnp.concatenate([c2, self._c[b:]], axis=0)
        return None

    def _push(self, meta: tuple, arr):
        self._tag += 1
        self._window.push((meta, arr), tag=f"{meta[0]}#{self._tag}")

    def _dispatch_decode(self, slots_active: List[int]):
        b = self._bucket()
        ps = self.kv.page_size
        pidx = onp.zeros(b, onp.int32)
        poff = onp.zeros(b, onp.int32)
        lengths = onp.ones(b, onp.int32)
        act = onp.zeros(b, bool)
        metas = []
        for s in slots_active:
            dl = int(self._device_len[s])
            pidx[s] = self._table[s, dl // ps]
            poff[s] = dl % ps
            lengths[s] = dl + 1
            act[s] = True
            metas.append((s, self._occupant[s]))
            self._device_len[s] += 1
        entry = self._entry("decode", b)
        args = (self.model.params, self._tokens_dev[:b], self._h[:b],
                self._c[:b], self.kv.k_pages._data,
                self.kv.v_pages._data, jnp.asarray(pidx),
                jnp.asarray(poff), jnp.asarray(self._table[:b]),
                jnp.asarray(lengths), jnp.asarray(act))
        with _tguard.hot_scope("DecodeEngine.decode_step"):
            nxt, h2, c2, kp, vp = self._call(entry, args)
        full = self._stitch(b, h2, c2, nxt, kp, vp)
        self._tokens_dev = full if full is not None else \
            jnp.concatenate([nxt, self._tokens_dev[b:]])
        self.stats["steps"] += 1
        self._last_was_prefill = False
        self._push(("decode", metas, self._clock()), nxt)

    def _dispatch_prefill(self, slot: int):
        req = self._occupant[slot]
        b = self._bucket()
        C = self._chunk
        n_valid = min(C, req.prompt.size - req.pos)
        toks = onp.zeros((b, C), onp.int32)
        toks[slot, :n_valid] = req.prompt[req.pos:req.pos + n_valid]
        start = onp.zeros(b, onp.int32)
        start[slot] = self._device_len[slot]
        nv = onp.zeros(b, onp.int32)
        nv[slot] = n_valid
        reset = onp.zeros(b, bool)
        reset[slot] = req.pos == 0
        act = onp.zeros(b, bool)
        act[slot] = True
        entry = self._entry("prefill", b)
        args = (self.model.params, jnp.asarray(toks), self._h[:b],
                self._c[:b], self.kv.k_pages._data,
                self.kv.v_pages._data, jnp.asarray(start),
                jnp.asarray(nv), jnp.asarray(reset), jnp.asarray(act),
                jnp.asarray(self._table[:b]))
        with _tguard.hot_scope("DecodeEngine.prefill_chunk"):
            nxt, h2, c2, kp, vp = self._call(entry, args)
        full = self._stitch(b, h2, c2, None, kp, vp)
        self._device_len[slot] += n_valid
        req.pos += n_valid
        final = req.pos >= req.prompt.size
        if final:
            # the slot joins the decode batch NEXT iteration; its first
            # token chains device-side (async) into the token array
            req.phase = "decode"
            self._tokens_dev = self._tokens_dev.at[slot].set(nxt[slot])
        self.stats["prefill_chunks"] += 1
        self._last_was_prefill = True
        self._push(("prefill", slot, req, final, self._clock()), nxt)

    # ---------------- retire (the one blessed sync) ----------------
    def _retire_sync(self, payload):
        meta, arr = payload
        toks = onp.asarray(arr)      # blessed: runs under the window's
        now = self._clock()          # allow_transfers at retire
        if meta[0] == "decode":
            _, pairs, t0 = meta
            dt = max(0.0, now - t0)
            self._ewma_step = dt if self._ewma_step is None \
                else 0.8 * self._ewma_step + 0.2 * dt
            for slot, req in pairs:
                if req.done:
                    continue
                self._deliver(slot, req, int(toks[slot]), now)
        else:
            _, slot, req, final, _t0 = meta
            if final and not req.done:
                self._deliver(slot, req, int(toks[slot]), now)
        util = self.kv.utilization()
        if util > self.stats["kv_util_peak"]:
            self.stats["kv_util_peak"] = util
        return toks

    def _deliver(self, slot: int, req: _Request, tok: int, now: float):
        first = req.generated == 0
        req.generated += 1
        req.stream._deliver(tok, now)
        self.stats["tokens"] += 1
        self._m_tokens.inc()
        if first:
            self._m_ttft.observe(max(0.0, now - req.t_submit))
        else:
            gap = max(0.0, now - req.t_last_tok)
            self._m_tpot.observe(gap)
            self._ewma_tpot = gap if self._ewma_tpot is None \
                else 0.8 * self._ewma_tpot + 0.2 * gap
        req.t_last_tok = now
        if req.deadline is not None and now > req.deadline:
            self.stats["deadline_missed"] += 1
            self._finish_slot(slot, req, DeadlineExceeded(
                f"decode request missed its deadline after "
                f"{req.generated} token(s)"))
            return
        eos = req.eos if req.eos is not None else self.eos_id
        if (eos is not None and tok == eos) or \
                req.generated >= req.max_new:
            self._finish_slot(slot, req, None)
            return
        # per-token deadline re-projection: when the TPOT EWMA says the
        # REMAINING tokens cannot land inside the deadline, shed the
        # stream NOW — its KV pages free immediately for streams that
        # can still make their budget, instead of decoding tokens the
        # client will throw away at the reactive check above
        left = req.max_new - req.generated
        if req.deadline is not None and self._ewma_tpot is not None \
                and now + left * self._ewma_tpot > req.deadline:
            self.stats["deadline_missed"] += 1
            self.stats["shed_midstream"] += 1
            self._finish_slot(slot, req, DeadlineExceeded(
                f"decode stream shed mid-flight after {req.generated} "
                f"token(s): projected remaining decode time "
                f"({left} x {self._ewma_tpot * 1e3:.2f} ms TPOT) "
                f"overruns the deadline — KV pages freed for streams "
                f"that can still finish in budget"))

    def _finish_slot(self, slot: int, req: _Request,
                     exc: Optional[BaseException]):
        req.done = True
        if self._occupant[slot] is req:
            self._occupant[slot] = None
            self._table[slot, :] = 0
        self.kv.release(req)
        if exc is None:
            self.stats["completed"] += 1
            req.stream._finish()
        else:
            req.stream._fail(exc)
        self._m_active.set(sum(1 for o in self._occupant
                               if o is not None))
        self._work.notify_all()

    def _fail_all(self, exc: BaseException):
        self._dead = exc
        self._window.abandon()
        for slot in range(self.slots):
            req = self._occupant[slot]
            if req is not None and not req.done:
                req.done = True
                self.kv.release(req)
                req.stream._fail(exc)
            self._occupant[slot] = None
        while self._queue:
            req = self._queue.popleft()
            self.kv.release(req)
            req.stream._fail(exc)
        self._m_active.set(0)

    # ---------------- lifecycle ----------------
    def _idle(self) -> bool:
        return (not self._queue and len(self._window) == 0
                and all(o is None for o in self._occupant))

    def _serve_loop(self):
        while not self._stop.is_set():
            did = self.step_once()
            if did:
                continue
            with self._lock:
                if len(self._window):
                    try:
                        self._window.drain()
                    except MXNetError as e:
                        self._fail_all(e)
                    continue
            with self._work:
                self._work.wait(0.002)

    def drain(self, timeout: float = 60.0) -> bool:
        """Stop admitting (subsequent submits shed with
        ``reason="draining"``) and run every accepted request to
        completion. True when fully drained."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        if self._thread is not None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    if self._idle() or self._dead is not None:
                        return self._dead is None
                time.sleep(0.002)
            return False
        while True:
            if self.step_once():
                continue
            with self._lock:
                if len(self._window):
                    try:
                        self._window.drain()
                    except MXNetError as e:
                        self._fail_all(e)
                        return False
                    continue
                return self._idle()

    def close(self, timeout: float = 5.0):
        """Drain the window, fail anything still queued with a typed
        ``ServingShutdown``, stop the dispatch thread."""
        self._stop.set()
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        with self._lock:
            try:
                if len(self._window):
                    self._window.drain()
            except MXNetError:
                self._window.abandon()
            if self._dead is None:
                exc = ServingShutdown("DecodeEngine closed")
                for slot in range(self.slots):
                    req = self._occupant[slot]
                    if req is not None and not req.done:
                        req.done = True
                        self.kv.release(req)
                        req.stream._fail(exc)
                    self._occupant[slot] = None
                while self._queue:
                    req = self._queue.popleft()
                    self.kv.release(req)
                    req.stream._fail(exc)
                self._dead = exc
                self._m_active.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# bench harness: continuous vs static A/B
# ---------------------------------------------------------------------------

def run_decode(model, prompts, max_new, *, static: bool = False,
               ladder: Optional[Sequence[int]] = None,
               page_size: Optional[int] = None,
               eos_id: Optional[int] = None, inflight: int = 1,
               warmup: bool = True) -> dict:
    """Submit every request up front and drive the engine to
    completion — the bench ``decode`` leg's harness. ``static``
    selects the whole-batch baseline policy; everything else (model,
    compiled programs, kernels, page geometry) is identical, so the
    delta is pure scheduling."""
    prompts = [onp.asarray(p, onp.int32).ravel() for p in prompts]
    mns = ([int(max_new)] * len(prompts) if isinstance(max_new, int)
           else [int(m) for m in max_new])
    slack = max(1, int(inflight))
    ps = int(page_size) if page_size else kv_page_size()
    mc = max(int(p.size) + m + slack for p, m in zip(prompts, mns))
    # size the pool so every request can hold its reservation at once:
    # the A/B measures scheduling, not page starvation
    total_pages = 1 + sum(pages_needed(p.size + m + slack, ps)
                          for p, m in zip(prompts, mns))
    eng = DecodeEngine(model, ladder=ladder, num_pages=total_pages,
                       page_size=ps, max_context=mc, eos_id=eos_id,
                       inflight=inflight, depth=len(prompts) + 1,
                       static=static, start=False)
    try:
        if warmup:
            eng.warmup()
        t0 = time.perf_counter()
        streams = [eng.submit(p, max_new=m)
                   for p, m in zip(prompts, mns)]
        eng.drain()
        wall = time.perf_counter() - t0
        recs = [s.record() for s in streams]
        tokens = sum(r["tokens"] for r in recs)
        from . import loadgen
        out = {
            "mode": "static" if static else "continuous",
            "requests": len(prompts),
            "tokens": int(tokens),
            "wall_s": round(wall, 4),
            "decode_tokens_per_sec": round(tokens / wall, 2)
            if wall > 0 else None,
            "steps": eng.stats["steps"],
            "prefill_chunks": eng.stats["prefill_chunks"],
            "kv_page_util": round(eng.stats["kv_util_peak"], 4),
            "slot_ladder": list(eng._ladder),
            "page_size": ps,
        }
        out.update(loadgen.streaming_summary(recs, wall))
        return out
    finally:
        eng.close()
