"""Continuous-batching autoregressive decode engine (docs/SERVING.md).

Request-level batching (DynamicBatcher) is the wrong granularity for
autoregressive decode: requests retire after wildly different numbers
of steps, and a whole-batch scheduler holds every finished slot hostage
to the longest member (the Orca observation — iteration-level
scheduling, arXiv via vLLM/Orca lineage). This module schedules at the
STEP boundary instead:

- **Iteration-level scheduling.** Requests join and leave the running
  batch BETWEEN decode steps. The compiled step is shape-stable over a
  fixed ladder of slot-count buckets (``decode.slot_ladder`` /
  ``MXNET_DECODE_SLOTS``; AOT-compiled, warm-started from
  ``MXNET_COMPILE_CACHE``) with a per-slot active mask; a slot freed by
  EOS/max-tokens is refilled from the queue on the next iteration.
- **Paged KV cache.** K/V history lives in :class:`~mxnet_tpu.serving
  .kvcache.PagedKVCache` pages behind a (slots, max_pages) page-table
  indirection, so admission control is simply "are there free pages" —
  a request that cannot reserve its worst-case pages is shed with a
  typed ``Overloaded(reason="kvcache")`` (composing the PR 15 EWMA/
  deadline shedder, which still applies first). Deadlines are
  re-projected PER TOKEN at retire: when the inter-token (TPOT) EWMA
  says the remaining tokens cannot land inside the request's
  deadline, the stream is shed mid-flight with a typed
  ``DeadlineExceeded`` and its KV pages free immediately for streams
  that can still make their budget.
- **Chunked prefill.** Long prompts are consumed ``decode.prefill_chunk``
  tokens at a time, strictly alternating with decode iterations when
  both kinds of work exist — a long prompt can never starve the
  running batch, and a short request's TTFT never waits on a long
  prompt ahead of it.
- **Single-step decode kernel.** The per-token recurrence runs through
  :func:`~mxnet_tpu.ops.kernels.rnn_scan.rnn_decode_step` (the
  block_t=1 rnn_scan variant behind the shared ``MXNET_PALLAS`` gate)
  and attention reads K/V through the page table via
  :func:`~mxnet_tpu.ops.attention.paged_decode_attention`.

Pipelining discipline: every step is dispatched async and pushed into a
:class:`~mxnet_tpu.engine.DispatchWindow`; the retire of a step is the
ONE blessed host sync, and that is where its tokens are read back and
streamed to the per-request :class:`DecodeStream` futures. Next-step
inputs chain DEVICE-side (the sampled-token array feeds the next
iteration without a host round trip), so the hot loop stays clean under
``MXNET_TRANSFER_GUARD=raise`` — a tier-1 test pins zero unblessed
syncs over a streamed multi-request run.

Slot-reuse safety: an in-flight step dispatched before a retire
discovered EOS writes one garbage token into the finished request's
(now freed) pages. That is safe by stream order — the device executes
steps in dispatch order, so the garbage write always lands BEFORE the
next occupant's prefill overwrites those pages — and it is budgeted:
admission reserves ``pages_needed(prompt + max_new + inflight)``.

**Speculative decode** (``decode.spec_k`` / ``MXNET_DECODE_SPEC_K``;
0 = off): a cheap host-side drafter (:class:`NgramDrafter` by default —
prompt-lookup over the request's own token history; any object with
``propose(history, k)`` plugs in, e.g. :class:`ModelDrafter` wrapping a
small engine-protocol model) proposes up to K tokens per slot, and ONE
``verify`` program — the chunked-prefill scan shape over the SAME
per-token cell (:func:`~mxnet_tpu.ops.kernels.rnn_scan
.rnn_verify_scan`) — scores all K positions, accepts the longest
prefix matching the model's own greedy continuation DEVICE-side, rolls
the recurrent carry back to the accepted position, and emits between 1
and K tokens per dispatch. Rejected positions wrote K/V beyond the
committed length; the rollback is pure length bookkeeping — attention
masks by ``lengths`` and the next step overwrites them. Emitted
streams are BIT-exact vs plain greedy decode (tier-1 pins it); the
verify bucket ladder AOT-compiles at :meth:`DecodeEngine.warmup`.

**Prefix sharing** (``decode.prefix_share`` /
``MXNET_DECODE_PREFIX_SHARE``): retired prefill chunks register their
committed pages in the cache's content-hash registry; a later request
whose prompt extends a registered prefix maps those physical pages
(refcounted), installs the registered recurrent-state snapshot, and
prefills only its unshared tail — admission prices only that tail.
First divergent write onto a page held by >= 2 requests triggers a
copy-on-write page copy (kvcache.py has the lifecycle).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as onp

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..analysis import guard as _tguard
from ..engine import DispatchWindow
from ..ops.attention import paged_decode_attention
from ..ops.kernels import pallas_mode
from ..ops.kernels.rnn_scan import rnn_decode_step, rnn_verify_scan
from .kvcache import KV_PAGE_SIZE, PagedKVCache, pages_needed
from .resilience import (DeadlineExceeded, Overloaded, ServingShutdown,
                         default_deadline_ms, shed_mode)
from .batcher import queue_depth

__all__ = ["DecodeEngine", "DecodeStream", "TinyDecoder", "run_decode",
           "NgramDrafter", "ModelDrafter",
           "slot_ladder", "kv_page_size", "prefill_chunk", "spec_k",
           "prefix_share", "DECODE_SLOT_LADDER", "PREFILL_CHUNK",
           "SPEC_K", "PREFIX_SHARE"]

#: shipped slot-count ladder (``decode.slot_ladder`` / ``MXNET_DECODE_SLOTS``)
DECODE_SLOT_LADDER = (1, 2, 4, 8)
#: shipped prompt-chunk width (``decode.prefill_chunk`` /
#: ``MXNET_DECODE_PREFILL_CHUNK``)
PREFILL_CHUNK = 16
#: shipped max draft tokens per speculative step (``decode.spec_k`` /
#: ``MXNET_DECODE_SPEC_K``; 0 = speculative decode off)
SPEC_K = 0
#: shipped prefix-cache sharing switch (``decode.prefix_share`` /
#: ``MXNET_DECODE_PREFIX_SHARE``; 1 = on)
PREFIX_SHARE = 1


def _parse_ladder(v) -> Tuple[int, ...]:
    """'1,2,4,8' (or an int sequence) -> sorted unique positive tuple."""
    if isinstance(v, (tuple, list)):
        vals = tuple(sorted({int(x) for x in v}))
    else:
        vals = tuple(sorted({int(x) for x in
                             str(v).replace(" ", "").split(",") if x}))
    if not vals or vals[0] < 1:
        raise ValueError(f"bad slot ladder {v!r}")
    return vals


def slot_ladder() -> Tuple[int, ...]:
    """THE slot-ladder accessor: autotune override >
    ``MXNET_DECODE_SLOTS`` > the default (tuning/space.py precedence)."""
    from ..tuning import space as _tspace
    v = _tspace.value("decode.slot_ladder",
                      ",".join(str(x) for x in DECODE_SLOT_LADDER))
    try:
        return _parse_ladder(v)
    except (TypeError, ValueError):
        return DECODE_SLOT_LADDER


def kv_page_size() -> int:
    """Tokens per KV page — autotune override >
    ``MXNET_DECODE_KV_PAGE_SIZE`` > ``kvcache.KV_PAGE_SIZE``."""
    from ..tuning import space as _tspace
    try:
        return max(1, int(_tspace.value("decode.kv_page_size",
                                        KV_PAGE_SIZE)))
    except (TypeError, ValueError):
        return KV_PAGE_SIZE


def prefill_chunk() -> int:
    """Prompt tokens one prefill iteration consumes — autotune override
    > ``MXNET_DECODE_PREFILL_CHUNK`` > the default."""
    from ..tuning import space as _tspace
    try:
        return max(1, int(_tspace.value("decode.prefill_chunk",
                                        PREFILL_CHUNK)))
    except (TypeError, ValueError):
        return PREFILL_CHUNK


def spec_k() -> int:
    """Max draft tokens per speculative-decode step (0 disables) —
    autotune override > ``MXNET_DECODE_SPEC_K`` > the default."""
    from ..tuning import space as _tspace
    try:
        return max(0, int(_tspace.value("decode.spec_k", SPEC_K)))
    except (TypeError, ValueError):
        return SPEC_K


def prefix_share() -> bool:
    """Whether the engine shares prefix-cache pages across requests —
    autotune override > ``MXNET_DECODE_PREFIX_SHARE`` > the default."""
    from ..tuning import space as _tspace
    try:
        return bool(int(_tspace.value("decode.prefix_share",
                                      PREFIX_SHARE)))
    except (TypeError, ValueError):
        return bool(PREFIX_SHARE)


def _page_size_valid(v, _config) -> bool:
    """A candidate page size is valid when a nominal full cache (the
    shipped ladder's worst slot count at a 256-token context, f32,
    2 heads x 16 dims x 1 layer) stays inside ``MXNET_MEMORY_BUDGET``
    — engines re-check their REAL geometry at construction."""
    try:
        v = int(v)
    except (TypeError, ValueError):
        return False
    if not 1 <= v <= 4096:
        return False
    try:
        from ..telemetry.memory import memory_budget
        budget = memory_budget()
    except Exception:           # pragma: no cover - defensive
        return True
    if budget is None:
        return True
    slots = DECODE_SLOT_LADDER[-1]
    page_bytes = 2 * 1 * v * 2 * 16 * 4       # K+V, 1 layer, 2x16 f32
    pages = 1 + slots * pages_needed(256, v)
    return pages * page_bytes <= budget


def _spec_k_valid(v, _config) -> bool:
    """A candidate draft width is valid when the speculative overrun
    slack (up to ``spec_k`` uncommitted KV positions per slot) still
    fits ``MXNET_MEMORY_BUDGET`` at the same nominal geometry
    ``_page_size_valid`` prices — engines re-check their REAL geometry
    at construction."""
    try:
        v = int(v)
    except (TypeError, ValueError):
        return False
    if not 0 <= v <= 64:
        return False
    try:
        from ..telemetry.memory import memory_budget
        budget = memory_budget()
    except Exception:           # pragma: no cover - defensive
        return True
    if budget is None or v == 0:
        return True
    slots = DECODE_SLOT_LADDER[-1]
    ps = KV_PAGE_SIZE
    page_bytes = 2 * 1 * ps * 2 * 16 * 4       # K+V, 1 layer, 2x16 f32
    pages = 1 + slots * pages_needed(256 + v, ps)
    return pages * page_bytes <= budget


def _register_tunables():
    """Decode-engine tunables, declared next to the constants they make
    sweepable (docs/PERF_NOTES.md "Autotuner")."""
    from ..tuning.space import Tunable, register
    register(Tunable(
        "decode.slot_ladder",
        default=",".join(str(x) for x in DECODE_SLOT_LADDER),
        grid=("1,2,4", "1,2,4,8", "1,2,4,8,16", "1,4,16"),
        env="MXNET_DECODE_SLOTS", parse=str,
        valid=lambda v, _c: bool(_parse_ladder(v)),
        seam="serving.decode.slot_ladder() -> DecodeEngine AOT "
             "slot-count buckets",
        scope="serving", affects_program=True,
        doc="slot-count buckets the decode step is compiled for "
            "(comma list; largest = physical slots)"))
    register(Tunable(
        "decode.kv_page_size", default=KV_PAGE_SIZE,
        grid=(8, 16, 32, 64),
        env="MXNET_DECODE_KV_PAGE_SIZE", parse=int,
        valid=_page_size_valid,
        seam="serving.decode.kv_page_size() -> PagedKVCache page "
             "geometry + page-table width",
        scope="serving", affects_program=True,
        doc="tokens per KV page (pages x page_bytes must fit "
            "MXNET_MEMORY_BUDGET)"))
    register(Tunable(
        "decode.prefill_chunk", default=PREFILL_CHUNK,
        grid=(8, 16, 32, 64, 128),
        env="MXNET_DECODE_PREFILL_CHUNK", parse=int,
        valid=lambda v, _c: 1 <= int(v) <= 4096,
        seam="serving.decode.prefill_chunk() -> chunked-prefill "
             "program width",
        scope="serving", affects_program=True,
        doc="prompt tokens one prefill iteration consumes (smaller = "
            "better decode-batch latency, larger = better prefill "
            "throughput)"))
    register(Tunable(
        "decode.spec_k", default=SPEC_K,
        grid=(0, 2, 4, 8),
        env="MXNET_DECODE_SPEC_K", parse=int,
        valid=_spec_k_valid,
        seam="serving.decode.spec_k() -> DecodeEngine draft->verify "
             "width (verify-program token dim = spec_k + 1)",
        scope="serving", affects_program=True,
        doc="max draft tokens the drafter proposes per speculative "
            "step (0 = off; overrun slack must fit the KV budget)"))
    register(Tunable(
        "decode.prefix_share", default=PREFIX_SHARE,
        grid=(0, 1),
        env="MXNET_DECODE_PREFIX_SHARE", parse=int,
        valid=lambda v, _c: int(v) in (0, 1),
        seam="serving.decode.prefix_share() -> PagedKVCache prefix "
             "registry + COW sharing",
        scope="serving", affects_program=False,
        doc="share committed prompt-prefix KV pages across requests "
            "(refcounted, copy-on-write on divergence)"))


try:
    _register_tunables()
except Exception:    # pragma: no cover - tuning must never break serving
    import logging
    logging.getLogger("mxnet_tpu.tuning").debug(
        "decode tunable registration failed", exc_info=True)


def _telemetry():
    from .. import telemetry
    return telemetry


# ---------------------------------------------------------------------------
# speculative drafters
# ---------------------------------------------------------------------------

class NgramDrafter:
    """The default drafter: prompt-lookup / n-gram matching over the
    request's OWN token history (prompt + everything emitted so far).
    ``propose`` finds the most recent earlier occurrence of the last
    ``n`` tokens and returns (up to ``k``) of the tokens that followed
    it — free to compute, host-side, and exact on repetitive suffixes
    (code, templates, greedy loops). Proposals are only ever drafts:
    the verify program accepts at most the model's own greedy
    continuation, so a bad draft costs speed, never correctness.
    """

    def __init__(self, n: int = 2, min_n: int = 1):
        self.n = max(1, int(n))
        self.min_n = max(1, min(int(min_n), self.n))

    def propose(self, history, k: int) -> List[int]:
        k = int(k)
        if k <= 0 or len(history) < 2:
            return []
        hist = list(history)
        L = len(hist)
        for n in range(min(self.n, L - 1), self.min_n - 1, -1):
            tail = hist[L - n:]
            # most recent earlier occurrence of the suffix n-gram
            for i in range(L - n - 1, -1, -1):
                if hist[i:i + n] == tail:
                    cont = hist[i + n:i + n + k]
                    if cont:
                        return [int(t) for t in cont]
                    break
        return []


class ModelDrafter:
    """Pluggable small-model drafter: greedy-decodes ``k`` draft tokens
    with a SECOND engine-protocol model (same ``decode_step`` contract,
    its own tiny state per request) — the classic two-model speculative
    setup. Draft quality tracks how well the small model imitates the
    target; correctness never depends on it. Host-side readback of each
    draft token makes this drafter sync per proposal, so it is NOT for
    transfer-guard-pinned paths — the default :class:`NgramDrafter`
    is."""

    def __init__(self, model):
        self.model = model
        self._state: Dict[int, tuple] = {}

    def reset(self, key: int):
        self._state.pop(key, None)

    def propose(self, history, k: int, key: int = 0) -> List[int]:
        k = int(k)
        if k <= 0 or not len(history):
            return []
        import jax.numpy as _jnp
        h, c = self.model.init_state(1)
        # replay the history through the cell (small model, tiny state);
        # incremental caching per key keeps this O(new tokens)
        cached = self._state.get(key)
        start = 0
        if cached is not None and cached[0] <= len(history) \
                and list(history[:cached[0]]) == cached[1]:
            start, _, h, c = cached[0], cached[1], cached[2], cached[3]
        for t in history[start:]:
            tok = _jnp.asarray([int(t)], _jnp.int32)
            h, c = self.model._cell(self.model.params, tok, h, c)
        self._state[key] = (len(history), list(history), h, c)
        out: List[int] = []
        logits_of = getattr(self.model, "draft_logits", None)
        cur = int(history[-1])
        for _ in range(k):
            if logits_of is None:
                break
            cur = int(logits_of(self.model.params, h).argmax())
            out.append(cur)
            tok = _jnp.asarray([cur], _jnp.int32)
            h, c = self.model._cell(self.model.params, tok, h, c)
        return out


def _accept_longest_prefix(ys, hs, cs, tokens, n_draft, active):
    """Device-side acceptance for one verify dispatch.

    ``ys`` (S, K): the model's greedy token at each verified position;
    ``hs``/``cs`` (K, S, ...): masked per-position state trajectories;
    ``tokens`` (S, K): the fed inputs (position 0 = last committed
    token, 1.. = drafts); ``n_draft`` (S,): valid input count.

    Position t's output is emitted iff every draft before it matched
    the model's own continuation (``ys[t-1] == tokens[t]`` for all
    t' <= t), so the emitted block is EXACTLY what sequential greedy
    decode would have produced — acceptance can shorten a step, never
    change a token. Returns (emitted (S, K), n_acc (S,), next_tok (S,),
    h_fin, c_fin) with the state rolled back to the last accepted
    position (inactive slots bit-preserve everything).
    """
    S, K = ys.shape
    if K > 1:
        idx = jnp.arange(1, K)[None, :]
        eq = (ys[:, :-1] == tokens[:, 1:]) & (idx < n_draft[:, None])
        n_acc = 1 + jnp.cumprod(eq.astype(jnp.int32), axis=1).sum(axis=1)
    else:
        n_acc = jnp.ones((S,), jnp.int32)
    n_acc = jnp.minimum(n_acc, jnp.maximum(n_draft, 1)).astype(jnp.int32)
    a_idx = jnp.maximum(n_acc - 1, 0)

    def _at_accept(traj):
        if traj is None:
            return None
        t = jnp.moveaxis(traj, 0, 1)              # (S, K, ...)
        ix = a_idx.reshape((S,) + (1,) * (t.ndim - 1))
        return jnp.take_along_axis(t, ix, axis=1)[:, 0]

    h_fin = _at_accept(hs)
    c_fin = _at_accept(cs)
    next_tok = jnp.take_along_axis(ys, a_idx[:, None], axis=1)[:, 0]
    next_tok = jnp.where(active, next_tok, tokens[:, 0])
    n_acc = jnp.where(active, n_acc, 0)
    return ys, n_acc, next_tok, h_fin, c_fin


# ---------------------------------------------------------------------------
# reference model
# ---------------------------------------------------------------------------

class TinyDecoder:
    """The reference autoregressive decode model — one LSTM cell through
    :func:`rnn_decode_step` plus one attention layer reading K/V through
    the page table — small enough for CPU tier-1 yet exercising BOTH
    decode kernels and the full paged-cache read/write path.

    Any model driving :class:`DecodeEngine` implements this protocol:
    ``params`` (a pytree), ``num_layers``/``num_heads``/``head_dim``/
    ``d_model``, :meth:`init_state`, :meth:`decode_step` and
    :meth:`prefill_chunk` (both pure functions of their inputs — the
    engine jits and AOT-compiles them per slot bucket).
    """

    num_layers = 1

    def __init__(self, vocab: int = 64, d_model: int = 32,
                 num_heads: int = 2, seed: int = 0):
        if d_model % num_heads:
            raise MXNetError(f"d_model={d_model} not divisible by "
                             f"num_heads={num_heads}")
        self.vocab = int(vocab)
        self.d_model = int(d_model)
        self.num_heads = int(num_heads)
        self.head_dim = self.d_model // self.num_heads
        rng = onp.random.RandomState(seed)
        H = self.d_model

        def mat(*shape, scale=0.3):
            return jnp.asarray(
                rng.normal(0.0, scale, shape).astype("float32"))

        self.params = {
            "embed": mat(self.vocab, H, scale=0.5),
            "w_ih": mat(4 * H, H), "b_ih": jnp.zeros((4 * H,), "float32"),
            "w_hh": mat(4 * H, H), "b_hh": jnp.zeros((4 * H,), "float32"),
            "wq": mat(H, H), "wk": mat(H, H), "wv": mat(H, H),
            "wo": mat(H, H),
        }

    def init_state(self, slots: int):
        H = self.d_model
        return (jnp.zeros((slots, H), "float32"),
                jnp.zeros((slots, H), "float32"))

    # -- one fused sub-step shared by decode and prefill (parity by
    #    construction: a token is processed by the same math either way)
    def _cell(self, params, tokens, h, c):
        emb = params["embed"][tokens]
        xw = emb @ params["w_ih"].T + params["b_ih"]
        return rnn_decode_step(xw, h, c, params["w_hh"], params["b_hh"],
                               "lstm")

    def _qkv(self, params, h2):
        S = h2.shape[0]
        nH, hd = self.num_heads, self.head_dim
        q = (h2 @ params["wq"]).reshape(S, nH, hd)
        k = (h2 @ params["wk"]).reshape(S, nH, hd)
        v = (h2 @ params["wv"]).reshape(S, nH, hd)
        return q, k, v

    def _logits(self, params, h2, attn):
        out = h2 + attn.reshape(h2.shape) @ params["wo"]
        return out @ params["embed"].T

    def decode_step(self, params, tokens, h, c, k_pages, v_pages,
                    pidx, poff, table, lengths, active):
        """One iteration over every slot: consume ``tokens`` (each
        slot's last token), write this position's K/V through the page
        table, attend over the slot's history, emit the next greedy
        token. Inactive slots are bit-preserved (masked carry) and
        their writes land on the null page."""
        h2, c2 = self._cell(params, tokens, h, c)
        act = active[:, None]
        h_new = jnp.where(act, h2, h)
        c_new = jnp.where(act, c2, c)
        q, k, v = self._qkv(params, h2)
        pidx = jnp.where(active, pidx, 0)
        poff = jnp.where(active, poff, 0)
        k_pages = k_pages.at[0, pidx, poff].set(k.astype(k_pages.dtype))
        v_pages = v_pages.at[0, pidx, poff].set(v.astype(v_pages.dtype))
        attn = paged_decode_attention(q, k_pages[0], v_pages[0],
                                      table, lengths)
        nxt = jnp.argmax(self._logits(params, h2, attn),
                         axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, tokens)
        return nxt, h_new, c_new, k_pages, v_pages

    def prefill_chunk(self, params, tokens, h, c, k_pages, v_pages,
                      start_len, n_valid, reset, active, table,
                      page_size: int):
        """Consume up to ``tokens.shape[1]`` prompt tokens for the
        active slot(s): scan the SAME per-token cell, writing each
        position's K/V through the page table; the returned token is
        the greedy continuation of the last valid position (meaningful
        on a prompt's final chunk — the request's first token)."""
        S, C = tokens.shape
        h = jnp.where(reset[:, None], 0.0, h)
        c = jnp.where(reset[:, None], 0.0, c)

        def body(carry, t):
            h, c, kp, vp = carry
            tok = tokens[:, t]
            valid = active & (t < n_valid)
            h2, c2 = self._cell(params, tok, h, c)
            vm = valid[:, None]
            h = jnp.where(vm, h2, h)
            c = jnp.where(vm, c2, c)
            _, k, v = self._qkv(params, h2)
            pos = start_len + t
            page = jnp.take_along_axis(
                table, (pos // page_size)[:, None], axis=1)[:, 0]
            pg = jnp.where(valid, page, 0)
            off = jnp.where(valid, pos % page_size, 0)
            kp = kp.at[0, pg, off].set(k.astype(kp.dtype))
            vp = vp.at[0, pg, off].set(v.astype(vp.dtype))
            return (h, c, kp, vp), None

        (h, c, k_pages, v_pages), _ = lax.scan(
            body, (h, c, k_pages, v_pages), jnp.arange(C))
        lengths = jnp.maximum(start_len + n_valid, 1)
        q, _, _ = self._qkv(params, h)
        attn = paged_decode_attention(q, k_pages[0], v_pages[0],
                                      table, lengths)
        nxt = jnp.argmax(self._logits(params, h, attn),
                         axis=-1).astype(jnp.int32)
        nxt = jnp.where(active, nxt, 0)
        return nxt, h, c, k_pages, v_pages

    def verify_chunk(self, params, tokens, h, c, k_pages, v_pages,
                     start_len, n_draft, active, table,
                     page_size: int):
        """Score ``tokens`` (S, K: last committed token + up to K-1
        drafts) in ONE dispatch: the recurrence runs the masked
        verify scan (the SAME per-position cell as :meth:`decode_step`
        — the carry never depends on attention), then each position
        writes its K/V through the page table and emits the greedy
        token over exactly the history sequential decode would see.
        Returns per-position tokens ``ys`` (S, K) plus the full state
        trajectories for device-side acceptance rollback."""
        S, K = tokens.shape
        emb = params["embed"][tokens]                     # (S, K, H)
        xw = (emb @ params["w_ih"].T
              + params["b_ih"]).transpose(1, 0, 2)        # (K, S, 4H)
        valid = active[None, :] & (jnp.arange(K)[:, None]
                                   < n_draft[None, :])
        hs, cs = rnn_verify_scan(xw, h, c, params["w_hh"],
                                 params["b_hh"], "lstm", valid)

        def body(kv, t):
            kp, vp = kv
            h2 = hs[t]
            q, k, v = self._qkv(params, h2)
            val = valid[t]
            pos = start_len + t
            page = jnp.take_along_axis(
                table, (pos // page_size)[:, None], axis=1)[:, 0]
            pg = jnp.where(val, page, 0)
            off = jnp.where(val, pos % page_size, 0)
            kp = kp.at[0, pg, off].set(k.astype(kp.dtype))
            vp = vp.at[0, pg, off].set(v.astype(vp.dtype))
            lengths = jnp.where(val, pos + 1, 1)
            attn = paged_decode_attention(q, kp[0], vp[0], table,
                                          lengths)
            y = jnp.argmax(self._logits(params, h2, attn),
                           axis=-1).astype(jnp.int32)
            return (kp, vp), y

        (k_pages, v_pages), ys = lax.scan(
            body, (k_pages, v_pages), jnp.arange(K))
        return ys.T, hs, cs, k_pages, v_pages


# ---------------------------------------------------------------------------
# streaming future
# ---------------------------------------------------------------------------

class DecodeStream:
    """Per-request streaming future: each generated token is delivered
    as the step that computed it retires through the dispatch window.
    Iterate for tokens as they arrive, or :meth:`result` for the full
    sequence; :meth:`record` yields the streaming-latency record
    (``ttft_s`` / ``tpot_s`` / ``tokens``) loadgen aggregates."""

    def __init__(self, t_submit: float):
        # bare on purpose: decode hot loop: per-token budget; leaf, never nests
        self._cv = threading.Condition()  # mx-lint: allow=MXA009
        self._tokens: List[int] = []
        self._times: List[float] = []
        self._cursor = 0
        self._done = False
        self._exc: Optional[BaseException] = None
        self.t_submit = t_submit
        # speculative-decode accounting (empty unless the engine runs
        # a draft->verify loop): per-step emitted-token counts plus
        # drafted/accepted totals — loadgen.streaming_summary turns
        # these into acceptance_rate and tokens_per_step percentiles
        self._step_tokens: List[int] = []
        self._drafted = 0
        self._accepted = 0

    # -- engine side (called under the engine lock)
    def _deliver(self, tok: int, t: float):
        with self._cv:
            self._tokens.append(int(tok))
            self._times.append(float(t))
            self._cv.notify_all()

    def _record_step(self, emitted: int, drafted: int, accepted: int):
        with self._cv:
            self._step_tokens.append(int(emitted))
            self._drafted += int(drafted)
            self._accepted += int(accepted)

    def _finish(self):
        with self._cv:
            self._done = True
            self._cv.notify_all()

    def _fail(self, exc: BaseException):
        with self._cv:
            self._exc = exc
            self._done = True
            self._cv.notify_all()

    # -- client side
    def next_token(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next token, blocking until one arrives; None at end of
        stream. Raises the request's typed failure (after any tokens
        delivered before it) once the cursor reaches it."""
        with self._cv:
            if not self._cv.wait_for(
                    lambda: self._cursor < len(self._tokens) or self._done,
                    timeout=timeout):
                raise MXNetError("DecodeStream.next_token timed out")
            if self._cursor < len(self._tokens):
                tok = self._tokens[self._cursor]
                self._cursor += 1
                return tok
            if self._exc is not None:
                raise self._exc
            return None

    def __iter__(self):
        while True:
            tok = self.next_token()
            if tok is None:
                return
            yield tok

    def result(self, timeout: Optional[float] = None) -> List[int]:
        with self._cv:
            if not self._cv.wait_for(lambda: self._done, timeout=timeout):
                raise MXNetError("DecodeStream.result timed out")
            if self._exc is not None:
                raise self._exc
            return list(self._tokens)

    @property
    def done(self) -> bool:
        with self._cv:
            return self._done

    @property
    def ttft_s(self) -> Optional[float]:
        with self._cv:
            return (self._times[0] - self.t_submit) if self._times else None

    def record(self) -> dict:
        """Streaming-latency record: the shape
        ``loadgen.streaming_summary`` aggregates."""
        with self._cv:
            times = list(self._times)
            n = len(times)
            rec = {
                "tokens": n,
                "ttft_s": (times[0] - self.t_submit) if n else None,
                "tpot_s": [times[i] - times[i - 1] for i in range(1, n)],
                "wall_s": (times[-1] - self.t_submit) if n else None,
                "outcome": ("error" if self._exc is not None
                            else "ok" if self._done else "pending"),
            }
            if self._step_tokens:
                rec["step_tokens"] = list(self._step_tokens)
                rec["spec_drafted"] = self._drafted
                rec["spec_accepted"] = self._accepted
            return rec


class _Request:
    __slots__ = ("prompt", "max_new", "eos", "stream", "deadline",
                 "t_submit", "t_last_tok", "slot", "phase", "pos",
                 "generated", "done", "npages", "seq", "need_tokens",
                 "history", "inflight", "shared_len")

    def __init__(self, prompt, max_new, eos, stream, deadline, npages,
                 seq, need_tokens=0):
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.stream = stream
        self.deadline = deadline
        self.t_submit = stream.t_submit
        self.t_last_tok = stream.t_submit
        self.slot = -1
        self.phase = "queued"      # queued -> prefill -> decode
        self.pos = 0               # prompt tokens consumed
        self.generated = 0
        self.done = False
        self.npages = npages
        self.seq = seq
        self.need_tokens = need_tokens   # worst-case KV positions
        # host-side token history (prompt + emitted): what the drafter
        # proposes from and what prefix registration keys on
        self.history = [int(t) for t in prompt]
        self.inflight = False      # a verify step is in flight
        self.shared_len = 0        # prompt tokens seated from the cache


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class DecodeEngine:
    """Iteration-level scheduler over a fixed slot ladder with a paged
    KV cache (module docstring has the design).

    ``static=True`` flips ONLY the scheduling policy to the classic
    whole-batch baseline — fill every slot, prefill all prompts, decode
    until the LAST member finishes, then admit the next batch — with
    the identical compiled programs, which is what makes the bench
    ``decode`` leg an honest continuous-vs-static A/B.

    Deterministic tests drive a ``start=False`` engine manually with
    :meth:`step_once` (+ :meth:`sync` to retire in-flight steps) and an
    injected ``clock``.
    """

    def __init__(self, model, *, ladder: Optional[Sequence[int]] = None,
                 num_pages: Optional[int] = None,
                 page_size: Optional[int] = None,
                 max_context: int = 128, max_new_default: int = 16,
                 eos_id: Optional[int] = None,
                 depth: Optional[int] = None, inflight: int = 1,
                 static: bool = False, admission: bool = True,
                 dtype: str = "float32",
                 clock: Callable[[], float] = time.perf_counter,
                 start: bool = True,
                 spec_k: Optional[int] = None, drafter=None,
                 prefix_share: Optional[bool] = None):
        self.model = model
        self._ladder = _parse_ladder(ladder if ladder is not None
                                     else slot_ladder())
        self.slots = self._ladder[-1]
        ps = int(page_size) if page_size else kv_page_size()
        self._chunk = prefill_chunk()
        self._spec_k = (globals()["spec_k"]() if spec_k is None
                        else max(0, int(spec_k)))
        self._prefix_share = (globals()["prefix_share"]()
                              if prefix_share is None
                              else bool(prefix_share))
        self._drafter = drafter if drafter is not None else \
            (NgramDrafter() if self._spec_k else None)
        self.max_context = int(max_context)
        self.max_pages_per_slot = pages_needed(self.max_context, ps)
        if num_pages is None:
            num_pages = 1 + self.slots * self.max_pages_per_slot
        # GQA models cache fewer K/V heads than they query with
        kv_heads = int(getattr(model, "num_kv_heads", model.num_heads))
        self.kv = PagedKVCache(model.num_layers, kv_heads,
                               model.head_dim, num_pages, ps, dtype=dtype)
        self._h, self._c = model.init_state(self.slots)
        self._tokens_dev = jnp.zeros((self.slots,), jnp.int32)
        self._table = onp.zeros((self.slots, self.max_pages_per_slot),
                                onp.int32)
        self._device_len = onp.zeros(self.slots, onp.int64)
        self._occupant: List[Optional[_Request]] = [None] * self.slots
        self._queue: "deque[_Request]" = deque()
        self._depth = queue_depth() if depth is None else max(1, int(depth))
        self.max_new_default = max(1, int(max_new_default))
        self.eos_id = eos_id
        self.static = bool(static)
        self.admission = bool(admission)
        # bare on purpose: decode hot loop: per-token budget; leaf, never nests
        self._lock = threading.RLock()  # mx-lint: allow=MXA009
        # bare on purpose: decode hot loop: per-token budget; leaf, never nests
        self._work = threading.Condition(self._lock)  # mx-lint: allow=MXA009
        self._clock = clock
        self._window = DispatchWindow(max_inflight=max(0, int(inflight)),
                                      what="decode step",
                                      sync_fn=self._retire_sync)
        self._programs: Dict[tuple, dict] = {}
        self._n_traces = 0
        self._seq = 0
        self._tag = 0
        self._draining = False
        self._dead: Optional[BaseException] = None
        self._ewma_step: Optional[float] = None
        # inter-token-gap EWMA (TPOT): the per-token deadline
        # re-projection sheds a stream mid-flight when the projected
        # remaining decode time cannot land inside its deadline
        self._ewma_tpot: Optional[float] = None
        self._last_was_prefill = False
        self.stats = {"submitted": 0, "completed": 0, "rejected": 0,
                      "deadline_missed": 0, "shed_midstream": 0,
                      "steps": 0, "prefill_chunks": 0, "tokens": 0,
                      "kv_util_peak": 0.0,
                      "spec_steps": 0, "spec_drafted": 0,
                      "spec_accepted": 0,
                      "accept_hist": {},     # accepted-block len -> n
                      "prefix_hits": 0, "prefix_tokens": 0,
                      "kv_shared_peak": 0}
        t = _telemetry()
        reg = t.registry()
        self._m_tokens = reg.counter(t.names.DECODE_TOKENS)
        self._m_active = reg.gauge(t.names.DECODE_ACTIVE_SLOTS)
        self._m_ttft = reg.histogram(t.names.DECODE_TTFT_SECONDS)
        self._m_tpot = reg.histogram(t.names.DECODE_TPOT_SECONDS)
        self._m_rejected = reg.counter(t.names.SERVING_REJECTED,
                                       label_key="reason")
        self._m_drafted = reg.counter(t.names.DECODE_SPEC_DRAFTED)
        self._m_accepted = reg.counter(t.names.DECODE_SPEC_ACCEPTED)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._serve_loop, name="mx-decode-engine",
                daemon=True)
            self._thread.start()

    # ---------------- compiled programs ----------------
    def _entry(self, kind: str, bucket: int) -> dict:
        key = (kind, bucket)
        entry = self._programs.get(key)
        if entry is None:
            entry = {"fn": self._shared_program(kind),
                     "exe": None, "analysis": None}
            self._programs[key] = entry
        return entry

    def _shared_program(self, kind: str):
        """One ``jax.jit`` wrapper per (model, kind, page geometry,
        kernel gate), shared by every engine over the same model: a
        rebuilt engine (fleet restart, A/B run, test) reuses the
        already-traced program for any slot bucket it has seen, paying
        zero retrace. The wrapper is bucket-polymorphic (jit re-traces
        per leading-dim shape internally); only AOT ``exe`` artifacts
        stay per-engine."""
        model = self.model
        ps = self.kv.page_size
        cache = model.__dict__.setdefault("_mx_decode_programs", {})
        ck = (kind, ps, pallas_mode())
        cached = cache.get(ck)
        if cached is not None:
            cached["owner"]["eng"] = self
            return cached["fn"]
        owner = {"eng": self}

        def count_trace():
            eng = owner["eng"]
            if eng is not None:
                eng._n_traces += 1

        if kind == "decode":
            def raw(params, tokens, h, c, kp, vp, pidx, poff,
                    table, lengths, active):
                count_trace()
                return model.decode_step(params, tokens, h, c, kp,
                                         vp, pidx, poff, table,
                                         lengths, active)
        elif kind == "verify":
            def raw(params, tokens, h, c, kp, vp, start_len,
                    n_draft, active, table):
                count_trace()
                ys, hs, cs, kp, vp = model.verify_chunk(
                    params, tokens, h, c, kp, vp, start_len,
                    n_draft, active, table, page_size=ps)
                emitted, n_acc, nxt, h2, c2 = _accept_longest_prefix(
                    ys, hs, cs, tokens, n_draft, active)
                return emitted, n_acc, nxt, h2, c2, kp, vp
        else:
            def raw(params, tokens, h, c, kp, vp, start_len,
                    n_valid, reset, active, table):
                count_trace()
                return model.prefill_chunk(params, tokens, h, c,
                                           kp, vp, start_len,
                                           n_valid, reset, active,
                                           table, page_size=ps)
        cache[ck] = {"fn": jax.jit(raw, donate_argnums=(4, 5)),
                     "owner": owner}
        return cache[ck]["fn"]

    def _example_args(self, kind: str, bucket: int):
        """ShapeDtypeStruct mirrors of one bucket's runtime arguments —
        the lowering/AOT example (no device allocation)."""
        b = int(bucket)
        sds = jax.ShapeDtypeStruct
        params = jax.tree_util.tree_map(
            lambda a: sds(jnp.shape(a), a.dtype), self.model.params)
        kv = sds((self.kv.num_layers, self.kv.num_pages,
                  self.kv.page_size, self.kv.num_heads,
                  self.kv.head_dim), jnp.dtype(self.kv.dtype))
        i32 = jnp.dtype("int32")
        table = sds((b, self.max_pages_per_slot), i32)
        # state mirrors follow the LIVE state arrays (an attention-only
        # model carries dummy (slots, 1) pass-throughs, the RNN carries
        # (slots, d_model) — the program must match either)
        h = sds((b,) + tuple(self._h.shape[1:]), self._h.dtype)
        c = sds((b,) + tuple(self._c.shape[1:]), self._c.dtype)
        if kind == "decode":
            return (params, sds((b,), i32), h, c, kv, kv,
                    sds((b,), i32), sds((b,), i32), table,
                    sds((b,), i32), sds((b,), jnp.dtype(bool)))
        if kind == "verify":
            return (params, sds((b, self._spec_k + 1), i32), h, c,
                    kv, kv, sds((b,), i32), sds((b,), i32),
                    sds((b,), jnp.dtype(bool)), table)
        return (params, sds((b, self._chunk), i32), h, c, kv, kv,
                sds((b,), i32), sds((b,), i32),
                sds((b,), jnp.dtype(bool)),
                sds((b,), jnp.dtype(bool)), table)

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """AOT-compile the decode + prefill program of every ladder
        bucket (``.lower().compile()``, warm-started from the
        persistent ``MXNET_COMPILE_CACHE``) so no request ever eats a
        first-iteration compile. Returns {(kind, bucket): executable}."""
        out = {}
        kinds = ("decode", "prefill", "verify") if self._spec_k > 0 \
            else ("decode", "prefill")
        for b in (buckets or self._ladder):
            for kind in kinds:
                entry = self._entry(kind, int(b))
                if entry["exe"] is None:
                    n_before = self._n_traces
                    try:
                        entry["exe"] = entry["fn"].lower(
                            *self._example_args(kind, int(b))).compile()
                    finally:
                        self._n_traces = n_before
                out[(kind, int(b))] = entry["exe"]
        return out

    def _call(self, entry: dict, args: tuple):
        fn = entry["exe"] if entry["exe"] is not None else entry["fn"]
        try:
            return fn(*args)
        except (TypeError, ValueError):
            if entry["exe"] is None:
                raise
            entry["exe"] = None       # AOT signature drifted: re-jit
            return entry["fn"](*args)

    # ---------------- static analysis ----------------
    @property
    def mode(self) -> str:
        return "predict"

    @property
    def n_traces(self) -> int:
        return self._n_traces

    def lower_entry(self, *args, batch_size: Optional[int] = None,
                    **kwargs):
        """Lower one slot bucket's DECODE program for static analysis —
        the same artifact contract as ``CompiledPredictor.lower_entry``
        so the program lint runs unchanged over the decode engine."""
        bucket = self._bucket_for(int(batch_size) if batch_size
                                  else self.slots)
        entry = self._entry("decode", bucket)
        if entry["analysis"] is not None:
            return entry["analysis"]
        example = self._example_args("decode", bucket)
        n_before = self._n_traces
        try:
            lowered = entry["fn"].lower(*example)
            try:
                jaxpr = jax.make_jaxpr(entry["fn"])(*example)
            except Exception:       # pragma: no cover - defensive
                jaxpr = None
        finally:
            self._n_traces = n_before
        info = dict(kind="predict", mode="predict", lowered=lowered,
                    jaxpr=jaxpr, mesh=None, axis=None,
                    expected_donated=None, unit_sizes=[],
                    n_params=len(jax.tree_util.tree_leaves(
                        self.model.params)),
                    n_state_leaves=0, blessed_dtypes=[], report=None)
        entry["analysis"] = info
        return info

    def analyze(self, batch_size: Optional[int] = None):
        """Full program lint of the decode-step program
        (:class:`~mxnet_tpu.analysis.ProgramReport`, ``predict``
        expectations: no collectives, no unblessed host transfers, no
        stranded fusables)."""
        from ..analysis.program import analyze_step
        return analyze_step(self, batch_size=batch_size)

    # ---------------- admission ----------------
    def _reject(self, reason: str, msg: str):
        self.stats["rejected"] += 1
        self._m_rejected.inc(label=reason)
        raise Overloaded(msg, reason=reason)

    def submit(self, prompt, max_new: Optional[int] = None,
               eos: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> DecodeStream:
        """Admit one request (or shed it with a typed ``Overloaded``)
        and return its token stream. Admission control, in order:
        draining, queue depth, the PR 15 EWMA deadline shedder, and KV
        page reservation (``reason="kvcache"``) — a request that cannot
        get its worst-case pages up front is shed NOW rather than
        corrupting a neighbour mid-flight."""
        prompt = onp.asarray(prompt, onp.int32).ravel()
        if prompt.size < 1:
            raise MXNetError("decode prompt must have >= 1 token")
        mn = self.max_new_default if max_new is None else max(1,
                                                              int(max_new))
        if deadline_ms is None:
            deadline_ms = default_deadline_ms()
        with self._lock:
            if self._dead is not None:
                raise ServingShutdown(
                    "DecodeEngine is shut down") from self._dead
            if self._draining:
                self._reject("draining",
                             "DecodeEngine is draining; request shed")
            if len(self._queue) >= self._depth:
                self._reject("queue",
                             f"decode queue full ({self._depth})")
            slack = max(1, self._window.max_inflight)
            if self._spec_k:
                # a verify step writes up to spec_k draft positions
                # past the committed length before acceptance trims
                slack += self._spec_k
            need_tokens = int(prompt.size) + mn + slack
            if need_tokens > self.max_pages_per_slot * self.kv.page_size:
                raise MXNetError(
                    f"request needs {need_tokens} KV positions "
                    f"(prompt {prompt.size} + max_new {mn} + inflight "
                    f"slack {slack}) > max_context {self.max_context}")
            npages = pages_needed(need_tokens, self.kv.page_size)
            if self._prefix_share:
                # price only the unshared tail: FULL pages covered by a
                # registered prefix are mapped, not allocated (the seat
                # re-checks and falls back to worst case if the entry
                # died; a partial shared page is still priced as owned
                # — it is the COW target's budget)
                ent = self.kv.lookup_prefix(
                    prompt, max_pos=int(prompt.size) - 1)
                if ent is not None:
                    npages = max(1, npages
                                 - ent.pos // self.kv.page_size)
            mode = shed_mode()
            if (deadline_ms is not None and mode != "off"
                    and self._ewma_step is not None):
                projected = self._ewma_step * (len(self._queue) + 1)
                if projected * 1e3 > float(deadline_ms):
                    self._reject(
                        "deadline",
                        f"projected first-token wait {projected * 1e3:.1f}"
                        f" ms exceeds deadline {deadline_ms:.1f} ms")
            now = self._clock()
            stream = DecodeStream(now)
            deadline = (now + float(deadline_ms) / 1e3
                        if deadline_ms is not None else None)
            req = _Request(prompt, mn, eos, stream, deadline, npages,
                           self._seq, need_tokens=need_tokens)
            self._seq += 1
            if self.admission and not self.kv.reserve(req, npages):
                self._reject(
                    "kvcache",
                    f"KV page pool exhausted: need {npages} page(s), "
                    f"{self.kv.free_pages()} free of "
                    f"{self.kv.num_pages - 1}")
            self._queue.append(req)
            self.stats["submitted"] += 1
            self._work.notify_all()
            return stream

    # ---------------- scheduling ----------------
    def _bucket_for(self, n: int) -> int:
        for b in self._ladder:
            if b >= n:
                return b
        return self._ladder[-1]

    def _bucket(self) -> int:
        hi = max((s + 1 for s in range(self.slots)
                  if self._occupant[s] is not None), default=1)
        return self._bucket_for(hi)

    def _refill(self):
        if self.static:
            # whole-batch barrier: admit a new batch only once every
            # slot is free (the baseline the bench A/Bs against)
            if any(o is not None for o in self._occupant):
                return
        ps = self.kv.page_size
        for slot in range(self.slots):
            if not self._queue:
                break
            if self._occupant[slot] is not None:
                continue
            req = self._queue[0]
            tot = (pages_needed(req.need_tokens, ps)
                   if req.need_tokens else req.npages)
            ent = None
            if self._prefix_share and req.prompt.size > 1:
                # seat-time lookup (the authoritative one — the
                # submit-time lookup only priced admission); cap leaves
                # >= 1 prompt token to prefill so the final chunk still
                # produces the request's first output token
                ent = self.kv.lookup_prefix(
                    req.prompt, max_pos=int(req.prompt.size) - 1)
            if ent is not None:
                shared = list(ent.pages)
                own_n = max(0, tot - len(shared))
                own = self.kv.alloc(req, own_n) if own_n else []
                if own is None:      # admission=False path: wait
                    break
                self.kv.share(req, shared)
                # reservation correction: keep ONE spare page when the
                # last shared page is partial — the COW target for the
                # first divergent write into it
                self.kv.trim_reservation(req, 1 if ent.pos % ps else 0)
                pages = shared + list(own)
                self._device_len[slot] = ent.pos
                req.pos = ent.pos
                req.shared_len = ent.pos
                if ent.state is not None:
                    self._h = self._h.at[slot].set(ent.state[0])
                    self._c = self._c.at[slot].set(ent.state[1])
                self.stats["prefix_hits"] += 1
                self.stats["prefix_tokens"] += ent.pos
            else:
                pages = self.kv.alloc(req, tot)
                if pages is None:    # admission=False path: wait
                    break
                self._device_len[slot] = 0
            self._queue.popleft()
            req.slot = slot
            req.phase = "prefill"
            self._occupant[slot] = req
            self._table[slot, :] = 0
            self._table[slot, :len(pages)] = pages
        self._m_active.set(sum(1 for o in self._occupant
                               if o is not None))

    def _plan(self):
        occ = self._occupant
        pre = [s for s in range(self.slots)
               if occ[s] is not None and occ[s].phase == "prefill"]
        dec = [s for s in range(self.slots)
               if occ[s] is not None and occ[s].phase == "decode"
               and not occ[s].done]
        kind = "decode"
        if self._spec_k:
            # speculative mode: a slot joins a verify step only once
            # its FIRST token has retired (the drafter proposes from
            # host history) and its previous verify is out of flight —
            # the window drain is the per-slot sync point, so a slot
            # never has two verifies speculating past each other
            kind = "verify"
            dec = [s for s in dec if not occ[s].inflight
                   and occ[s].generated >= 1]
        if self.static:
            if pre:
                return "prefill", min(pre, key=lambda s: occ[s].seq)
            if dec:
                return kind, dec
            return None, None
        # continuous: strict alternation — prefill may never run twice
        # in a row while decode work exists (the non-starvation rule)
        if pre and (not dec or not self._last_was_prefill):
            return "prefill", min(pre, key=lambda s: occ[s].seq)
        if dec:
            return kind, dec
        return None, None

    def step_once(self) -> bool:
        """One scheduler iteration: refill free slots, dispatch ONE
        compiled program (a decode step over every active slot, or one
        prefill chunk), push it into the window. False when there is no
        work. The manual-driving hook for deterministic tests; the
        background loop calls exactly this."""
        with self._lock:
            if self._dead is not None:
                return False
            self._refill()
            kind, what = self._plan()
            if kind is None:
                return False
            try:
                if kind == "prefill":
                    self._dispatch_prefill(what)
                elif kind == "verify":
                    self._dispatch_verify(what)
                else:
                    self._dispatch_decode(what)
            except MXNetError as e:
                self._fail_all(e)
                return False
            return True

    def sync(self):
        """Retire every in-flight step (the blessed waits) — delivers
        all tokens computed so far to their streams."""
        with self._lock:
            if len(self._window):
                self._window.drain()

    def _stitch(self, b: int, h2, c2, nxt, kp, vp):
        """Fold one bucket's outputs back into the full-slot device
        arrays (device-side chaining: no host round trip)."""
        self.kv.k_pages._data = kp
        self.kv.v_pages._data = vp
        if b == self.slots:
            self._h, self._c = h2, c2
            return nxt
        self._h = jnp.concatenate([h2, self._h[b:]], axis=0)
        self._c = jnp.concatenate([c2, self._c[b:]], axis=0)
        return None

    def _push(self, meta: tuple, arr):
        self._tag += 1
        self._window.push((meta, arr), tag=f"{meta[0]}#{self._tag}")

    def _cow_guard(self, slot: int, req: _Request, start: int, n: int):
        """Copy-on-write fence: before a dispatch writes device
        positions ``[start, start + n)``, give the slot private copies
        of every page in the write range still shared with another
        request (one async device-side page copy each; the table row
        repoints to the copy). MUST run before the dispatch snapshots
        the table into program arguments."""
        if not self._prefix_share or n <= 0:
            return
        ps = self.kv.page_size
        for pi in range(start // ps, (start + n - 1) // ps + 1):
            page = int(self._table[slot, pi])
            if page and self.kv.page_shared(page):
                self._table[slot, pi] = self.kv.cow(req, page)

    def _dispatch_decode(self, slots_active: List[int]):
        b = self._bucket()
        ps = self.kv.page_size
        pidx = onp.zeros(b, onp.int32)
        poff = onp.zeros(b, onp.int32)
        lengths = onp.ones(b, onp.int32)
        act = onp.zeros(b, bool)
        metas = []
        for s in slots_active:
            dl = int(self._device_len[s])
            self._cow_guard(s, self._occupant[s], dl, 1)
            pidx[s] = self._table[s, dl // ps]
            poff[s] = dl % ps
            lengths[s] = dl + 1
            act[s] = True
            metas.append((s, self._occupant[s]))
            self._device_len[s] += 1
        entry = self._entry("decode", b)
        args = (self.model.params, self._tokens_dev[:b], self._h[:b],
                self._c[:b], self.kv.k_pages._data,
                self.kv.v_pages._data, jnp.asarray(pidx),
                jnp.asarray(poff), jnp.asarray(self._table[:b]),
                jnp.asarray(lengths), jnp.asarray(act))
        with _tguard.hot_scope("DecodeEngine.decode_step"):
            nxt, h2, c2, kp, vp = self._call(entry, args)
        full = self._stitch(b, h2, c2, nxt, kp, vp)
        self._tokens_dev = full if full is not None else \
            jnp.concatenate([nxt, self._tokens_dev[b:]])
        self.stats["steps"] += 1
        self._last_was_prefill = False
        self._push(("decode", metas, self._clock()), nxt)

    def _dispatch_prefill(self, slot: int):
        req = self._occupant[slot]
        b = self._bucket()
        C = self._chunk
        n_valid = min(C, req.prompt.size - req.pos)
        toks = onp.zeros((b, C), onp.int32)
        toks[slot, :n_valid] = req.prompt[req.pos:req.pos + n_valid]
        start = onp.zeros(b, onp.int32)
        start[slot] = self._device_len[slot]
        nv = onp.zeros(b, onp.int32)
        nv[slot] = n_valid
        reset = onp.zeros(b, bool)
        reset[slot] = req.pos == 0
        act = onp.zeros(b, bool)
        act[slot] = True
        self._cow_guard(slot, req, int(start[slot]), n_valid)
        entry = self._entry("prefill", b)
        args = (self.model.params, jnp.asarray(toks), self._h[:b],
                self._c[:b], self.kv.k_pages._data,
                self.kv.v_pages._data, jnp.asarray(start),
                jnp.asarray(nv), jnp.asarray(reset), jnp.asarray(act),
                jnp.asarray(self._table[:b]))
        with _tguard.hot_scope("DecodeEngine.prefill_chunk"):
            nxt, h2, c2, kp, vp = self._call(entry, args)
        full = self._stitch(b, h2, c2, None, kp, vp)
        self._device_len[slot] += n_valid
        req.pos += n_valid
        final = req.pos >= req.prompt.size
        if final:
            # the slot joins the decode batch NEXT iteration; its first
            # token chains device-side (async) into the token array
            req.phase = "decode"
            self._tokens_dev = self._tokens_dev.at[slot].set(nxt[slot])
        reg = None
        if self._prefix_share:
            # snapshot NOW (post-stitch the state rows are exactly the
            # post-chunk state; by retire time they may have advanced):
            # the registry commits tokens[:pos] -> pages + state at
            # retire, once the writes are known good
            npg = pages_needed(req.pos, self.kv.page_size)
            reg = (onp.ascontiguousarray(req.prompt[:req.pos]),
                   req.pos,
                   [int(p) for p in self._table[slot, :npg]],
                   (self._h[slot], self._c[slot]))
        self.stats["prefill_chunks"] += 1
        self._last_was_prefill = True
        self._push(("prefill", slot, req, final, self._clock(), reg),
                   nxt)

    def _dispatch_verify(self, slots_active: List[int]):
        b = self._bucket()
        ps = self.kv.page_size
        K = self._spec_k + 1
        toks = onp.zeros((b, K), onp.int32)
        start = onp.zeros(b, onp.int32)
        nd = onp.ones(b, onp.int32)
        act = onp.zeros(b, bool)
        metas = []
        for s in slots_active:
            req = self._occupant[s]
            dl = int(self._device_len[s])
            # never draft past the request's token budget or its page
            # table (the admission slack covers spec_k positions)
            room = self.max_pages_per_slot * ps - dl - 1
            left = req.max_new - req.generated - 1
            k_prop = max(0, min(self._spec_k, left, room))
            drafts = (list(self._drafter.propose(req.history,
                                                 k_prop))[:k_prop]
                      if k_prop else [])
            n = 1 + len(drafts)
            toks[s, 0] = req.history[-1]
            if drafts:
                toks[s, 1:n] = drafts
            start[s] = dl
            nd[s] = n
            act[s] = True
            req.inflight = True
            self._cow_guard(s, req, dl, n)
            metas.append((s, req, n))
        entry = self._entry("verify", b)
        args = (self.model.params, jnp.asarray(toks), self._h[:b],
                self._c[:b], self.kv.k_pages._data,
                self.kv.v_pages._data, jnp.asarray(start),
                jnp.asarray(nd), jnp.asarray(act),
                jnp.asarray(self._table[:b]))
        with _tguard.hot_scope("DecodeEngine.verify_step"):
            emitted, n_acc, nxt, h2, c2, kp, vp = self._call(entry, args)
        full = self._stitch(b, h2, c2, nxt, kp, vp)
        self._tokens_dev = full if full is not None else \
            jnp.concatenate([nxt, self._tokens_dev[b:]])
        self.stats["steps"] += 1
        self.stats["spec_steps"] += 1
        self._last_was_prefill = False
        self._push(("verify", metas, self._clock()), (emitted, n_acc))

    # ---------------- retire (the one blessed sync) ----------------
    def _retire_sync(self, payload):
        meta, arr = payload
        now = self._clock()          # blessed: runs under the window's
        if meta[0] == "decode":      # allow_transfers at retire
            toks = onp.asarray(arr)
            _, pairs, t0 = meta
            dt = max(0.0, now - t0)
            self._ewma_step = dt if self._ewma_step is None \
                else 0.8 * self._ewma_step + 0.2 * dt
            for slot, req in pairs:
                if req.done:
                    continue
                self._deliver(slot, req, int(toks[slot]), now)
        elif meta[0] == "verify":
            emitted = onp.asarray(arr[0])
            n_acc = onp.asarray(arr[1])
            toks = emitted
            _, triples, t0 = meta
            dt = max(0.0, now - t0)
            self._ewma_step = dt if self._ewma_step is None \
                else 0.8 * self._ewma_step + 0.2 * dt
            for slot, req, n in triples:
                req.inflight = False
                if req.done:
                    continue
                a = max(1, min(int(n_acc[slot]), n))
                # KV commit is pure length bookkeeping: the verify
                # already wrote positions [dl, dl+n); attention masks
                # by lengths, so the rejected tail is plain garbage
                # that a later step overwrites
                self._device_len[slot] += a
                drafted, accepted = n - 1, a - 1
                self.stats["spec_drafted"] += drafted
                self.stats["spec_accepted"] += accepted
                hist = self.stats["accept_hist"]
                hist[a] = hist.get(a, 0) + 1
                if drafted:
                    self._m_drafted.inc(drafted)
                if accepted:
                    self._m_accepted.inc(accepted)
                req.stream._record_step(a, drafted, accepted)
                for t in range(a):
                    self._deliver(slot, req, int(emitted[slot, t]), now)
                    if req.done:
                        break
        else:
            toks = onp.asarray(arr)
            _, slot, req, final, _t0, reg = meta
            if reg is not None and not req.done:
                toks_r, pos_r, pages_r, state_r = reg
                self.kv.register_prefix(toks_r, pos_r, pages_r,
                                        state=state_r)
            if final and not req.done:
                self._deliver(slot, req, int(toks[slot]), now)
        shared = self.kv.shared_pages()
        if shared > self.stats["kv_shared_peak"]:
            self.stats["kv_shared_peak"] = shared
        util = self.kv.utilization()
        if util > self.stats["kv_util_peak"]:
            self.stats["kv_util_peak"] = util
        return toks

    def _deliver(self, slot: int, req: _Request, tok: int, now: float):
        first = req.generated == 0
        req.generated += 1
        req.history.append(int(tok))
        req.stream._deliver(tok, now)
        self.stats["tokens"] += 1
        self._m_tokens.inc()
        if first:
            self._m_ttft.observe(max(0.0, now - req.t_submit))
        else:
            gap = max(0.0, now - req.t_last_tok)
            self._m_tpot.observe(gap)
            self._ewma_tpot = gap if self._ewma_tpot is None \
                else 0.8 * self._ewma_tpot + 0.2 * gap
        req.t_last_tok = now
        if req.deadline is not None and now > req.deadline:
            self.stats["deadline_missed"] += 1
            self._finish_slot(slot, req, DeadlineExceeded(
                f"decode request missed its deadline after "
                f"{req.generated} token(s)"))
            return
        eos = req.eos if req.eos is not None else self.eos_id
        if (eos is not None and tok == eos) or \
                req.generated >= req.max_new:
            self._finish_slot(slot, req, None)
            return
        # per-token deadline re-projection: when the TPOT EWMA says the
        # REMAINING tokens cannot land inside the deadline, shed the
        # stream NOW — its KV pages free immediately for streams that
        # can still make their budget, instead of decoding tokens the
        # client will throw away at the reactive check above
        left = req.max_new - req.generated
        if req.deadline is not None and self._ewma_tpot is not None \
                and now + left * self._ewma_tpot > req.deadline:
            self.stats["deadline_missed"] += 1
            self.stats["shed_midstream"] += 1
            self._finish_slot(slot, req, DeadlineExceeded(
                f"decode stream shed mid-flight after {req.generated} "
                f"token(s): projected remaining decode time "
                f"({left} x {self._ewma_tpot * 1e3:.2f} ms TPOT) "
                f"overruns the deadline — KV pages freed for streams "
                f"that can still finish in budget"))

    def _finish_slot(self, slot: int, req: _Request,
                     exc: Optional[BaseException]):
        req.done = True
        if self._occupant[slot] is req:
            self._occupant[slot] = None
            self._table[slot, :] = 0
        self.kv.release(req)
        if exc is None:
            self.stats["completed"] += 1
            req.stream._finish()
        else:
            req.stream._fail(exc)
        self._m_active.set(sum(1 for o in self._occupant
                               if o is not None))
        self._work.notify_all()

    def _fail_all(self, exc: BaseException):
        self._dead = exc
        self._window.abandon()
        for slot in range(self.slots):
            req = self._occupant[slot]
            if req is not None and not req.done:
                req.done = True
                self.kv.release(req)
                req.stream._fail(exc)
            self._occupant[slot] = None
        while self._queue:
            req = self._queue.popleft()
            self.kv.release(req)
            req.stream._fail(exc)
        self._m_active.set(0)

    # ---------------- lifecycle ----------------
    def _idle(self) -> bool:
        return (not self._queue and len(self._window) == 0
                and all(o is None for o in self._occupant))

    def _serve_loop(self):
        while not self._stop.is_set():
            did = self.step_once()
            if did:
                continue
            with self._lock:
                if len(self._window):
                    try:
                        self._window.drain()
                    except MXNetError as e:
                        self._fail_all(e)
                    continue
            with self._work:
                self._work.wait(0.002)

    def drain(self, timeout: float = 60.0) -> bool:
        """Stop admitting (subsequent submits shed with
        ``reason="draining"``) and run every accepted request to
        completion. True when fully drained."""
        with self._lock:
            self._draining = True
            self._work.notify_all()
        if self._thread is not None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                with self._lock:
                    if self._idle() or self._dead is not None:
                        return self._dead is None
                time.sleep(0.002)
            return False
        while True:
            if self.step_once():
                continue
            with self._lock:
                if len(self._window):
                    try:
                        self._window.drain()
                    except MXNetError as e:
                        self._fail_all(e)
                        return False
                    continue
                return self._idle()

    def close(self, timeout: float = 5.0):
        """Drain the window, fail anything still queued with a typed
        ``ServingShutdown``, stop the dispatch thread."""
        self._stop.set()
        with self._work:
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        with self._lock:
            try:
                if len(self._window):
                    self._window.drain()
            except MXNetError:
                self._window.abandon()
            if self._dead is None:
                exc = ServingShutdown("DecodeEngine closed")
                for slot in range(self.slots):
                    req = self._occupant[slot]
                    if req is not None and not req.done:
                        req.done = True
                        self.kv.release(req)
                        req.stream._fail(exc)
                    self._occupant[slot] = None
                while self._queue:
                    req = self._queue.popleft()
                    self.kv.release(req)
                    req.stream._fail(exc)
                self._dead = exc
                self._m_active.set(0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# bench harness: continuous vs static A/B
# ---------------------------------------------------------------------------

def run_decode(model, prompts, max_new, *, static: bool = False,
               ladder: Optional[Sequence[int]] = None,
               page_size: Optional[int] = None,
               eos_id: Optional[int] = None, inflight: int = 1,
               warmup: bool = True, spec_k: Optional[int] = None,
               prefix_share: Optional[bool] = None,
               drafter=None) -> dict:
    """Submit every request up front and drive the engine to
    completion — the bench ``decode`` leg's harness. ``static``
    selects the whole-batch baseline policy; everything else (model,
    compiled programs, kernels, page geometry) is identical, so the
    delta is pure scheduling."""
    prompts = [onp.asarray(p, onp.int32).ravel() for p in prompts]
    mns = ([int(max_new)] * len(prompts) if isinstance(max_new, int)
           else [int(m) for m in max_new])
    sk = (globals()["spec_k"]() if spec_k is None
          else max(0, int(spec_k)))
    slack = max(1, int(inflight)) + sk
    ps = int(page_size) if page_size else kv_page_size()
    mc = max(int(p.size) + m + slack for p, m in zip(prompts, mns))
    # size the pool so every request can hold its reservation at once:
    # the A/B measures scheduling, not page starvation
    total_pages = 1 + sum(pages_needed(p.size + m + slack, ps)
                          for p, m in zip(prompts, mns))
    eng = DecodeEngine(model, ladder=ladder, num_pages=total_pages,
                       page_size=ps, max_context=mc, eos_id=eos_id,
                       inflight=inflight, depth=len(prompts) + 1,
                       static=static, start=False, spec_k=sk,
                       prefix_share=prefix_share, drafter=drafter)
    try:
        if warmup:
            eng.warmup()
        t0 = time.perf_counter()
        streams = [eng.submit(p, max_new=m)
                   for p, m in zip(prompts, mns)]
        eng.drain()
        wall = time.perf_counter() - t0
        recs = [s.record() for s in streams]
        tokens = sum(r["tokens"] for r in recs)
        from . import loadgen
        out = {
            "mode": "static" if static else "continuous",
            "requests": len(prompts),
            "tokens": int(tokens),
            "wall_s": round(wall, 4),
            "decode_tokens_per_sec": round(tokens / wall, 2)
            if wall > 0 else None,
            "steps": eng.stats["steps"],
            "prefill_chunks": eng.stats["prefill_chunks"],
            "kv_page_util": round(eng.stats["kv_util_peak"], 4),
            "kv_num_pages": eng.kv.num_pages,
            "slot_ladder": list(eng._ladder),
            "page_size": ps,
        }
        if eng._spec_k:
            st = eng.stats
            out["spec_k"] = eng._spec_k
            out["spec_steps"] = st["spec_steps"]
            out["spec_drafted"] = st["spec_drafted"]
            out["spec_accepted"] = st["spec_accepted"]
            out["accept_hist"] = dict(st["accept_hist"])
        if eng._prefix_share:
            kvs = eng.kv.stats()
            out["prefix_hits"] = kvs["prefix_hits"]
            out["cow_copies"] = kvs["cow_copies"]
            out["kv_shared_peak"] = eng.stats["kv_shared_peak"]
        out.update(loadgen.streaming_summary(recs, wall))
        return out
    finally:
        eng.close()
