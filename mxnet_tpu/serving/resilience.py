"""Resilient serving: typed failures, admission control, auto-recovery.

PR 12's serving engine is fast; this module makes it survivable — the
request-scheduler and failure-recovery discipline of the TensorFlow
serving paths (arXiv:1605.08695 §4.3) composed from the elastic
machinery PR 11 already built, with AOT re-warm from the persistent
compile cache (arXiv:1810.09868) making predictor rebuilds cheap:

- **Typed failure taxonomy.** Every way an accepted request can fail is
  a distinct exception type the client can branch on:
  :class:`DeadlineExceeded` (the request's latency budget expired while
  it queued — dropped at dequeue, never dispatched),
  :class:`Overloaded` (shed at admission: queue full, projected wait
  past the deadline, circuit breaker open, or drain in progress —
  ``.reason`` says which), :class:`ServingShutdown` (the dispatcher
  died or the batcher closed with the request still pending — the
  anti-hang guarantee). All subclass ``MXNetError``.
- **Admission control / load shedding** (``MXNET_SERVING_SHED``):
  rejecting at ``submit`` when the projected queue wait (from the
  batcher's EWMA micro-batch service time) already exceeds the
  request's deadline keeps *accepted* requests inside their p99 under
  overload, instead of everyone timing out together.
- **:class:`CircuitBreaker`**: closed → open (fast-fail new submits
  while recovery runs) → half-open (post-recovery probe) → closed,
  exported as ``mx_serving_breaker_state``.
- **:class:`ServingSupervisor`**: the serving twin of
  ``elastic.ElasticSupervisor`` — classifies failures at the dispatch
  and window-retire seams via ``elastic.detect.classify``, rebuilds
  the predictor over ``parallel.dist.available_devices()`` with AOT
  buckets warm-started from ``MXNET_COMPILE_CACHE``, re-enqueues
  in-flight requests exactly once (bounded backoff retries for
  ``transient``; ``fatal``/``oom`` propagate), and drains gracefully
  on SIGTERM/:class:`~mxnet_tpu.elastic.PreemptionNotice`.

Telemetry: ``mx_serving_rejected_total{reason}``,
``mx_serving_deadline_missed_total``, ``mx_serving_retries_total``,
``mx_serving_recoveries_total``, ``mx_serving_breaker_state``,
``mx_serving_drain_seconds`` through the names.py catalog
(docs/OBSERVABILITY.md; docs/SERVING.md "Resilient serving").
"""
from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional, Sequence

from ..analysis.threads import mx_lock, mx_rlock
from ..base import MXNetError

__all__ = ["DeadlineExceeded", "Overloaded", "ServingShutdown",
           "CircuitBreaker", "ServingSupervisor", "default_deadline_ms",
           "shed_mode", "queue_timeout_s", "transient_retries"]

_LOG = logging.getLogger("mxnet_tpu.serving")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


# ---------------------------------------------------------------- errors
class DeadlineExceeded(MXNetError):
    """The request's latency budget expired while it waited in the
    queue: dropped at dequeue — never padded into a bucket, never
    dispatched — so the device's work all lands inside someone's
    deadline. Counted under ``mx_serving_deadline_missed_total``."""


class Overloaded(MXNetError):
    """The request was shed at admission (``.reason`` ∈ {``queue``,
    ``deadline``, ``breaker``, ``draining``, ``kvcache``}): the service
    preserved
    the p99 of already-accepted traffic instead of queueing work it
    cannot finish in time. Counted under
    ``mx_serving_rejected_total{reason}``. Retryable — after backoff,
    against another replica, or once the breaker closes."""

    def __init__(self, msg: str, reason: str = "queue"):
        super().__init__(msg)
        self.reason = reason


class ServingShutdown(MXNetError):
    """The batcher can no longer serve this request: the dispatcher
    thread died, or ``close()``/``drain()`` ran with the request still
    pending. Every pending future receives this instead of hanging
    forever — the anti-hang half of the resilience contract."""


# ---------------------------------------------------------------- env gates
def default_deadline_ms() -> Optional[float]:
    """``MXNET_SERVING_DEADLINE_MS``: default per-request latency
    budget applied when ``submit(deadline_ms=)`` is not given. Unset,
    empty, or <= 0 means no deadline."""
    v = os.environ.get("MXNET_SERVING_DEADLINE_MS", "").strip()
    if not v:
        return None
    try:
        ms = float(v)
    except ValueError:
        return None
    return ms if ms > 0 else None


def shed_mode(default: str = "deadline") -> str:
    """``MXNET_SERVING_SHED``: admission-control policy —

    - ``off`` — no shedding; a full queue blocks ``submit`` up to the
      queue timeout (then :class:`Overloaded`);
    - ``deadline`` (default) — additionally reject at ``submit`` when
      the projected queue wait (EWMA service time x batches ahead)
      already exceeds the request's deadline; requests without a
      deadline behave as ``off``;
    - ``queue`` — never block: a full queue rejects immediately.
    """
    v = os.environ.get("MXNET_SERVING_SHED", "").strip().lower()
    return v if v in ("off", "deadline", "queue") else default


def queue_timeout_s(default_ms: float = 120000.0) -> float:
    """``MXNET_SERVING_QUEUE_TIMEOUT_MS``: how long a blocking
    ``submit`` may wait on a full queue before it is shed with a typed
    :class:`Overloaded` (the previously implicit 120 s bound, now
    explicit). <= 0 means reject immediately."""
    try:
        v = float(os.environ.get("MXNET_SERVING_QUEUE_TIMEOUT_MS",
                                 str(default_ms)))
    except (TypeError, ValueError):
        v = default_ms
    return max(0.0, v) / 1e3


def transient_retries(default: int = 2) -> int:
    """``MXNET_SERVING_RETRIES``: bounded re-dispatch budget per
    request for ``transient``-classified dispatch failures (IO blips,
    injected faults). Device-loss re-enqueue is separately capped at
    exactly one."""
    try:
        v = int(os.environ.get("MXNET_SERVING_RETRIES", default))
    except (TypeError, ValueError):
        return default
    return max(0, v)


# ---------------------------------------------------------------- breaker
class CircuitBreaker:
    """Three-state circuit breaker for the serving admission path.

    ``closed`` (normal traffic) → ``open`` (every :meth:`allow` is
    False — the supervisor trips it when recovery starts, or
    ``failure_threshold`` consecutive failures accumulate) →
    ``half_open`` (probe traffic allowed: the supervisor moves here
    once the predictor is rebuilt, or ``cooldown_s`` elapses) →
    ``closed`` again on the first recorded success; a failure while
    half-open re-opens.

    State is exported as ``mx_serving_breaker_state`` (0 closed,
    1 half-open, 2 open) and every transition is kept in
    :attr:`transitions` for the diagnose panel. ``clock=`` injection
    makes the cooldown deterministic under test.
    """

    CLOSED = "closed"
    HALF_OPEN = "half_open"
    OPEN = "open"
    _LEVEL = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(self, failure_threshold: int = 1,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = mx_lock("serving.breaker")
        self._clock = clock
        self._threshold = max(1, int(failure_threshold))
        self._cooldown = cooldown_s
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at: Optional[float] = None
        self.transitions: List[tuple] = [(self.CLOSED, clock(), "init")]
        t = _telemetry()
        self._m_state = t.registry().gauge(t.names.SERVING_BREAKER_STATE)
        self._m_state.set(0)

    def _set(self, state: str, cause: str):
        """Transition (call under the lock)."""
        if state == self._state:
            return
        self._state = state
        if state == self.OPEN:
            self._opened_at = self._clock()
        if len(self.transitions) < 256:
            self.transitions.append((state, self._clock(), cause))
        self._m_state.set(self._LEVEL[state])

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """Whether a new submit may pass. Open + elapsed cooldown
        auto-transitions to half-open and admits the probe."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._cooldown is not None and \
                        self._opened_at is not None and \
                        self._clock() - self._opened_at >= self._cooldown:
                    self._set(self.HALF_OPEN, "cooldown")
                    return True
                return False
            return True          # half-open: probe traffic flows

    def record_failure(self, cause: str = "failure"):
        with self._lock:
            self._failures += 1
            if self._state == self.HALF_OPEN or \
                    self._failures >= self._threshold:
                self._set(self.OPEN, cause)

    def record_success(self):
        with self._lock:
            self._failures = 0
            if self._state == self.HALF_OPEN:
                self._set(self.CLOSED, "probe_ok")

    def trip(self, cause: str = "recovery"):
        """Force open (the supervisor's recovery entry)."""
        with self._lock:
            self._set(self.OPEN, cause)

    def half_open(self, cause: str = "recovered"):
        with self._lock:
            if self._state == self.OPEN:
                self._set(self.HALF_OPEN, cause)

    def close(self, cause: str = "reset"):
        with self._lock:
            self._failures = 0
            self._set(self.CLOSED, cause)


# ---------------------------------------------------------------- supervisor
class ServingSupervisor:
    """Keep a serving deployment alive across device loss, transient
    dispatch failures, and preemption — the serving twin of
    :class:`~mxnet_tpu.elastic.ElasticSupervisor`::

        def build():                        # deterministic!
            net = make_net()                # params materialized
            return mx.serving.CompiledPredictor(net,
                                                bucket_sizes=(1, 2, 4, 8))

        sup = mx.serving.ServingSupervisor(build, example=(x_row,),
                                           max_batch=8, timeout_ms=2.0)
        fut = sup.submit(x)                 # breaker-guarded
        out = fut.result(30)
        sup.drain()                         # graceful shutdown

    ``build()`` constructs a FRESH :class:`CompiledPredictor`; it runs
    once per formation under ``jax.default_device(available_devices()
    [0])`` so a rebuilt predictor's params land on a surviving device,
    and ``example`` (a tuple of one-row args) is passed to
    ``warmup()`` so every AOT bucket is re-compiled — warm-started
    from ``MXNET_COMPILE_CACHE``, so recovery pays cache hits, not
    fresh XLA compiles.

    Failure handling (the :func:`~mxnet_tpu.elastic.classify`
    taxonomy) at the batcher's dispatch and window-retire seams:

    - ``device_lost`` — trip the breaker (new submits fast-fail with
      :class:`Overloaded` ``reason="breaker"``), abandon the poisoned
      in-flight window, rebuild the predictor over the surviving
      world, re-enqueue every in-flight request EXACTLY ONCE (a
      request lost twice fails with the device-loss error), move the
      breaker to half-open; the first successful retire closes it.
    - ``transient`` — re-enqueue with exponential backoff, bounded by
      ``MXNET_SERVING_RETRIES`` per request.
    - ``fatal`` / ``oom`` — propagate: the affected futures fail with
      the original error (a smaller world cannot cure a shape bug,
      and re-dispatching an OOM only re-OOMs).

    ``drain_on_preemption`` (default True) polls the process-global
    :class:`~mxnet_tpu.elastic.PreemptionNotice` from the dispatch
    loop: SIGTERM flips the batcher to drain mode — reject new
    (:class:`Overloaded` ``reason="draining"``), flush forming +
    in-flight, close — so no accepted request is silently lost. Pass a
    STRING instead of True to poll a *scoped* notice
    (``elastic.notice(scope)``): a notice for that scope drains only
    this supervisor — the fleet's per-replica drain-then-retire path —
    while the process-global notice still drains everyone.
    """

    def __init__(self, build: Callable, example: Optional[Sequence] = None,
                 *, max_batch: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 depth: Optional[int] = None,
                 inflight: Optional[int] = None,
                 max_requeues: int = 1,
                 max_retries: Optional[int] = None,
                 backoff_base: float = 0.05, backoff_max: float = 2.0,
                 breaker: Optional[CircuitBreaker] = None,
                 drain_on_preemption=True,
                 clock: Callable[[], float] = time.perf_counter,
                 start: bool = True):
        from .batcher import DynamicBatcher
        from ..elastic import detect as _detect
        self._build = build
        self._example = tuple(example) if example is not None else None
        self._max_requeues = max(0, int(max_requeues))
        self._max_retries = transient_retries() if max_retries is None \
            else max(0, int(max_retries))
        self._backoff_base = float(backoff_base)
        self._backoff_max = float(backoff_max)
        self._detect = _detect
        self._lock = mx_rlock("serving.supervisor")
        self._transient_streak = 0
        self._closed = False
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        self.stats = {"recoveries": 0, "requeued": 0, "retried": 0,
                      "failed_requeues": 0, "recovery_downtime_s": 0.0,
                      "drains": 0}
        self.last_recovery: Optional[dict] = None
        t = _telemetry()
        reg = t.registry()
        self._m_retries = reg.counter(t.names.SERVING_RETRIES,
                                      label_key="cause")
        self._m_recoveries = reg.counter(t.names.SERVING_RECOVERIES,
                                         label_key="cause")
        self._predictor = self._form(first=True)
        self._batcher = DynamicBatcher(
            self._predictor, max_batch=max_batch, timeout_ms=timeout_ms,
            depth=depth, inflight=inflight, clock=clock, start=start)
        self._batcher.breaker = self.breaker
        self._batcher.on_batch_failure = self._on_batch_failure
        self._batcher.on_batch_retired = self._on_batch_retired
        self.notice_scope = drain_on_preemption \
            if isinstance(drain_on_preemption, str) else None
        if drain_on_preemption:
            # a scoped notice's requested() also honours the process-
            # global flag, so a real SIGTERM still drains every scope
            n = self._detect.notice(self.notice_scope)
            self._batcher.drain_check = n.requested

    # ---------------- public surface ----------------
    @property
    def predictor(self):
        """The live predictor (rebuilt at every recovery)."""
        return self._predictor

    @property
    def batcher(self):
        return self._batcher

    def submit(self, *args, deadline_ms=None, timeout=None):
        """Breaker-guarded submit; returns a
        :class:`~mxnet_tpu.serving.ServingFuture`. Raises typed
        :class:`Overloaded`/:class:`ServingShutdown` at admission."""
        return self._batcher.submit(*args, deadline_ms=deadline_ms,
                                    timeout=timeout)

    def drain(self):
        """Graceful shutdown: reject new, flush forming + in-flight,
        close (``mx_serving_drain_seconds``)."""
        self.stats["drains"] += 1
        self._batcher.drain()
        self._closed = True

    def close(self):
        self._batcher.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---------------- formation ----------------
    def _form(self, first: bool = False):
        """Build (or rebuild) the predictor on the surviving world and
        AOT-warm its buckets (compile-cache hits make this cheap)."""
        import jax
        from ..parallel import dist as _dist
        devs = _dist.available_devices()
        if not devs:
            raise MXNetError("serving: no devices survive; cannot "
                             "(re)build the predictor")
        with jax.default_device(devs[0]):
            pred = self._build()
            if self._example is not None:
                pred.warmup(*self._example)
        if not first:
            _LOG.warning(
                "serving: predictor rebuilt on %s (%d bucket program(s)"
                " AOT-warmed)", devs[0], pred.n_traces)
        return pred

    # ---------------- failure handling (dispatcher thread) ----------------
    def _on_batch_failure(self, reqs, exc, seam: str) -> bool:
        """Batcher hook: classify and recover. Returns True when the
        requests were handled (re-enqueued or failed here); False lets
        the batcher apply its default fail-the-futures path."""
        cause = self._detect.classify(exc)
        if cause == "device_lost":
            self._recover(list(reqs), exc, seam, cause)
            return True
        if cause == "transient":
            return self._retry_transient(list(reqs), exc, seam)
        return False             # fatal / oom / stall: propagate

    def _on_batch_retired(self):
        """Batcher hook after a successful window retire: a half-open
        breaker closes, the transient backoff streak resets."""
        self._transient_streak = 0
        self.breaker.record_success()

    def _retry_transient(self, reqs, exc, seam) -> bool:
        with self._lock:
            self._transient_streak += 1
            streak = self._transient_streak
        retry, fail = [], []
        for r in reqs:
            if r.retries >= self._max_retries:
                fail.append(r)
            else:
                r.retries += 1
                retry.append(r)
        for r in fail:
            self.stats["failed_requeues"] += 1
            r.future._fail(MXNetError(
                f"serving request failed after {r.retries} transient "
                f"retr{'ies' if r.retries != 1 else 'y'} "
                f"(MXNET_SERVING_RETRIES): {type(exc).__name__}: {exc}"))
        if not retry:
            return True
        delay = min(self._backoff_max,
                    self._backoff_base * (2 ** (streak - 1)))
        _LOG.warning(
            "serving: transient failure at %s (%s: %s); re-enqueueing "
            "%d request(s) after %.2fs backoff", seam,
            type(exc).__name__, exc, len(retry), delay)
        if delay > 0:
            time.sleep(delay)
        for r in retry:
            r.future._rearm()
            self._m_retries.inc(label="transient")
        self.stats["retried"] += len(retry)
        self._batcher.requeue(retry)
        return True

    def _recover(self, reqs, exc, seam, cause):
        """Device loss: breaker open → abandon in-flight → rebuild the
        predictor over the surviving devices → re-enqueue exactly once
        → breaker half-open. Runs on the dispatcher thread; the whole
        body is a blessed transfer region (recovery syncs are by
        design, like checkpoint restores)."""
        from ..analysis import guard as _tguard
        with self._lock:
            t0 = time.monotonic()
            self.breaker.trip(cause)
            # belt-and-braces anomaly (chain-marked: no-op when an
            # instrumented seam already recorded it)
            self._detect.maybe_record_device_lost(exc, f"serving {seam}")
            extra = self._batcher.abandon_inflight()
            seen = {id(r) for r in reqs}
            reqs = reqs + [r for r in extra if id(r) not in seen]
            reqs.sort(key=lambda r: r.t_submit)
            with _tguard.allow_transfers("serving recovery"):
                pred = self._rebuild(exc)
            if pred is None:     # rebuild failed: nothing left to serve
                for r in reqs:
                    self.stats["failed_requeues"] += 1
                    r.future._fail(ServingShutdown(
                        f"serving recovery failed after {cause} at "
                        f"{seam}: {type(exc).__name__}: {exc}"))
                return
            self._predictor = pred
            self._batcher.rebind(pred)
            requeue = []
            for r in reqs:
                if r.requeues >= self._max_requeues:
                    self.stats["failed_requeues"] += 1
                    r.future._fail(MXNetError(
                        f"serving request lost to repeated device "
                        f"failure (re-enqueued {r.requeues}x): "
                        f"{type(exc).__name__}: {exc}"))
                else:
                    r.requeues += 1
                    r.future._rearm()
                    self._m_retries.inc(label=cause)
                    requeue.append(r)
            self._batcher.requeue(requeue)
            self.stats["requeued"] += len(requeue)
            self.breaker.half_open()
            downtime = time.monotonic() - t0
            self.stats["recoveries"] += 1
            self.stats["recovery_downtime_s"] += downtime
            self.last_recovery = {
                "cause": cause, "seam": seam, "downtime_s": downtime,
                "requeued": len(requeue),
                "failed": len(reqs) - len(requeue),
                "time_unix": time.time()}
            self._m_recoveries.inc(label=cause)
            _LOG.warning(
                "serving: recovered from %s at %s in %.2fs "
                "(%d request(s) re-enqueued, %d failed)", cause, seam,
                downtime, len(requeue), len(reqs) - len(requeue))

    def _rebuild(self, exc):
        """Bounded-retry predictor rebuild; None when every attempt
        failed (the world is gone)."""
        attempts = max(1, self._detect.max_retries())
        last = exc
        for i in range(attempts):
            try:
                return self._form()
            except Exception as e:       # noqa: BLE001 - classify below
                last = e
                delay = min(self._backoff_max,
                            self._backoff_base * (2 ** i))
                _LOG.warning(
                    "serving: predictor rebuild attempt %d/%d failed "
                    "(%s: %s); retrying in %.2fs", i + 1, attempts,
                    type(e).__name__, e, delay)
                time.sleep(delay)
        _LOG.error("serving: predictor rebuild exhausted %d attempts "
                   "(%s: %s)", attempts, type(last).__name__, last)
        return None
