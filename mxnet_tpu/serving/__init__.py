"""mx.serving — production inference serving engine (docs/SERVING.md).

The millions-of-users half of the north star: the training substrate
(AOT lowering + ``MXNET_COMPILE_CACHE``, the dispatch window, the
telemetry catalog, the program-lint gates) turned into a serving path.

- :class:`CompiledPredictor` — AOT-compiled inference executables per
  leading-dim shape bucket: taping suspended, params resident on
  device, warm-started from the persistent compile cache, with the
  same static-analysis gates (``analyze()``/``memory_report()``/
  fusion census) as the training step.
- :class:`DynamicBatcher` — bounded-queue request coalescing into the
  bucketed shapes the compile cache keys on (pad-to-bucket with a
  valid-row mask; ``MXNET_SERVING_MAX_BATCH`` /
  ``MXNET_SERVING_BATCH_TIMEOUT_MS``), dispatched pipelined through a
  :class:`~mxnet_tpu.engine.DispatchWindow` so the device never idles
  between micro-batches — now with per-request deadlines
  (``submit(deadline_ms=)``), admission control/load shedding
  (``MXNET_SERVING_SHED``), graceful drain, and typed failures
  (an accepted request never hangs).
- :mod:`.resilience` — :class:`ServingSupervisor` (device-loss
  recovery riding the elastic seams: classify via
  ``elastic.detect.classify``, rebuild over ``available_devices()``
  with cache-warm AOT buckets, re-enqueue in-flight requests exactly
  once), :class:`CircuitBreaker`, and the typed error taxonomy
  (:class:`DeadlineExceeded` / :class:`Overloaded` /
  :class:`ServingShutdown`).
- :mod:`.fleet` — :class:`FleetController`/:class:`FleetRouter`: a
  multi-replica serving fleet (one predictor+batcher+supervisor per
  device, AOT-warm from the shared compile cache) with least-wait
  routing, replica-loss failover onto the survivors (exactly-once
  re-enqueue), drain-then-retire on scoped preemption notices,
  autoscaling, and zero-downtime rolling weight swaps
  (``mx_fleet_*`` telemetry; docs/SERVING.md "Serving fleet").
- :func:`predictor_for` — bf16/fp16/int8 serving variants through the
  existing AMP and post-training-quantization paths.
- :mod:`.loadgen` — closed-/open-loop load generation with per-request
  outcome census {ok, rejected, deadline_missed, error}, goodput vs
  raw QPS, and exact p50/p99 (the ``serving`` bench leg in bench.py).

Observability: ``mx_serving_*`` series in the telemetry catalog —
queue depth, in-flight micro-batches, batch occupancy, request-latency
histogram, rejected/deadline-missed/retries/recoveries counters,
breaker state, drain duration (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

from .resilience import (CircuitBreaker, DeadlineExceeded, Overloaded,
                         ServingShutdown, ServingSupervisor,
                         default_deadline_ms, queue_timeout_s, shed_mode,
                         transient_retries)
from .predictor import CompiledPredictor, DEFAULT_BUCKETS, predictor_for
from .batcher import (DynamicBatcher, ServingFuture, batch_timeout_s,
                      max_batch_rows, queue_depth)
from .kvcache import (KV_PAGE_SIZE, PagedKVCache, pages_needed,
                      prefix_hash)
from .decode import (DecodeEngine, DecodeStream, ModelDrafter,
                     NgramDrafter, TinyDecoder, kv_page_size,
                     prefill_chunk, prefix_share, run_decode,
                     slot_ladder, spec_k)
from .fleet import (FleetController, FleetEvent, FleetRouter,
                    fleet_max_replicas, fleet_min_replicas,
                    fleet_replicas, fleet_restart_retries,
                    fleet_scale_down_wait_s, fleet_scale_up_wait_s)
from . import fleet
from . import loadgen
from . import resilience
from . import decode
from . import kvcache

__all__ = ["CompiledPredictor", "DynamicBatcher", "ServingFuture",
           "predictor_for", "DEFAULT_BUCKETS", "loadgen", "resilience",
           "max_batch_rows", "batch_timeout_s", "queue_depth",
           "CircuitBreaker", "ServingSupervisor", "DeadlineExceeded",
           "Overloaded", "ServingShutdown", "default_deadline_ms",
           "queue_timeout_s", "shed_mode", "transient_retries",
           "decode", "kvcache", "DecodeEngine", "DecodeStream",
           "TinyDecoder", "PagedKVCache", "KV_PAGE_SIZE",
           "pages_needed", "run_decode", "slot_ladder", "kv_page_size",
           "prefill_chunk", "prefix_hash", "NgramDrafter",
           "ModelDrafter", "spec_k", "prefix_share",
           "fleet", "FleetController", "FleetRouter",
           "FleetEvent", "fleet_replicas", "fleet_min_replicas",
           "fleet_max_replicas", "fleet_scale_up_wait_s",
           "fleet_scale_down_wait_s", "fleet_restart_retries"]
