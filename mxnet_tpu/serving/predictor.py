"""AOT-compiled inference executables (``serving.CompiledPredictor``).

The training half of this framework compiles the whole train step into
one donated-buffer XLA program (gluon/fused_step.py); this is the
INFERENCE counterpart, the full-program-compilation discipline of the
Julia-to-TPU work (arXiv:1810.09868) applied to the serving path:

- **One program per shape bucket.** The forward runs ONCE under trace
  (taping suspended, ``autograd`` recording off, ``train_mode=False``)
  through the same functional ``ParamBinding`` the fused step uses, and
  the resulting program is AOT-lowered and compiled
  (:meth:`CompiledPredictor.aot_compile` / :meth:`warmup`) so the hot
  loop never pays a jit compile. ``MXNET_COMPILE_CACHE`` warm-starts
  the executables across process restarts — a restarted replica serves
  its first request from the disk cache instead of re-paying XLA.
- **Params resident on device.** Parameters are passed by handle every
  call — the same device buffers, no per-request host→device copy and
  no donation (inference reuses them; nothing is consumed). INT8
  predictors close their quantized weights over the trace as XLA
  constants.
- **Bucketed batch shapes.** ``bucket_sizes`` quantizes the leading
  batch dimension; :meth:`bucket_for` / :meth:`pad_to_bucket` pad a
  partial batch up to the next bucket (zero rows, sliced away by the
  caller) so N concurrent request sizes hit a handful of compiled
  programs instead of N. The :class:`~mxnet_tpu.serving.DynamicBatcher`
  coalesces concurrent requests INTO these buckets.
- **Same static-analysis gates as training.** :meth:`analyze` runs the
  full program lint (collective census, host-transfer scan, dtype
  drift, fusion census) over the serving program; :meth:`memory_report`
  attributes its HBM; ``expect_mode`` knows the ``predict`` contract
  (no collectives on a single device, no stranded fusable ops).
- **Sync-free dispatch.** :meth:`predict` returns ASYNC NDArrays — the
  host never reads the result; the response-side sync belongs to
  whoever consumes it (the batcher's window retire, or the client's
  ``.asnumpy()``). The whole call is a transfer-guard hot region:
  ``MXNET_TRANSFER_GUARD=raise`` turns any stray host sync inside it
  into an error (docs/SERVING.md).
"""
from __future__ import annotations

import logging
import time
from collections import OrderedDict
from typing import Any, Callable, Optional, Sequence

import numpy as onp

import jax
import jax.numpy as jnp

from .. import _tape
from ..analysis import guard as _tguard
from ..base import MXNetError
from ..gluon.block import ParamBinding, _TRACED
from ..gluon.fused_step import _analysis_mode
from ..ndarray.ndarray import NDArray
from ..ndarray.random import next_key, push_trace_key, pop_trace_key

__all__ = ["CompiledPredictor", "DEFAULT_BUCKETS", "predictor_for"]

_LOG = logging.getLogger("mxnet_tpu.serving")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


# elastic device-loss detection (elastic/detect.py), lazily reached so
# a lost device escaping the predictor call gets its exactly-one
# device_lost anomaly (the ServingSupervisor's recovery trigger)
_EDET = None


def _edetect():
    global _EDET
    if _EDET is None:
        from ..elastic import detect as _d
        _EDET = _d
    return _EDET


#: default leading-dim shape buckets: powers of two up to 64 — small
#: enough that a replica compiles them all at startup, coarse enough
#: that the compile cache keys on a handful of programs
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

_ARRAY_TYPES = (NDArray, onp.ndarray, jax.Array)


def _data_of(leaf):
    return leaf._data if isinstance(leaf, NDArray) else leaf


def _pad_rows(d, bucket: int):
    """Zero-pad a leaf's leading dim up to ``bucket`` rows (host-side
    for host arrays, an async device op for device arrays — never a
    sync)."""
    raw = _data_of(d)
    n = int(raw.shape[0])
    if n == bucket:
        return d
    if isinstance(raw, onp.ndarray):
        pad = onp.zeros((bucket - n,) + raw.shape[1:], raw.dtype)
        out = onp.concatenate([raw, pad], axis=0)
    else:
        pad = jnp.zeros((bucket - n,) + tuple(raw.shape[1:]), raw.dtype)
        out = jnp.concatenate([raw, pad], axis=0)
    return NDArray(out) if isinstance(d, NDArray) else out


class CompiledPredictor:
    """One callable = the whole forward pass, AOT-compiled per shape
    bucket.

    ``net`` must be initialized with materialized shapes (run one eager
    forward first — the model-zoo constructors' usual discipline).

        pred = mx.serving.CompiledPredictor(net)
        pred.warmup(example_row)          # AOT-compile every bucket
        out = pred.predict(x)             # async NDArray, no host sync
    """

    def __init__(self, net, bucket_sizes: Optional[Sequence[int]] = None,
                 analyze: Optional[str] = None):
        self._net = net
        sizes = tuple(sorted({int(b) for b in
                              (bucket_sizes or DEFAULT_BUCKETS)}))
        if not sizes or sizes[0] < 1:
            raise MXNetError("bucket_sizes must be positive integers, "
                             f"got {bucket_sizes!r}")
        self.bucket_sizes = sizes
        self._mode: Optional[str] = None   # None→undecided, 'fused'|'eager'
        self._lru: "OrderedDict[Any, dict]" = OrderedDict()
        self._n_traces = 0
        self._requests_done = 0
        self._autotune_outcome = None
        self._analyze = _analysis_mode(analyze)
        self._analysis_report = None
        # measured per-micro-batch service time from the warmup()
        # execution; a DynamicBatcher seeds its admission EWMA from it
        # so deadline shedding works from request 1 (no cold-start
        # blindness). None until warmup ran.
        self.service_time_seed_s: Optional[float] = None
        # params with materialized data, bound functionally per call —
        # the same handles every time (resident on device); quantized
        # blocks own no Parameters and close their weights over the trace
        self._params = [p for p in net.collect_params().values()
                        if p._data is not None]
        if any(p._data is None for p in net.collect_params().values()):
            raise MXNetError(
                "CompiledPredictor needs materialized parameter shapes — "
                "run one eager forward (net(example)) before wrapping")

    # ---------------- introspection ----------------
    @property
    def n_traces(self) -> int:
        """Distinct compiled bucket programs built so far (what the
        bucket-retrace tests assert on)."""
        return self._n_traces

    @property
    def mode(self) -> Optional[str]:
        return self._mode

    @property
    def analysis_report(self):
        return self._analysis_report

    # ---------------- bucketing ----------------
    def bucket_for(self, rows: int) -> int:
        """Smallest configured bucket >= ``rows``."""
        for b in self.bucket_sizes:
            if rows <= b:
                return b
        raise MXNetError(
            f"request of {rows} rows exceeds the largest shape bucket "
            f"({self.bucket_sizes[-1]}); raise bucket_sizes= or split "
            "the request")

    def pad_to_bucket(self, *args):
        """Pad every array leaf's leading dim up to the next bucket.
        Returns ``(padded_args, rows)`` — ``rows`` is the valid-row
        count (the mask): outputs beyond it are padding and must be
        sliced away."""
        leaves, treedef = jax.tree_util.tree_flatten(
            args, is_leaf=lambda t: isinstance(t, NDArray))
        rows = None
        for l in leaves:
            if isinstance(l, _ARRAY_TYPES) and \
                    getattr(_data_of(l), "ndim", 0) >= 1:
                rows = int(_data_of(l).shape[0])
                break
        if rows is None:
            raise MXNetError("pad_to_bucket: no array leaf with a "
                             "leading batch dim")
        bucket = self.bucket_for(rows)
        padded = [_pad_rows(l, bucket)
                  if isinstance(l, _ARRAY_TYPES) and
                  getattr(_data_of(l), "ndim", 0) >= 1 else l
                  for l in leaves]
        return jax.tree_util.tree_unflatten(treedef, padded), rows

    # ---------------- bucket cache ----------------
    def _flatten(self, args, kwargs):
        all_leaves, arg_treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda t: isinstance(t, NDArray))
        traced = [l for l in all_leaves if isinstance(l, _ARRAY_TYPES)]
        static_spec = tuple(_TRACED if isinstance(l, _ARRAY_TYPES) else l
                            for l in all_leaves)
        nd_mask = tuple(isinstance(l, NDArray) for l in traced)
        return traced, arg_treedef, static_spec, nd_mask

    def _entry_for(self, args, kwargs):
        traced, arg_treedef, static_spec, nd_mask = self._flatten(
            args, kwargs)
        shapes = tuple((tuple(_data_of(l).shape), str(_data_of(l).dtype))
                       for l in traced)
        sig = (arg_treedef, static_spec, nd_mask, shapes)
        entry = self._lru.get(sig)
        if entry is None:
            entry = self._build_bucket(arg_treedef, static_spec, nd_mask)
            t = _telemetry()
            t.registry().counter(t.names.COMPILE_RETRACES).inc()
            self._lru[sig] = entry
        else:
            self._lru.move_to_end(sig)
        return entry, traced

    def _build_bucket(self, arg_treedef, static_spec, nd_mask) -> dict:
        net = self._net
        params = self._params
        pred_self = self
        entry: dict = {"exe": None, "flops": None, "out_tree": None,
                       "analysis": None, "memory": None}

        def run(pds, traced_leaves, key):
            pred_self._n_traces += 1
            it = iter(NDArray(l) if m else l
                      for l, m in zip(traced_leaves, nd_mask))
            leaves = [next(it) if s is _TRACED else s
                      for s in static_spec]
            args, kwargs = jax.tree_util.tree_unflatten(arg_treedef,
                                                        leaves)
            binding = ParamBinding(params, pds)
            push_trace_key(key)
            # the inference fast path: taping SUSPENDED (no autograd
            # graph), recording off, eval mode — the forward is a pure
            # function of (params, inputs)
            prev_r = _tape.set_recording(False)
            prev_s = _tape.set_taping_suspended(True)
            prev_t = _tape.set_training(False)
            try:
                with binding:
                    out = net(*args, **kwargs)
            finally:
                _tape.set_recording(prev_r)
                _tape.set_taping_suspended(prev_s)
                _tape.set_training(prev_t)
                pop_trace_key()
            out_leaves, out_tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda t: isinstance(t, NDArray))
            entry["out_tree"] = out_tree
            return tuple(_data_of(l) if isinstance(l, _ARRAY_TYPES)
                         else jnp.asarray(l) for l in out_leaves)

        entry["fn"] = jax.jit(run)
        return entry

    # ---------------- call ----------------
    def predict(self, *args, **kwargs):
        """Dispatch one (bucketed) batch; returns the net's output
        structure with ASYNC NDArray leaves — no host sync happens in
        here (the transfer guard enforces it when armed). Inputs must
        already be bucket-shaped; pair with :meth:`pad_to_bucket` or
        the :class:`~mxnet_tpu.serving.DynamicBatcher`."""
        with _tguard.hot_scope("CompiledPredictor.predict"), \
                _edetect().device_lost_guard("CompiledPredictor.predict"):
            if self._mode is None:
                self._mode = "fused"
            if self._mode == "eager":
                out = self._eager_call(args, kwargs)
            else:
                try:
                    out = self._fused_call(args, kwargs)
                except Exception as e:
                    if self._requests_done:
                        raise   # proven program: a genuine error
                    _LOG.warning(
                        "CompiledPredictor: trace failed (%s: %s); "
                        "falling back to the eager forward",
                        type(e).__name__, e)
                    self._mode = "eager"
                    out = self._eager_call(args, kwargs)
            self._requests_done += 1
        if self._analyze is not None and self._analysis_report is None:
            self._run_analysis(args, kwargs)
        return out

    __call__ = predict

    def _fused_call(self, args, kwargs):
        entry, traced = self._entry_for(args, kwargs)
        pds = tuple(p._data._data for p in self._params)
        leaf_datas = tuple(_data_of(l) for l in traced)
        fn = entry["exe"] or entry["fn"]
        datas = fn(pds, leaf_datas, next_key())
        return jax.tree_util.tree_unflatten(
            entry["out_tree"], [NDArray(d) for d in datas])

    def _eager_call(self, args, kwargs):
        prev_r = _tape.set_recording(False)
        prev_t = _tape.set_training(False)
        try:
            return self._net(*args, **kwargs)
        finally:
            _tape.set_recording(prev_r)
            _tape.set_training(prev_t)

    # ---------------- AOT ----------------
    def aot_compile(self, *args, **kwargs):
        """Lower + compile this batch's bucket ahead of time and pin
        the executable (warm-started from ``MXNET_COMPILE_CACHE`` when
        armed); returns XLA's flop count for the program, or None where
        cost_analysis is unavailable."""
        if self._mode == "eager":
            return None
        entry, traced = self._entry_for(args, kwargs)
        if entry["exe"] is not None:
            return entry["flops"]
        pds = tuple(p._data._data for p in self._params)
        leaf_datas = tuple(_data_of(l) for l in traced)
        n_before = self._n_traces
        try:
            exe = entry["fn"].lower(pds, leaf_datas, next_key()).compile()
        except Exception as e:   # pragma: no cover - platform-dependent
            _LOG.warning("CompiledPredictor: AOT lower/compile "
                         "unavailable (%s); falling back to jit",
                         type(e).__name__)
            return None
        finally:
            # an AOT lower re-runs the traced python; the live jit call
            # for the same bucket will trace once more — count ONE
            # program per bucket, not the analysis artifacts
            self._n_traces = n_before
        self._n_traces += 1
        self._mode = "fused"
        entry["exe"] = exe
        try:
            ca = exe.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            f = float(ca.get("flops", 0.0))
            entry["flops"] = f if f > 0 else None
        except Exception:        # pragma: no cover - platform-dependent
            entry["flops"] = None
        return entry["flops"]

    @property
    def autotune_result(self):
        """The :class:`~mxnet_tpu.tuning.AutotuneOutcome` of the last
        ``warmup(autotune=)`` pass (None before warmup / mode off)."""
        return self._autotune_outcome

    def warmup(self, *example, buckets: Optional[Sequence[int]] = None,
               autotune: Optional[str] = None):
        """AOT-compile every shape bucket from one example request
        (a 1-row batch): each bucket's program is lowered + compiled
        before traffic arrives, so no live request ever pays a compile.
        Returns ``{bucket_size: flops}``.

        ``autotune`` (default: the ``MXNET_AUTOTUNE`` gate — docs/
        PERF_NOTES.md "Autotuner"): before compiling, replay or search
        this deployment's serving tunables (``serving.max_batch``,
        ``serving.batch_timeout_ms``); the tuned overrides govern any
        :class:`~mxnet_tpu.serving.DynamicBatcher` constructed AFTER
        warmup. Per-request results are bit-identical at any setting
        (the knobs are dispatch policy, not math)."""
        from .. import tuning as _tuning
        if _tuning.autotune_mode(autotune) != "off":
            try:
                self._autotune_outcome = _tuning.tune_predictor(
                    self, example, mode=autotune)
            except Exception as e:   # pragma: no cover - defensive
                _LOG.warning("CompiledPredictor: autotune failed "
                             "(%s: %s); serving with defaults",
                             type(e).__name__, e)
        out = {}
        last_padded = None
        for b in (buckets or self.bucket_sizes):
            padded = tuple(
                _pad_rows(l, b) if isinstance(l, _ARRAY_TYPES) and
                getattr(_data_of(l), "ndim", 0) >= 1 else l
                for l in example)
            out[b] = self.aot_compile(*padded)
            last_padded = padded
        # time ONE execution of the largest warmed bucket (compile
        # already paid above): the measured micro-batch service time
        # seeds the DynamicBatcher's admission EWMA, so deadline-based
        # shedding projects honestly from the very first request
        if last_padded is not None and example:
            try:
                t0 = time.perf_counter()
                res = self.predict(*last_padded)
                jax.block_until_ready([
                    _data_of(l) for l in jax.tree_util.tree_leaves(
                        res, is_leaf=lambda t: isinstance(t, NDArray))
                    if isinstance(l, _ARRAY_TYPES)])
                self.service_time_seed_s = time.perf_counter() - t0
            except Exception:    # pragma: no cover - warmup is advisory
                _LOG.debug("warmup timing execution failed",
                           exc_info=True)
        return out

    # ---------------- static analysis ----------------
    def lower_entry(self, *args, batch_size: Optional[int] = None,
                    **kwargs):
        """Lower this bucket's program for static analysis — the same
        artifact contract as ``CompiledTrainStep.lower_entry`` so the
        program lint (analysis/program.py) runs unchanged over serving
        programs. No retrace is counted; live params are untouched."""
        if self._mode == "eager":
            return None
        entry, traced = self._entry_for(args, kwargs)
        if entry.get("analysis") is not None:
            return entry["analysis"]
        pds = tuple(p._data._data for p in self._params)
        leaf_datas = tuple(_data_of(l) for l in traced)
        key = next_key()
        blessed = []
        if any(str(d.dtype) in ("bfloat16", "float16") for d in pds):
            # low-precision predictors keep norm layers in f32 by
            # design (amp.convert_hybrid_block) — widening back is
            # intentional there
            blessed = [("bfloat16", "float32"), ("float16", "float32")]
        n_before = self._n_traces
        try:
            fargs = (pds, leaf_datas, key)
            lowered = entry["fn"].lower(*fargs)
            try:
                jaxpr = jax.make_jaxpr(entry["fn"])(*fargs)
            except Exception:    # pragma: no cover - defensive
                jaxpr = None
        finally:
            self._n_traces = n_before
        info = dict(kind="predict", mode="predict", lowered=lowered,
                    jaxpr=jaxpr, mesh=None, axis=None,
                    expected_donated=None, unit_sizes=[],
                    n_params=len(pds), n_state_leaves=0,
                    blessed_dtypes=blessed, report=None)
        entry["analysis"] = info
        return info

    def analyze(self, *args, **kwargs):
        """Full program lint of this bucket's serving program
        (:class:`~mxnet_tpu.analysis.ProgramReport`): collective census
        (a single-device predict program must have none), host-transfer
        scan, dtype drift, fusion census — the same gates the training
        step passes (docs/ANALYSIS.md)."""
        from ..analysis.program import analyze_step
        return analyze_step(self, *args, **kwargs)

    def fusion_report(self, *args, **kwargs):
        report = self.analyze(*args, **kwargs)
        return getattr(report, "fusion", None)

    def memory_report(self, *args, **kwargs):
        """Static HBM footprint of this bucket's compiled program
        (:class:`~mxnet_tpu.telemetry.MemoryReport`); with no arguments,
        the field-wise max over every bucket analyzed so far."""
        t = _telemetry()
        if not args and not kwargs:
            reports = [e["memory"] for e in self._lru.values()
                       if e.get("memory") is not None]
            return t.memory.MemoryReport.merge(reports) if reports \
                else None
        if self._mode == "eager":
            return None
        entry, _ = self._entry_for(args, kwargs)
        if entry.get("memory") is not None:
            return entry["memory"]
        compiled = entry.get("exe")
        if compiled is None:
            info = self.lower_entry(*args, **kwargs)
            if info is None:
                return None
            compiled = info["lowered"].compile()
        report = t.memory.MemoryReport.from_compiled(compiled)
        entry["memory"] = report
        n_buckets = sum(1 for e in self._lru.values()
                        if e.get("memory") is not None)
        t.memory.register_compiled_report(
            f"predict:bucket{n_buckets}", report)
        return report

    def _run_analysis(self, args, kwargs):
        try:
            report = self.analyze(*args, **kwargs)
        except Exception as e:   # analysis must not kill serving
            _LOG.warning("CompiledPredictor: program analysis failed "
                         "(%s: %s); skipping", type(e).__name__, e)
            self._analysis_report = False
            return
        self._analysis_report = report
        if self._analyze == "warn" and not report.ok:
            _LOG.warning("CompiledPredictor program analysis:\n%s",
                         report.summary())
        elif self._analyze == "raise":
            report.raise_if_findings()


def predictor_for(net, dtype: str = "float32", calib_data=None,
                  calib_mode: str = "naive",
                  bucket_sizes: Optional[Sequence[int]] = None,
                  **kwargs) -> CompiledPredictor:
    """Build a predictor at the requested serving precision, reusing
    the training stack's conversion paths (docs/SERVING.md):

    - ``float32``/``fp32`` — the net as-is;
    - ``bfloat16``/``bf16``/``float16`` — ``amp.convert_hybrid_block``
      casts non-norm parameters down (norm layers stay f32);
    - ``int8`` — ``contrib.quantization.quantize_net`` calibrates on
      ``calib_data`` (required) and swaps Dense/Conv children for the
      INT8 MXU kernels.

    Conversion mutates ``net`` in place (the reference conversion
    contract); pass a copy to keep an f32 original.
    """
    d = dtype.lower()
    if d in ("float32", "fp32", "f32"):
        pass
    elif d in ("bfloat16", "bf16", "float16", "fp16"):
        from .. import amp as _amp
        _amp.convert_hybrid_block(
            net, "bfloat16" if d.startswith("b") else "float16")
    elif d == "int8":
        if calib_data is None:
            raise MXNetError("int8 serving needs calib_data= batches "
                             "for range calibration")
        from ..contrib.quantization import quantize_net
        quantize_net(net, calib_data, calib_mode=calib_mode)
    else:
        raise MXNetError(f"unknown serving dtype {dtype!r} (float32, "
                         "bfloat16, float16, int8)")
    return CompiledPredictor(net, bucket_sizes=bucket_sizes, **kwargs)
