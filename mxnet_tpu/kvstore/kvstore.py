"""KVStore implementations.

Reference analog: src/kvstore/ — KVStoreLocal's CPU/GPU Comm trees
(comm.h:104,452), KVStoreNCCL (kvstore_nccl.h:62), and the ps-lite
KVStoreDist (kvstore_dist.h). TPU-native collapse (SURVEY §2.3): ALL of those
become one 'tpu' backend. Single-process multi-device reduction is a jnp sum
(XLA inserts the device transfers); when arrays are sharded over a
jax.sharding Mesh, the reduction IS `psum` over the mesh axis and rides ICI;
multi-host uses the same code over a global mesh via jax.distributed
(DCN-spanning collectives) — see parallel/dist.py.

API parity: both the legacy int/str-keyed init/push/pull surface
(include/mxnet/kvstore.h:59-497) and the 2.0 broadcast/pushpull surface.
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..base import MXNetError, get_env
from ..ndarray.ndarray import NDArray
from .base import KVStoreBase

__all__ = ["KVStore", "KVStoreTPU", "create"]


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def _reduce_sum(values: List[NDArray]) -> NDArray:
    """Sum per-device replica arrays. XLA handles cross-device copies; with
    a sharded mesh array this lowers to psum over ICI (the CommDevice /
    CommDeviceTree / NCCL paths of the reference collapse here)."""
    if len(values) == 1:
        return NDArray(values[0]._data)
    acc = values[0]._data
    for v in values[1:]:
        acc = acc + v._data
    return NDArray(acc)


def _write_out(o: NDArray, result: NDArray) -> None:
    """Write a merged result into a caller's array. If the caller handle is
    row_sparse and the merged data is not its own (multi-replica or
    cross-process reduce changed the row set), refresh the aux arrays to the
    all-rows form so (indices, values) never go stale against the dense
    mirror — correctness first; the O(rows) lazy path is preserved on the
    common single-replica round-trip where the data object is unchanged."""
    from ..ndarray import sparse as nd_sparse
    if isinstance(o, nd_sparse.RowSparseNDArray) \
            and o._data is not result._data:
        import jax.numpy as _jnp
        o._aux = {"indices": NDArray(_jnp.arange(result._data.shape[0],
                                                 dtype=_jnp.int32)),
                  "values": NDArray(result._data)}
    o._data = result._data


@KVStoreBase.register
class KVStoreTPU(KVStoreBase):
    """The 'tpu' backend (reference north star: kvstore='tpu').

    Also serves as 'local'/'device'/'nccl' — on TPU those distinctions
    (CPU-reduce vs GPU merge-buffer vs NCCL ring) are mesh-layout choices
    XLA makes, not code paths.
    """

    def __init__(self, name: str = "tpu"):
        self._name = name
        self._store: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._opt_states: Dict[str, tuple] = {}
        self._compression = None

    @property
    def type(self) -> str:
        return self._name

    # ---------------- 2.0 API ----------------
    def broadcast(self, key, value, out, priority=0):
        value = _as_list(value)
        merged = _reduce_sum(value) if len(value) > 1 else value[0]
        self._store[str(key)] = NDArray(merged._data)
        for o in _as_list(out):
            o._data = merged._data
        return out

    def _merge(self, values: List[NDArray]) -> NDArray:
        """Reduce per-device replica values to one array. KVStoreDist
        overrides this with a cross-process collective."""
        return _reduce_sum(values)

    def _compressed(self, key, values: List[NDArray]) -> List[NDArray]:
        """Wire-compression applied before merge (reference compresses on
        push, kvstore_dist.h). Returns NEW arrays; callers must keep the
        originals for result writeback."""
        if self._compression is None:
            return values
        return [self._compression.compress_decompress(v, (str(key), i))
                for i, v in enumerate(values)]

    def pushpull_list(self, keys, values, outs=None, priority=0):
        """Multi-key pushpull (reference analog: the engine queues one op
        per key and ps-lite batches the wire traffic, kvstore_dist.h).
        Base store: per-key loop — a single-process reduce is already one
        XLA dispatch per key with async dispatch, nothing to fuse.
        KVStoreDist overrides with fused bucketed collectives."""
        outs = [None] * len(keys) if outs is None else outs
        return [self.pushpull(k, v, out=o, priority=priority)
                for k, v, o in zip(keys, values, outs)]

    def pushpull(self, key, value, out=None, priority=0):
        values = _as_list(value)
        outs_alias = out is None or out is value or (
            len(_as_list(out)) == len(values)
            and all(o is v for o, v in zip(_as_list(out), values)))
        if (len(values) == 1 and self._updater is None
                and self._compression is None and self.num_workers == 1
                and outs_alias):
            # single replica, no store-side transform: the reduce is the
            # identity. Skip it WITHOUT touching v._data so a lazy
            # row_sparse gradient's dense mirror is never materialized
            # (the O(rows) Embedding path).
            return value if out is None else out
        merged = self._merge(self._compressed(key, values))
        if self._updater is not None:
            skey = str(key)
            if skey not in self._store:
                self._store[skey] = NDArray(merged._data)
            self._updater(key, merged, self._store[skey])
            result = self._store[skey]
        else:
            result = merged
        if out is None:
            # write back into the caller's arrays (NOT the compressed
            # copies _compressed returned)
            for v in values:
                _write_out(v, result)
            return value
        for o in _as_list(out):
            _write_out(o, result)
        return out

    # ---------------- legacy API (reference kvstore.h) ----------------
    def init(self, key, value):
        keys = _as_list(key) if isinstance(key, (list, tuple)) else [key]
        values = _as_list(value)
        for k, v in zip(keys, values):
            self._store[str(k)] = NDArray(v._data)

    def push(self, key, value, priority=0):
        keys = key if isinstance(key, (list, tuple)) else [key]
        if len(keys) == 1:
            grouped = {str(keys[0]): _as_list(value)}
        else:
            grouped = {}
            for k, v in zip(keys, value):
                grouped.setdefault(str(k), []).extend(_as_list(v))
        for k, vals in grouped.items():
            merged = self._merge(self._compressed(k, vals))
            if self._updater is not None:
                if k not in self._store:
                    self._store[k] = NDArray(merged._data)
                self._updater(_int_or_str(k), merged, self._store[k])
            else:
                self._store[k] = merged

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = _as_list(out)
        if len(keys) == 1:
            for o in outs:
                _write_out(o, self._store[str(keys[0])])
        else:
            for k, o in zip(keys, outs):
                for oo in _as_list(o):
                    _write_out(oo, self._store[str(k)])
        return out

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Sparse pull (reference kvstore row_sparse_pull): gathers only the
        requested rows."""
        from ..ndarray import sparse as nd_sparse
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = _as_list(out)
        rids = _as_list(row_ids)
        for k, o, r in zip(keys, outs, rids):
            full = self._store[str(k)]
            rows = r._data.astype(jnp.int32)
            vals = jnp.take(full._data, rows, axis=0)
            o._data = jnp.zeros_like(full._data).at[rows].set(vals)
        return out

    # ---------------- optimizer-on-store ----------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        from ..optimizer import get_updater
        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def is_capable(self, capability: str) -> bool:
        return capability in ("optimizer", "int_keys")

    # ---------------- compression ----------------
    def set_gradient_compression(self, compression_params):
        from ..parallel.compression import GradientCompression
        self._compression = GradientCompression(**compression_params)

    # ---------------- fused-step integration ----------------
    @property
    def in_program_reduce(self) -> bool:
        """True when the gradient reduction this store performs can live
        INSIDE one compiled train step (``Trainer.compile_step``): a
        single-process store holds ONE logical array per parameter, so
        the reduce is the identity (mesh-sharded arrays get their psum
        inserted by XLA under jit). Stores that must cross a process
        boundary (KVStoreDist with >1 worker) return False and the fused
        step falls back to a host-side ``pushpull_list`` between its
        gradient and update programs."""
        return True

    @property
    def in_program_reduce_scatter(self) -> bool:
        """True when the in-program reduction may additionally lower to
        the ZeRO-1 decomposition (reduce-scatter → shard-local optimizer
        update → all-gather, arXiv:2004.13336) instead of a plain psum —
        the path ``Trainer.compile_step`` takes on a dp mesh. Single-
        process stores hold one logical array per parameter, so XLA is
        free to re-associate the reduction; stores that cannot reduce
        in-program (``in_program_reduce`` False) cannot reduce-scatter
        in-program either."""
        return self.in_program_reduce

    # ---------------- topology ----------------
    @property
    def rank(self) -> int:
        try:
            return jax.process_index()
        except Exception:
            return 0

    @property
    def num_workers(self) -> int:
        try:
            return jax.process_count()
        except Exception:
            return 1

    def barrier(self):
        """Global sync point (reference kvstore barrier). Within one process
        this is a device sync; multi-host riding jax.distributed it is a
        cross-host barrier."""
        from .. import engine
        engine.get().wait_for_all()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _int_or_str(k: str):
    try:
        return int(k)
    except ValueError:
        return k


class KVStoreDist(KVStoreTPU):
    """Multi-host store (reference kvstore_dist.h over ps-lite). TPU-native:
    rides the jax.distributed runtime — every worker contributes its local
    gradient as one shard of a global array over a one-device-per-process
    mesh, and a jitted SPMD sum issues the DCN-spanning allreduce (the
    successor of ps::KVWorker::ZPush/ZPull, reference kvstore_dist.h:44-157,
    and the server-side merge kvstore_dist_server.h:330-359). Requires
    jax.distributed.initialize() (see parallel/dist.py launch helper).

    Sync vs async (reference kvstore_dist_server.h:164-206): in sync mode
    every pushpull blocks until the merged value is materialized — all
    workers advance in lockstep. In async mode the collective is *dispatched*
    but not waited on (JAX async dispatch), so a worker continues into its
    next step while the reduction is in flight; ordering per key is still
    preserved by XLA's program order, which is strictly stronger than
    ps-lite async (no unbounded staleness).
    """

    def __init__(self, name: str = "dist_sync"):
        super().__init__(name)
        self._async = "async" in name
        self._mesh = None
        self._sum_fns = {}  # keyed by mesh (weak-ref by id is unsafe;
        # the mesh object itself is hashable and tiny)
        # observability: collective dispatches and host syncs per store —
        # the quantities the batched path exists to shrink
        self.stats = {"collectives": 0, "blocks": 0}

    @property
    def in_program_reduce(self) -> bool:
        """Cross-process reduction cannot be traced into a single-process
        jit program (it rides make_array_from_single_device_arrays over a
        worker mesh); with >1 worker — or when tests force the fused
        bucketed path via ``_force_fuse`` — the compiled train step must
        route gradients through host-side ``pushpull_list``."""
        return jax.process_count() == 1 and not getattr(
            self, "_force_fuse", False)

    # -------- cross-process collective machinery --------
    def _worker_mesh(self):
        """One device per process, ordered by process index — the 'worker'
        axis every cross-host reduction runs over."""
        if self._mesh is None:
            import numpy as onp
            from jax.sharding import Mesh
            per_proc = {}
            for d in jax.devices():
                per_proc.setdefault(d.process_index, d)
            devs = [per_proc[i] for i in sorted(per_proc)]
            self._mesh = Mesh(onp.array(devs), ("worker",))
        return self._mesh

    def _dispatch_sum(self, x: jax.Array) -> jax.Array:
        """Dispatch (without waiting) the worker-axis allreduce of one array.

        Each process donates its local value as the shard at index
        process_index of a (num_workers, *shape) global array; a jitted sum
        over the worker axis makes XLA emit the cross-host all-reduce.
        All workers must call this in the same per-key order (the reference's
        sync contract; kvstore.h:129-141 engine-ordering analog)."""
        nproc = jax.process_count()
        if nproc == 1:
            return x
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self._worker_mesh()
        local_dev = mesh.devices.flat[jax.process_index()]
        xl = jax.device_put(x, local_dev)[None]
        gshape = (nproc,) + tuple(x.shape)
        garr = jax.make_array_from_single_device_arrays(
            gshape, NamedSharding(mesh, PartitionSpec("worker")), [xl])
        fn = self._sum_fns.get(mesh)
        if fn is None:
            # keyed by mesh: a store surviving a mesh change (device set
            # changed) must rebuild out_shardings, not silently reuse them
            fn = self._sum_fns[mesh] = jax.jit(
                lambda a: jnp.sum(a, axis=0),
                out_shardings=NamedSharding(mesh, PartitionSpec()))
        self.stats["collectives"] += 1
        return fn(garr)

    def _block(self, out) -> None:
        """One host sync over an array or a whole batch of them."""
        self.stats["blocks"] += 1
        jax.block_until_ready(out)

    def _cross_process_sum(self, x: jax.Array) -> jax.Array:
        """Allreduce one array; in sync mode, wait for it (one host sync
        PER KEY — the batched pushpull_list path amortizes this)."""
        if jax.process_count() == 1:
            return x
        out = self._dispatch_sum(x)
        if not self._async:
            self._block(out)
        return out.addressable_data(0)

    # -------- overridden reduction point --------
    def _merge(self, values: List[NDArray]) -> NDArray:
        """Local replica reduce, then the worker-axis allreduce; push and
        pushpull (and their compression hook) are inherited unchanged."""
        local = _reduce_sum(values)
        return NDArray(self._cross_process_sum(local._data))

    # -------- fused multi-key path --------
    def pushpull_list(self, keys, values, outs=None, priority=0):
        """Fused multi-key pushpull (reference: ps-lite message batching +
        kvstore_dist.h big-array slicing, MXNET_KVSTORE_SLICE_THRESHOLD):
        per-key local reductions are flattened and packed into few
        dtype-homogeneous bucketed collectives, ALL dispatched before any
        wait, with ONE host sync per call in sync mode — vs one device_put
        + collective + block per key on the scalar path (a ResNet-scale
        model pays ~160 sequential host syncs per step there).

        Row-sparse values keep the per-key path (their merge is
        value-dependent); single-process stores keep the base loop (its
        identity shortcut preserves the lazy O(rows) gradient path)."""
        outs = [None] * len(keys) if outs is None else outs
        if jax.process_count() == 1 and not getattr(self, "_force_fuse",
                                                    False):
            return super().pushpull_list(keys, values, outs, priority)
        from ..ndarray import sparse as nd_sparse
        results: List = [None] * len(keys)
        dense = []  # (pos, str_key, caller_values, local_sum jax.Array)
        for i, (k, v) in enumerate(zip(keys, values)):
            vals = _as_list(v)
            if any(isinstance(x, nd_sparse.RowSparseNDArray) for x in vals):
                results[i] = self.pushpull(k, v, out=outs[i],
                                           priority=priority)
                continue
            local = _reduce_sum(self._compressed(k, vals))
            dense.append((i, str(k), vals, local._data))

        # pack into dtype-homogeneous buckets of <= threshold elements; an
        # oversize array forms its own bucket (one collective moves any
        # size — the reference slices because ps-lite messages cannot)
        thresh = int(get_env("MXNET_KVSTORE_SLICE_THRESHOLD", 4 << 20,
                             int))
        # group by dtype FIRST (not by adjacency: an interleaved
        # f32/i32/f32 list must still form one bucket per dtype), then
        # split oversize groups at the threshold. Deterministic across
        # workers: dict insertion order follows the shared key order.
        by_dtype: Dict[str, list] = {}
        for item in dense:
            by_dtype.setdefault(str(item[3].dtype), []).append(item)
        buckets = []
        for items in by_dtype.values():
            cur, cur_n = [], 0
            for item in items:
                arr = item[3]
                if cur and cur_n + arr.size > thresh:
                    buckets.append(cur)
                    cur, cur_n = [], 0
                cur.append(item)
                cur_n += arr.size
            if cur:
                buckets.append(cur)

        pending = []
        for b in buckets:
            buf = b[0][3].ravel() if len(b) == 1 else \
                jnp.concatenate([it[3].ravel() for it in b])
            pending.append((b, self._dispatch_sum(buf)))
        if not self._async and pending and jax.process_count() > 1:
            self._block([g for _, g in pending])

        for b, garr in pending:
            flat = garr.addressable_data(0) \
                if jax.process_count() > 1 else garr
            off = 0
            for i, skey, vals, local in b:
                n = local.size
                merged = NDArray(flat[off:off + n].reshape(local.shape))
                off += n
                if self._updater is not None:
                    if skey not in self._store:
                        self._store[skey] = NDArray(merged._data)
                    self._updater(_int_or_str(skey), merged,
                                  self._store[skey])
                    result = self._store[skey]
                else:
                    result = merged
                o = outs[i]
                if o is None:
                    for vv in vals:
                        _write_out(vv, result)
                    results[i] = values[i]
                else:
                    for oo in _as_list(o):
                        _write_out(oo, result)
                    results[i] = o
        return results

    def broadcast(self, key, value, out, priority=0):
        """Rank 0's value wins (reference: server holds init value; workers
        pull it). Implemented as a worker-axis sum where non-root workers
        contribute zeros."""
        value = _as_list(value)
        local = _reduce_sum(value) if len(value) > 1 else value[0]
        data = local._data
        if jax.process_count() > 1:
            if jax.process_index() != 0:
                data = jnp.zeros_like(data)
            data = self._cross_process_sum(data)
        self._store[str(key)] = NDArray(data)
        for o in _as_list(out):
            o._data = data
        return out

    def init(self, key, value):
        keys = _as_list(key) if isinstance(key, (list, tuple)) else [key]
        values = _as_list(value)
        for k, v in zip(keys, values):
            self.broadcast(k, v, out=[v])

    def barrier(self):
        """Cross-host barrier (reference ps::Postoffice barrier)."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("mxnet_tpu_kvstore_barrier")
        else:
            super().barrier()


# name → class resolution (reference factory kvstore.cc:41-79)
_ALIASES = {
    "local": KVStoreTPU, "device": KVStoreTPU, "tpu": KVStoreTPU,
    "nccl": KVStoreTPU,
    "dist": KVStoreDist, "dist_sync": KVStoreDist, "dist_async": KVStoreDist,
    "dist_device_sync": KVStoreDist, "p3": KVStoreDist,
}


def create(name: str = "local") -> KVStoreTPU:
    """Create a KVStore (reference kvstore.create / factory
    src/kvstore/kvstore.cc:41)."""
    if isinstance(name, KVStoreBase):
        return name
    lname = name.lower()
    if lname in _ALIASES:
        return _ALIASES[lname](lname)
    if lname in KVStoreBase.kv_registry:
        return KVStoreBase.kv_registry[lname]()
    raise MXNetError(f"unknown kvstore type {name!r}")


KVStore = KVStoreTPU
