"""KVStore: data-parallel gradient aggregation (reference: src/kvstore/ +
python/mxnet/kvstore/)."""
from .base import KVStoreBase
from .kvstore import KVStore, KVStoreTPU, create
from . import base, kvstore
