"""Pluggable KVStore backend registry.

Reference analog: python/mxnet/kvstore/base.py:74,220 — KVStoreBase.register
lets Horovod/BytePS-style backends plug in by name. Here the default backend
is 'tpu' (ICI collectives); the registry is preserved so external backends
can still be registered.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..base import MXNetError

__all__ = ["KVStoreBase"]


class KVStoreBase:
    """Backend interface: broadcast + pushpull (2.0-era API; reference
    kvstore/base.py)."""

    kv_registry: Dict[str, type] = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        KVStoreBase.kv_registry[name] = klass
        return klass

    @staticmethod
    def is_capable(capability: str) -> bool:
        return capability in ("optimizer", "int_keys")

    # ---- interface ----
    def broadcast(self, key, value, out, priority=0):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    @property
    def type(self) -> str:
        return type(self).__name__.lower()

    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1
