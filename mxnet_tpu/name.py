"""Automatic symbol naming scopes.

Reference analog: python/mxnet/name.py (:21 NameManager, :71 Prefix) —
same contract: a context-local manager turns (user name | None, hint)
into a canonical name, counting per hint; ``Prefix`` prepends a string.
Consumed by ``mx.sym`` op construction (symbol/__init__.py).
"""
import contextvars

__all__ = ["NameManager", "Prefix", "current"]


class NameManager:
    """Automatic naming: user-specified names pass through; otherwise
    ``<hint><n>`` with a per-hint counter. Use as a context manager to
    install for the enclosed symbol constructions."""

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        if name:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def __enter__(self):
        self._old_manager = _current.get()
        _current.set(self)
        return self

    def __exit__(self, ptype, value, trace):
        _current.set(self._old_manager)


class Prefix(NameManager):
    """Name manager that attaches a prefix to every generated or
    user-given name (reference name.py:71)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)


_current = contextvars.ContextVar("namemanager", default=NameManager())


def current():
    """The active name manager."""
    return _current.get()
