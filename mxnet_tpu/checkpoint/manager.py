"""Checkpoint lifecycle: retention, async writes, auto-resume.

``TrainCheckpointManager`` drives the atomic format (atomic.py) with the
policy a long training run needs:

- ``save(step, trainer, net)`` captures device state synchronously (one
  device->host copy per buffer — the only part that must pause
  training) and hands serialization + fsync + commit to a background
  thread, overlapped with the next training steps;
- a failed background write surfaces on the NEXT ``save``/``wait`` —
  never silently;
- after each commit the newest ``keep_last`` checkpoints are kept and
  older ones pruned (prune runs strictly after publish, so a crash
  mid-prune can never reduce the directory below its newest valid
  checkpoint);
- ``restore_latest`` loads the newest checkpoint that VALIDATES
  (corrupt/truncated ones are skipped with a warning) and applies it;
- under multi-host ``parallel.dist`` each process stages into its own
  ``host-<rank>/`` subtree (one atomic commit per host, no cross-host
  write races); restore merges every host's segment files.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from ..analysis.threads import mx_lock
from ..base import MXNetError
from . import atomic
from .state import TrainState, apply_train_state, capture_train_state

__all__ = ["TrainCheckpointManager"]

_LOG = logging.getLogger("mxnet_tpu.checkpoint")

_TELEM = None


def _telemetry():
    global _TELEM
    if _TELEM is None:
        from .. import telemetry as _t
        _TELEM = _t
    return _TELEM


def _dist_rank_size():
    try:
        from ..parallel import dist
        return dist.rank(), dist.size()
    except Exception:        # pragma: no cover - parallel not importable
        return 0, 1


class TrainCheckpointManager:
    """Step-indexed atomic train-state checkpoints with retention.

    ::

        mgr = mx.checkpoint.TrainCheckpointManager(dir, keep_last=3)
        ...
        mgr.save(step, trainer=trainer, net=net)     # async by default
        ...
        meta = mgr.restore_latest(trainer=trainer, net=net)
        start = meta["step"] if meta else 0

    ``gluon.TrainLoop(checkpoint_dir=...)`` wraps exactly this.
    """

    def __init__(self, directory: str, keep_last: int = 3,
                 async_save: bool = True):
        if keep_last < 1:
            raise MXNetError(f"keep_last must be >= 1, got {keep_last}")
        self._base = os.path.abspath(directory)
        rank, size = _dist_rank_size()
        self._rank, self._size = rank, size
        self._root = self._base if size == 1 else \
            os.path.join(self._base, f"host-{rank}")
        self._keep_last = keep_last
        self._async = async_save
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # guards the writer handoff (_thread/_error) between save(),
        # wait() and the background writer; the join itself runs
        # outside it so waiters never block each other behind slow I/O
        self._mu = mx_lock("checkpoint.manager")
        self._last_saved: Optional[int] = None
        t = _telemetry()
        reg = t.registry()
        self._m_saves = reg.counter(t.names.CHECKPOINT_SAVES)
        self._m_errors = reg.counter(t.names.CHECKPOINT_ERRORS)
        self._m_capture = reg.histogram(t.names.CHECKPOINT_CAPTURE_SECONDS)
        self._m_write = reg.histogram(t.names.CHECKPOINT_SAVE_SECONDS)
        self._m_restores = reg.counter(t.names.CHECKPOINT_RESTORES)
        self._m_recovery = reg.histogram(
            t.names.CHECKPOINT_RECOVERY_SECONDS)
        self._last_restore: Optional[Dict[str, Any]] = None

    @property
    def directory(self) -> str:
        return self._base

    # ---------------- save ----------------
    def save(self, step: int, trainer=None, net=None,
             extra: Optional[Dict[str, Any]] = None,
             block: Optional[bool] = None) -> TrainState:
        """Capture (synchronously) and persist (async unless
        ``block=True``/``async_save=False``) the full train state."""
        self.wait()   # one write in flight; surfaces any prior failure
        t0 = time.perf_counter()
        state = capture_train_state(trainer=trainer, net=net, step=step,
                                    extra=extra)
        self._m_capture.observe(time.perf_counter() - t0)
        if self._last_restore is not None:
            # restore provenance rides every subsequent save: a
            # post-mortem on this checkpoint can tell WHERE the run it
            # belongs to came from (elastic reshard forensics)
            state.meta.setdefault("resumed_from", {
                k: self._last_restore[k]
                for k in ("step", "resumed_from", "dp_from", "dp_to")})
        try:
            # the capture copies live until the background write drops
            # them — visible in the census `checkpoint` pool meanwhile
            _telemetry().memory.census().register("checkpoint", state)
        except Exception:        # pragma: no cover - census must never
            pass                 # block a save
        sync = not self._async if block is None else block
        if sync:
            self._write(state)
        else:
            t = threading.Thread(
                target=self._write_guarded, args=(state,),
                name=f"ckpt-write-step{step}", daemon=True)
            with self._mu:
                self._thread = t
            t.start()
        return state

    def save_state(self, state: TrainState):
        """Persist an already-captured TrainState synchronously."""
        self.wait()
        self._write(state)

    def _write_guarded(self, state: TrainState):
        try:
            self._write(state)
        except BaseException as e:   # propagate via wait()/next save()
            _LOG.error("async checkpoint write for step %d failed: %s",
                       state.step, e)
            self._m_errors.inc()
            with self._mu:
                self._error = e

    def _write(self, state: TrainState):
        t0 = time.perf_counter()
        atomic.write_checkpoint(self._root, state.step, state.arrays,
                                array_meta=state.array_meta,
                                meta=state.meta)
        self._last_saved = state.step
        atomic.prune_checkpoints(self._root, self._keep_last)
        t1 = time.perf_counter()
        self._m_write.observe(t1 - t0)
        self._m_saves.inc()
        t = _telemetry()
        if t.active():
            # runs on the background writer thread for async saves; the
            # timeline ring + histogram are thread-safe
            t.timeline().record("checkpoint", t0, t1, step=state.step)

    def wait(self):
        """Block until the in-flight write finishes; re-raise its error."""
        with self._mu:
            t, self._thread = self._thread, None
        if t is not None:
            t.join()        # outside the lock: never join while holding it
        with self._mu:
            err, self._error = self._error, None
        if err is not None:
            raise MXNetError(
                f"background checkpoint write failed: {err}") from err

    # ---------------- query ----------------
    def latest_step(self) -> Optional[int]:
        found = atomic.latest_valid(self._root)
        return found[0] if found else None

    def has_checkpoint(self) -> bool:
        return self.latest_step() is not None

    def latest_path(self) -> Optional[str]:
        """Directory of the newest VALID checkpoint, or None — the
        path a serving-side weight rollout loads
        (:meth:`~mxnet_tpu.serving.FleetController.swap_weights`
        accepts it directly; corrupt candidates are already skipped
        here, and the fleet re-validates before any replica drains)."""
        found = atomic.latest_valid(self._root)
        return found[1] if found else None

    @property
    def last_saved_step(self) -> Optional[int]:
        return self._last_saved

    # ---------------- restore ----------------
    def _load_merged(self):
        """Newest step valid on every host (merging per-host segment
        files); single-host: newest valid step."""
        if self._size == 1:
            return atomic.load_latest(self._root)
        # pragma: no cover start - exercised only on multi-host rigs
        hosts = [d for d in sorted(os.listdir(self._base))
                 if d.startswith("host-") and
                 os.path.isdir(os.path.join(self._base, d))]
        valid: Dict[int, list] = {}
        for h in hosts:
            sub = os.path.join(self._base, h)
            for s in atomic.list_checkpoints(sub):
                valid.setdefault(s, []).append(sub)
        for s in sorted(valid, reverse=True):
            if len(valid[s]) != len(hosts):
                continue
            arrays: Dict[str, Any] = {}
            manifest = None
            try:
                for sub in valid[s]:
                    a, m = atomic.read_checkpoint(
                        os.path.join(sub, atomic.step_dir_name(s)))
                    arrays.update(a)
                    manifest = m
                return s, arrays, manifest
            except atomic.CheckpointCorruptError as e:
                _LOG.warning("skipping corrupt multi-host step %d: %s",
                             s, e)
        return None
        # pragma: no cover end

    def restore_latest(self, trainer=None, net=None,
                       strict: bool = True) -> Optional[Dict[str, Any]]:
        """Apply the newest valid checkpoint; returns its meta (incl.
        'step'), or None when the directory holds no valid checkpoint."""
        self.wait()
        t0 = time.perf_counter()
        found = self._load_merged()
        if found is None:
            return None
        return self._apply_found(found, trainer, net, strict, t0)

    def restore_step(self, step: int, trainer=None, net=None,
                     strict: bool = True) -> Dict[str, Any]:
        """Apply ONE SPECIFIC retained checkpoint step (raises if it is
        missing or corrupt) — the elastic reference-replay / planned
        rollback path, where "newest" is not the state you want."""
        self.wait()
        t0 = time.perf_counter()
        path = os.path.join(self._root, atomic.step_dir_name(step))
        arrays, manifest = atomic.read_checkpoint(path)
        return self._apply_found((step, arrays, manifest), trainer, net,
                                 strict, t0)

    def _apply_found(self, found, trainer, net, strict, t0):
        """Shared restore tail: apply + restore metrics + provenance."""
        step, arrays, manifest = found
        array_meta = {k: v for k, v in manifest["arrays"].items()}
        state = TrainState(arrays, manifest.get("meta", {}),
                           array_meta=array_meta)
        meta = apply_train_state(state, trainer=trainer, net=net,
                                 strict=strict)
        _LOG.info("restored checkpoint step %d from %s", step, self._root)
        meta = dict(meta)
        meta.setdefault("step", step)
        dt = time.perf_counter() - t0
        self._m_restores.inc()
        self._m_recovery.observe(dt)
        dp_from = meta.get("dp_size")
        dp_to = self._current_dp()
        self._last_restore = {
            "step": int(step),
            "resumed_from": os.path.join(self._root,
                                         atomic.step_dir_name(step)),
            "dp_from": dp_from, "dp_to": dp_to,
            "reshard": (f"dp{dp_from}->dp{dp_to}"
                        if dp_from and dp_from != dp_to else None),
            "duration_s": dt, "time_unix": time.time()}
        if self._last_restore["reshard"]:
            _LOG.info("restore reshards %s",
                      self._last_restore["reshard"])
        return meta

    @staticmethod
    def _current_dp() -> Optional[int]:
        try:
            from ..parallel.mesh import current_mesh
            m = current_mesh()
            return int(m.shape.get("dp", 1)) if m is not None else 1
        except Exception:        # pragma: no cover - defensive
            return None

    @property
    def restore_provenance(self) -> Optional[Dict[str, Any]]:
        """Where the current run's state came from: ``{step,
        resumed_from, dp_from, dp_to, reshard, duration_s, time_unix}``
        of the most recent restore through this manager (None before
        any restore). ``reshard`` names a dp=N→dp=M layout change, the
        elastic shrink/grow signature."""
        return self._last_restore
