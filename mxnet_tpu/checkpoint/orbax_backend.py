"""Checkpoint / resume (reference: NDArray serialization ndarray.h:399-411,
Gluon save_parameters/load_parameters block.py:339,375, Trainer
save_states/load_states trainer.py:477,506 — all single-file, rank-0
writes; the reference has NO sharded/distributed checkpointing, SURVEY §5).

TPU-native extension: orbax-backed checkpoints that save/restore the full
training state (parameters + optimizer state + step + bias-correction
counters) atomically, with a retention policy. Restore re-applies each
parameter onto the live array's sharding (a sharded param stays sharded).
Arrays are materialized on host during restore — for models too large for
one host's memory, drive orbax's abstract-target restore directly. The
reference-parity single-file paths (``nd.save``/``save_parameters``/
``Trainer.save_states``) remain the simple route.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointManager"]


def _state_tree(net=None, trainer=None, extra=None) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    if net is not None:
        tree["params"] = {k: v._data._data for k, v in
                          net.collect_params().items()
                          if v._data is not None}
    if trainer is not None:
        states = {}
        upd = getattr(trainer, "_updater", None)
        if upd is not None:
            for idx, st in upd.states.items():
                states[str(idx)] = _flatten_state(st)
        tree["optimizer"] = states
        # bias-correction counters (reference get_states dump_optimizer=True
        # keeps num_update/index counts so Adam-style steps resume exactly)
        opt = getattr(trainer, "_optimizer", None)
        if opt is not None:
            tree["opt_counts"] = {
                "num_update": onp.asarray(opt.num_update),
                "index_keys": onp.asarray(
                    sorted(opt._index_update_count), dtype=onp.int64),
                "index_vals": onp.asarray(
                    [opt._index_update_count[k]
                     for k in sorted(opt._index_update_count)],
                    dtype=onp.int64),
            }
    if extra:
        tree["extra"] = {k: onp.asarray(v) for k, v in extra.items()}
    return tree


def _flatten_state(st):
    import jax
    leaves, _ = jax.tree_util.tree_flatten(
        st, is_leaf=lambda t: isinstance(t, NDArray))
    return [l._data if isinstance(l, NDArray) else l for l in leaves]


def _unflatten_into(st, leaves):
    import jax
    flat, treedef = jax.tree_util.tree_flatten(
        st, is_leaf=lambda t: isinstance(t, NDArray))
    new = [NDArray(d) if isinstance(o, NDArray) else type(o)(d)
           for o, d in zip(flat, leaves)]
    return jax.tree_util.tree_unflatten(treedef, new)


def save_checkpoint(path: str, net=None, trainer=None, step: int = 0,
                    extra: Optional[Dict] = None):
    """Atomically save params (+ optimizer state, + user extras) to an
    orbax checkpoint directory."""
    import orbax.checkpoint as ocp
    tree = _state_tree(net, trainer, extra)
    tree["step"] = onp.asarray(step)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.abspath(path), tree, force=True)
    ckptr.wait_until_finished()


def load_checkpoint(path: str, net=None, trainer=None) -> Dict[str, Any]:
    """Restore a checkpoint in place; returns the raw tree (incl. 'step'
    and 'extra')."""
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    tree = ckptr.restore(os.path.abspath(path))
    _apply_tree(tree, net, trainer)
    return tree


class CheckpointManager:
    """Step-indexed checkpoints with retention (orbax CheckpointManager):
    ``save(step, net, trainer)`` / ``restore_latest(net, trainer)``."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, net=None, trainer=None,
             extra: Optional[Dict] = None):
        import orbax.checkpoint as ocp
        tree = _state_tree(net, trainer, extra)
        tree["step"] = onp.asarray(step)
        self._mgr.save(step, args=ocp.args.StandardSave(tree))
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore_latest(self, net=None, trainer=None) -> Dict[str, Any]:
        import orbax.checkpoint as ocp
        step = self._mgr.latest_step()
        if step is None:
            raise MXNetError(f"no checkpoints under {self._dir}")
        tree = self._mgr.restore(step)
        _apply_tree(tree, net, trainer)
        return tree


def _apply_tree(tree, net, trainer):
    import jax
    import jax.numpy as jnp
    if net is not None and "params" in tree:
        params = net.collect_params()
        for k, p in params.items():
            if k in tree["params"]:
                arr = jnp.asarray(tree["params"][k])
                cur = p._data
                # preserve the live parameter's sharding: restoring must
                # not silently replace a sharded array with a replicated one
                if cur is not None and hasattr(cur._data, "sharding"):
                    arr = jax.device_put(arr, cur._data.sharding)
                p.set_data(NDArray(arr))
    if trainer is not None and tree.get("optimizer"):
        upd = getattr(trainer, "_updater", None)
        if upd is not None:
            t_params = list(getattr(trainer, "_params", []))
            for idx_s, leaves in tree["optimizer"].items():
                idx = int(idx_s)
                if idx not in upd.states:
                    # natural resume flow: load before the first step().
                    # Allocate the typed state so the saved moments are
                    # applied — dropping them while num_update advances
                    # would silently run Adam with zero moments at t=N.
                    if idx < len(t_params) and \
                            t_params[idx]._data is not None:
                        upd.states[idx] = \
                            upd.optimizer.create_state_multi_precision(
                                idx, t_params[idx].data())
                    else:
                        raise MXNetError(
                            f"cannot restore optimizer state {idx}: "
                            "trainer has no initialized parameter there")
                upd.states[idx] = _unflatten_into(upd.states[idx], leaves)
    if trainer is not None and "opt_counts" in tree:
        opt = getattr(trainer, "_optimizer", None)
        if opt is not None:
            oc = tree["opt_counts"]
            opt.num_update = int(oc["num_update"])
            opt._index_update_count = {
                int(k): int(v) for k, v in zip(oc["index_keys"],
                                               oc["index_vals"])}
