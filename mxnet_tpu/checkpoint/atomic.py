"""Atomic, checksummed checkpoint directories.

The reference's persistence is a bare file write (``NDArray::Save``,
include/mxnet/ndarray.h:399) — a kill mid-write clobbers the previous
good file. Preemptible TPU pods need the database discipline instead
(arXiv:1605.08695 §4.3 periodic checkpoint/restore): every checkpoint is

1. **staged** into a hidden temp dir (``.tmp-*``) next to its final
   location — one ``.npy`` file per array, fsynced, with a CRC32 per
   array recorded in a JSON ``manifest.json`` (also fsynced);
2. **committed** with a single ``os.replace(tmp, step-N)`` — the only
   visibility point, atomic on POSIX — followed by an fsync of the
   parent directory;
3. **published** by atomically rewriting a ``latest`` pointer file.

A reader therefore never observes a partial checkpoint: either the
``step-N`` directory exists with a complete, checksummed payload, or it
does not exist at all. ``load_latest`` additionally *verifies* every
CRC and falls back to the newest older checkpoint that validates,
warning about (and skipping) corrupt ones — so even post-commit disk
corruption degrades a resume by K steps instead of killing it.

bfloat16 arrays are stored as a uint16 view with the logical dtype in
the manifest (numpy cannot serialize bf16 natively); everything else is
a plain ``.npy``. The format is self-contained — no pickle — so it is
robust to class renames across versions.

Fault points (``mxnet_tpu.testing.faults``): ``checkpoint.stage``,
``checkpoint.manifest``, ``checkpoint.commit``, ``checkpoint.publish``,
``checkpoint.prune`` — each bracketed before/after, so kill-9 tests can
die at every boundary and prove the invariant above.
"""
from __future__ import annotations

import io
import json
import logging
import os
import shutil
import uuid
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from ..testing.faults import fault_point

__all__ = ["CheckpointCorruptError", "write_checkpoint", "read_checkpoint",
           "validate_checkpoint", "list_checkpoints", "latest_valid",
           "load_latest", "prune_checkpoints", "atomic_write_bytes",
           "step_dir_name", "MANIFEST", "FORMAT_VERSION"]

_LOG = logging.getLogger("mxnet_tpu.checkpoint")

MANIFEST = "manifest.json"
LATEST = "latest"
FORMAT_VERSION = 1
_STEP_PREFIX = "step-"


class CheckpointCorruptError(MXNetError):
    """Manifest unreadable or a payload failed its checksum."""


# ---------------------------------------------------------------- helpers
def _fsync_path(path: str):
    """fsync a file or directory by path (directory fsync persists the
    entries created/renamed inside it)."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0) \
        if os.path.isdir(path) else os.O_RDONLY
    try:
        fd = os.open(path, flags)
    except OSError:        # pragma: no cover - platform without dir-open
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _npy_bytes(arr: onp.ndarray) -> Tuple[bytes, str]:
    """Serialize to .npy bytes; bf16 goes as a uint16 view with the
    logical dtype recorded separately (returned)."""
    logical = str(arr.dtype)
    if logical == "bfloat16":
        arr = arr.view(onp.uint16)
    buf = io.BytesIO()
    onp.save(buf, onp.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue(), logical


def _from_npy(raw: bytes, logical_dtype: str) -> onp.ndarray:
    arr = onp.load(io.BytesIO(raw), allow_pickle=False)
    if logical_dtype == "bfloat16":
        import jax.numpy as jnp
        arr = onp.asarray(jnp.asarray(arr).view(jnp.bfloat16))
    return arr


def step_dir_name(step: int) -> str:
    return f"{_STEP_PREFIX}{int(step):010d}"


def _parse_step(name: str) -> Optional[int]:
    if not name.startswith(_STEP_PREFIX):
        return None
    try:
        return int(name[len(_STEP_PREFIX):])
    except ValueError:
        return None


def atomic_write_bytes(fname: str, data: bytes, fault: str = "ndarray.save"):
    """Crash-safe single-file write: stage to ``fname.tmp-<pid>``, fsync,
    ``os.replace`` over the destination, fsync the directory. A kill at
    any point leaves either the old complete file or the new complete
    file — never a torn mix."""
    tmp = f"{fname}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        fault_point(fault, "before")
        os.replace(tmp, fname)
        fault_point(fault, "after")
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    parent = os.path.dirname(os.path.abspath(fname))
    _fsync_path(parent)


# ---------------------------------------------------------------- write
def write_checkpoint(root: str, step: int,
                     arrays: Dict[str, onp.ndarray],
                     array_meta: Optional[Dict[str, dict]] = None,
                     meta: Optional[dict] = None) -> str:
    """Write one atomic checkpoint ``<root>/step-<N>``; returns its path.

    ``arrays``: name -> host numpy array. ``array_meta``: optional extra
    JSON per array (merged into its manifest entry). ``meta``: free-form
    JSON for the whole checkpoint (step counters, optimizer class, ...).
    """
    root = os.path.abspath(root)
    os.makedirs(root, exist_ok=True)
    final = os.path.join(root, step_dir_name(step))
    tmp = os.path.join(root, f".tmp-{step_dir_name(step)}-{os.getpid()}-"
                             f"{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(tmp, "arrays"))
    manifest: Dict[str, Any] = {
        "format": FORMAT_VERSION, "step": int(step),
        "meta": meta or {}, "arrays": {}}
    try:
        fault_point("checkpoint.stage", "before")
        for i, (name, arr) in enumerate(arrays.items()):
            arr = onp.asarray(arr)
            raw, logical = _npy_bytes(arr)
            rel = os.path.join("arrays", f"{i}.npy")
            entry = {"file": rel, "crc32": zlib.crc32(raw),
                     "shape": [int(s) for s in arr.shape],
                     "dtype": logical, "nbytes": len(raw)}
            if array_meta and name in array_meta:
                entry.update(array_meta[name])
            manifest["arrays"][name] = entry
            path = os.path.join(tmp, rel)
            with open(path, "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
        fault_point("checkpoint.stage", "after")
        fault_point("checkpoint.manifest", "before")
        mpath = os.path.join(tmp, MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        fault_point("checkpoint.manifest", "after")
        _fsync_path(os.path.join(tmp, "arrays"))
        _fsync_path(tmp)
        # the ONE visibility point: before this replace the checkpoint
        # does not exist; after it, it is complete and checksummed
        fault_point("checkpoint.commit", "before")
        if os.path.isdir(final):      # re-saving the same step: replace
            _replace_dir(tmp, final)
        else:
            os.replace(tmp, final)
        fault_point("checkpoint.commit", "after")
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _fsync_path(root)
    _publish_latest(root, step)
    return final


def _replace_dir(tmp: str, final: str):
    """os.replace cannot overwrite a non-empty dir: move the old one
    aside first so the final name never points at a partial payload."""
    aside = final + f".old-{uuid.uuid4().hex[:8]}"
    os.replace(final, aside)
    os.replace(tmp, final)
    shutil.rmtree(aside, ignore_errors=True)


def _publish_latest(root: str, step: int):
    fault_point("checkpoint.publish", "before")
    atomic_write_bytes(os.path.join(root, LATEST),
                       (step_dir_name(step) + "\n").encode(),
                       fault="checkpoint.publish.replace")
    fault_point("checkpoint.publish", "after")


# ---------------------------------------------------------------- read
def validate_checkpoint(path: str) -> dict:
    """Parse the manifest and verify every array file's CRC; returns the
    manifest. Raises CheckpointCorruptError on any mismatch."""
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path}: unreadable manifest ({e})") from e
    if manifest.get("format") != FORMAT_VERSION:
        raise CheckpointCorruptError(
            f"checkpoint {path}: unsupported format "
            f"{manifest.get('format')!r}")
    for name, entry in manifest.get("arrays", {}).items():
        fpath = os.path.join(path, entry["file"])
        try:
            with open(fpath, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                f"checkpoint {path}: missing payload for {name!r}") from e
        if len(raw) != entry["nbytes"] or \
                zlib.crc32(raw) != entry["crc32"]:
            raise CheckpointCorruptError(
                f"checkpoint {path}: checksum mismatch for {name!r} "
                f"({entry['file']})")
    return manifest


def read_checkpoint(path: str) \
        -> Tuple[Dict[str, onp.ndarray], dict]:
    """Load a validated checkpoint: returns (arrays, manifest)."""
    manifest = validate_checkpoint(path)
    arrays: Dict[str, onp.ndarray] = {}
    for name, entry in manifest["arrays"].items():
        with open(os.path.join(path, entry["file"]), "rb") as f:
            arr = _from_npy(f.read(), entry["dtype"])
        arrays[name] = arr.reshape(tuple(entry["shape"]))
    return arrays, manifest


def list_checkpoints(root: str) -> List[int]:
    """Committed step numbers under ``root``, ascending (no validation)."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        s = _parse_step(name)
        if s is not None and os.path.isdir(os.path.join(root, name)):
            steps.append(s)
    return sorted(steps)


def _latest_pointer(root: str) -> Optional[int]:
    try:
        with open(os.path.join(root, LATEST)) as f:
            return _parse_step(f.read().strip())
    except OSError:
        return None


def latest_valid(root: str) -> Optional[Tuple[int, str]]:
    """Newest checkpoint that passes validation: the ``latest`` pointer
    is tried first, then every committed step newest-first. Corrupt
    candidates are skipped with a warning. Returns (step, path) or
    None."""
    root = os.path.abspath(root)
    candidates: List[int] = []
    ptr = _latest_pointer(root)
    if ptr is not None:
        candidates.append(ptr)
    for s in reversed(list_checkpoints(root)):
        if s not in candidates:
            candidates.append(s)
    candidates.sort(reverse=True)
    for s in candidates:
        path = os.path.join(root, step_dir_name(s))
        try:
            validate_checkpoint(path)
            return s, path
        except CheckpointCorruptError as e:
            _LOG.warning("skipping corrupt checkpoint: %s", e)
    return None


def load_latest(root: str) \
        -> Optional[Tuple[int, Dict[str, onp.ndarray], dict]]:
    """Load the newest VALID checkpoint; (step, arrays, manifest) or
    None when no valid checkpoint exists."""
    found = latest_valid(root)
    if found is None:
        return None
    step, path = found
    arrays, manifest = read_checkpoint(path)
    return step, arrays, manifest


def prune_checkpoints(root: str, keep_last: int,
                      protect: Tuple[int, ...] = ()):
    """Delete all but the newest ``keep_last`` committed checkpoints
    (never the ones in ``protect``). Pruning happens strictly after
    commit+publish, so a crash mid-prune still leaves >= keep_last valid
    checkpoints behind."""
    if keep_last <= 0:
        return
    steps = list_checkpoints(root)
    doomed = [s for s in steps[:-keep_last] if s not in protect]
    for s in doomed:
        fault_point("checkpoint.prune", "before")
        shutil.rmtree(os.path.join(root, step_dir_name(s)),
                      ignore_errors=True)
        fault_point("checkpoint.prune", "after")
    # stale staging dirs from crashed writers are garbage, not state
    if os.path.isdir(root):
        for name in os.listdir(root):
            if name.startswith(".tmp-"):
                shutil.rmtree(os.path.join(root, name), ignore_errors=True)
