"""Checkpoint / resume subsystem.

Two generations live here:

- **Atomic native checkpoints** (this PR, preemption-safe): staged +
  checksummed + committed by a single ``os.replace`` (atomic.py),
  capturing the COMPLETE train state including fused/ZeRO-sharded
  optimizer buffers (state.py), with retention + async writes +
  auto-resume (manager.py). ``gluon.TrainLoop(checkpoint_dir=...)`` is
  the high-level entry; fault-injection points prove crash consistency
  (mxnet_tpu/testing/faults.py, docs/ROBUSTNESS.md).
- **orbax-backed checkpoints** (orbax_backend.py, kept for
  compatibility): ``save_checkpoint``/``load_checkpoint``/
  ``CheckpointManager`` over ``orbax.checkpoint``.
"""
from .atomic import (CheckpointCorruptError, atomic_write_bytes,  # noqa: F401
                     latest_valid, list_checkpoints, load_latest,
                     prune_checkpoints, read_checkpoint,
                     validate_checkpoint, write_checkpoint)
from .state import (TrainState, apply_train_state,  # noqa: F401
                    assemble_segments, capture_train_state)
from .manager import TrainCheckpointManager  # noqa: F401
from .orbax_backend import (CheckpointManager, load_checkpoint,  # noqa: F401
                            save_checkpoint)
from . import atomic, manager, orbax_backend, state  # noqa: F401

__all__ = [
    # native atomic stack
    "TrainCheckpointManager", "TrainState", "capture_train_state",
    "apply_train_state", "assemble_segments", "write_checkpoint",
    "read_checkpoint", "validate_checkpoint", "load_latest",
    "latest_valid", "list_checkpoints", "prune_checkpoints",
    "atomic_write_bytes", "CheckpointCorruptError",
    # orbax compatibility layer
    "save_checkpoint", "load_checkpoint", "CheckpointManager",
]
