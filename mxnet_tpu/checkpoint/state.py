"""Complete training-state capture/restore (``TrainState``).

PR 2's ZeRO-1 sharded fused step moved the optimizer state out of the
eager ``Updater`` into ``_ZeroShardPlan`` buffers that live permanently
``NamedSharding``-partitioned — ``Trainer.save_states`` (a pickle of the
eager updater) silently misses all of it. This module extracts the
WHOLE state of a training run into a flat ``{name: host-numpy}`` dict
plus JSON meta, in a *logical* (layout-free) format:

- ``param/<name>``   — every Parameter (incl. grad_req='null' stats);
- ``opt/<idx>/<slot>`` — optimizer state per trainable param, in the
  PARAM's shape: zero-sharded flat buffers are unpadded, split out of
  their buckets, and reshaped on capture, so the on-disk format is
  independent of the dp size — a dp=N checkpoint resumes on a dp=M mesh
  (or in plain fused / eager mode, for plain-tuple states);
- ``master/<idx>``   — fp32 master copies of multi-precision params;
- ``rng/key``        — the process PRNG key chain;
- meta: step, update counters (Adam's bias correction), lr-scheduler
  state, optimizer class.

Arrays whose shards are not all host-local (multi-host ``parallel.dist``
runs) are captured as per-host dim0 segments (``name#seg<start>``) and
reassembled on restore via :func:`assemble_segments`.

Restore is *adoption-based*: parameters are written back preserving the
live array's sharding; optimizer state lands in ``Updater.states`` as
plain NDArray tuples, which the eager path uses directly and which
``_ZeroShardPlan`` adopts (re-flattening, re-padding and re-sharding to
the CURRENT mesh) when the next zero-sharded step materializes. A live
zero plan is updated in place.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["TrainState", "capture_train_state", "apply_train_state",
           "assemble_segments"]

_LOG = logging.getLogger("mxnet_tpu.checkpoint")


class TrainState:
    """A captured snapshot: ``arrays`` (host numpy), per-array JSON
    ``array_meta``, and whole-state JSON ``meta``."""

    def __init__(self, arrays: Dict[str, onp.ndarray],
                 meta: Dict[str, Any],
                 array_meta: Optional[Dict[str, dict]] = None):
        self.arrays = arrays
        self.meta = meta
        self.array_meta = array_meta or {}

    @property
    def step(self) -> int:
        return int(self.meta.get("step", 0))

    def __repr__(self):
        return (f"TrainState(step={self.step}, "
                f"{len(self.arrays)} arrays)")


# ---------------------------------------------------------------- host copy
def _host_copy(data, name: str, arrays: dict, array_meta: dict):
    """Device->host. Fully-addressable arrays (every single-process run)
    copy whole; multi-host shardings emit one dim0 segment per LOCAL
    shard so each host persists only what it owns."""
    if isinstance(data, NDArray):
        data = data._data
    if getattr(data, "is_fully_addressable", True):
        arrays[name] = onp.asarray(data)
        return
    seen = set()
    for shard in data.addressable_shards:        # pragma: no cover - multihost
        idx = shard.index[0] if shard.index else slice(None)
        start = idx.start or 0
        if start in seen:
            continue
        seen.add(start)
        key = f"{name}#seg{start}"
        arrays[key] = onp.asarray(shard.data)
        array_meta[key] = {"seg_of": name, "dim0_start": int(start),
                           "global_shape": [int(s) for s in data.shape]}


def assemble_segments(arrays: Dict[str, onp.ndarray],
                      array_meta: Dict[str, dict]) -> Dict[str, onp.ndarray]:
    """Merge ``name#seg<start>`` per-host segments back into full arrays
    (inverse of the multi-host capture). Raises if a region is missing."""
    segs: Dict[str, List[Tuple[int, onp.ndarray]]] = {}
    out: Dict[str, onp.ndarray] = {}
    for name, arr in arrays.items():
        am = array_meta.get(name) or {}
        if "seg_of" in am:
            segs.setdefault(am["seg_of"], []).append(
                (int(am["dim0_start"]), arr))
        else:
            out[name] = arr
    for name, parts in segs.items():
        parts.sort(key=lambda t: t[0])
        gshape = array_meta[f"{name}#seg{parts[0][0]}"]["global_shape"]
        full = onp.zeros(tuple(gshape), dtype=parts[0][1].dtype)
        pos = 0
        for start, arr in parts:
            if start != pos:
                raise MXNetError(
                    f"checkpoint segment gap in {name!r} at row {pos}: "
                    "not all hosts' shard files are present")
            full[start:start + arr.shape[0]] = arr
            pos = start + arr.shape[0]
        if pos != gshape[0]:
            raise MXNetError(
                f"checkpoint segments for {name!r} cover {pos} of "
                f"{gshape[0]} rows: incomplete multi-host restore")
        out[name] = full
    return out


# ---------------------------------------------------------------- capture
def _param_items(trainer, net):
    if net is not None:
        return list(net.collect_params().items())
    if trainer is not None:
        return list(zip(trainer._param_names, trainer._all_params))
    return []


def _live_zero_plan(trainer):
    """The _ZeroShardPlan of a live CompiledTrainStep, if one owns the
    optimizer state (Trainer._register_compiled tracks them)."""
    if trainer is None:
        return None
    for step in trainer._live_compiled_steps():
        if getattr(step, "_zero", None) is not None:
            return step._zero
    return None


def _sched_state(sch) -> Optional[dict]:
    if sch is None:
        return None
    state = {}
    for k, v in vars(sch).items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            nested = _sched_state(v) if hasattr(v, "base_lr") else None
            if nested is not None:
                state[k] = {"__sched__": nested}
            continue
        state[k] = v
    return state


def _sched_restore(sch, state: Optional[dict]):
    if sch is None or not state:
        return
    for k, v in state.items():
        if isinstance(v, dict) and "__sched__" in v:
            _sched_restore(getattr(sch, k, None), v["__sched__"])
        elif hasattr(sch, k):
            setattr(sch, k, type(getattr(sch, k))(v)
                    if getattr(sch, k) is not None else v)


def _capture_zero_states(plan, arrays, array_meta):
    """Unpack the flat padded NamedSharding-sharded unit buffers into
    per-param, param-shaped logical states (dp-size independent)."""
    for unit, st in zip(plan.units, plan.states):
        for li, leaf in enumerate(st):
            _host_copy(leaf, f"__zu/{li}", arrays, array_meta)
            flat = arrays.pop(f"__zu/{li}", None)
            if flat is None:      # pragma: no cover - multihost segments
                # segments stay flat+padded per unit; record membership
                # so a same-layout multihost restore can reassemble
                for key in list(arrays):
                    if key.startswith(f"__zu/{li}#seg"):
                        new = key.replace(
                            f"__zu/{li}",
                            f"zunit/{unit['members'][0]}/{li}")
                        arrays[new] = arrays.pop(key)
                        array_meta[new] = array_meta.pop(key)
                continue
            off = 0
            for j, shp, n in zip(unit["members"], unit["shapes"],
                                 unit["sizes"]):
                arrays[f"opt/{j}/{li}"] = \
                    flat[off:off + n].reshape(shp)
                off += n
    for k, slot in plan.master_slot.items():
        unit = plan.units[k]
        j = unit["members"][0]
        _host_copy(plan.masters[slot], "__zm", arrays, array_meta)
        flat = arrays.pop("__zm", None)
        if flat is not None:
            arrays[f"master/{j}"] = \
                flat[:unit["sizes"][0]].reshape(unit["shapes"][0])


def _current_dp(trainer) -> int:
    """The data-parallel width the state was captured at — restore
    provenance for the elastic reshard path (``dp_from`` in
    ``TrainCheckpointManager.restore_provenance``). The on-disk format
    itself stays layout-free; this is metadata only."""
    plan = _live_zero_plan(trainer)
    if plan is not None:
        return int(plan.n_shards)
    try:
        from ..parallel.mesh import current_mesh
        m = current_mesh()
        if m is not None:
            return int(m.shape.get("dp", 1))
    except Exception:            # pragma: no cover - defensive
        pass
    return 1


def _capture_updater_states(trainer, arrays):
    import jax
    for idx, st in trainer._updater.states.items():
        leaves = jax.tree_util.tree_leaves(
            st, is_leaf=lambda t: isinstance(t, NDArray))
        for li, leaf in enumerate(leaves):
            arrays[f"opt/{idx}/{li}"] = onp.asarray(
                leaf._data if isinstance(leaf, NDArray) else leaf)


def capture_train_state(trainer=None, net=None, step: int = 0,
                        extra: Optional[Dict[str, Any]] = None) -> TrainState:
    """Snapshot params + optimizer state (fused/zero-sharded included) +
    counters + RNG into host memory. The device->host copies happen HERE,
    synchronously — serialization of the returned TrainState can then
    overlap with further training steps (manager.py)."""
    from ..ndarray import random as _random
    arrays: Dict[str, onp.ndarray] = {}
    array_meta: Dict[str, dict] = {}
    meta: Dict[str, Any] = {"step": int(step)}

    names = []
    for name, p in _param_items(trainer, net):
        if p._data is not None:
            _host_copy(p._data, f"param/{name}", arrays, array_meta)
            names.append(name)
    meta["param_names"] = names
    meta["dp_size"] = _current_dp(trainer)

    if trainer is not None:
        opt = trainer._optimizer
        plan = _live_zero_plan(trainer)
        meta["opt_mode"] = "zero" if plan is not None else "updater"
        meta["optimizer"] = type(opt).__name__
        meta["num_update"] = int(opt.num_update)
        meta["index_update_count"] = {
            str(k): int(v) for k, v in opt._index_update_count.items()}
        meta["trainable_names"] = [p.name for p in trainer._params]
        meta["lr_scheduler"] = _sched_state(
            getattr(opt, "lr_scheduler", None))
        if plan is not None:
            _capture_zero_states(plan, arrays, array_meta)
        else:
            _capture_updater_states(trainer, arrays)

    arrays["rng/key"] = onp.asarray(_random.get_key_state())
    if extra:
        for k, v in extra.items():
            arrays[f"extra/{k}"] = onp.asarray(
                v._data if isinstance(v, NDArray) else v)
    return TrainState(arrays, meta, array_meta)


# ---------------------------------------------------------------- apply
def _put_like(arr: onp.ndarray, live):
    """Host array -> device, preserving the live array's sharding (a
    sharded param must come back sharded, not silently replicated)."""
    import jax
    import jax.numpy as jnp
    out = jnp.asarray(arr)
    if live is not None and hasattr(live, "sharding"):
        out = jax.device_put(out, live.sharding)
    return out


def _apply_params(arrays, trainer, net, strict):
    applied = 0
    for name, p in _param_items(trainer, net):
        key = f"param/{name}"
        if key not in arrays:
            if strict and p._data is not None:
                raise MXNetError(
                    f"checkpoint has no data for parameter {name!r} "
                    "(pass strict=False to keep its current value)")
            continue
        arr = arrays[key]
        cur = p._data._data if p._data is not None else None
        if cur is not None and tuple(cur.shape) != tuple(arr.shape):
            raise MXNetError(
                f"checkpoint shape {tuple(arr.shape)} does not match "
                f"parameter {name!r} shape {tuple(cur.shape)}")
        p.set_data(NDArray(_put_like(arr, cur)))
        applied += 1
    return applied


def _apply_opt_states(arrays, meta, trainer):
    """Land per-param logical states in Updater.states as plain NDArray
    tuples (param-shaped) — directly usable by the eager/fused paths and
    adopted by _ZeroShardPlan when the next sharded step builds."""
    import jax
    by_idx: Dict[int, Dict[int, onp.ndarray]] = {}
    for key, arr in arrays.items():
        if key.startswith("opt/"):
            _, idx, li = key.split("/")
            by_idx.setdefault(int(idx), {})[int(li)] = arr
    upd = trainer._updater
    for idx, slots in by_idx.items():
        leaves = [NDArray(_put_like(slots[li], None))
                  for li in sorted(slots)]
        if idx >= len(trainer._params):
            raise MXNetError(
                f"checkpoint optimizer state index {idx} out of range "
                f"({len(trainer._params)} trainable params)")
        cur = upd.states.get(idx)
        if cur is not None and meta.get("opt_mode") != "zero":
            # typed restore: preserve the live structure (e.g. nested
            # multi-precision (master, state) tuples)
            flat, treedef = jax.tree_util.tree_flatten(
                cur, is_leaf=lambda t: isinstance(t, NDArray))
            if len(flat) == len(leaves):
                upd.states[idx] = jax.tree_util.tree_unflatten(
                    treedef, leaves)
                continue
        upd.states[idx] = tuple(leaves)

    masters = {}
    for key, arr in arrays.items():
        if key.startswith("master/"):
            masters[int(key.split("/")[1])] = onp.asarray(
                arr, dtype=onp.float32)
    # consumed by _ZeroShardPlan.__init__ (and a live plan below): the
    # fp32 master of a multi-precision param must survive bit-exactly —
    # recasting from the fp16 weight would lose the low-order bits
    trainer._restored_masters = masters

    opt = trainer._optimizer
    if "num_update" in meta:
        opt.num_update = int(meta["num_update"])
    if "index_update_count" in meta:
        opt._index_update_count = {
            int(k): int(v) for k, v in meta["index_update_count"].items()}
    _sched_restore(getattr(opt, "lr_scheduler", None),
                   meta.get("lr_scheduler"))


def _reload_live_plan(trainer):
    """A zero plan already materialized (mid-run restore): rebuild its
    flat padded sharded buffers from the freshly restored Updater states
    and masters, in place."""
    import jax.numpy as jnp
    for step in trainer._live_compiled_steps():
        plan = getattr(step, "_zero", None)
        if plan is None:
            continue
        fresh = plan.__class__(trainer, plan.mesh, plan.axis)
        for st, new_st in zip(plan.states, fresh.states):
            for s, n in zip(st, new_st):
                s._data = n._data
        for m, nm in zip(plan.masters, fresh.masters):
            m._data = nm._data
        _LOG.info("restored state into live zero-shard plan (%d units)",
                  len(plan.units))


def apply_train_state(state: TrainState, trainer=None, net=None,
                      strict: bool = True) -> Dict[str, Any]:
    """Restore a captured/loaded TrainState into (net, trainer); returns
    the state's meta (incl. 'step'). Works before the first step (states
    are adopted when the fused/zero program builds) and mid-run (a live
    zero plan is refreshed in place)."""
    from ..ndarray import random as _random
    arrays = assemble_segments(state.arrays, state.array_meta)
    _apply_params(arrays, trainer, net, strict)
    if trainer is not None:
        _apply_opt_states(arrays, state.meta, trainer)
        _reload_live_plan(trainer)
    if "rng/key" in arrays:
        _random.set_key_state(arrays["rng/key"])
    return state.meta
