"""Text token indexing: ``Vocabulary``.

Reference analog: python/mxnet/contrib/text/vocab.py:28 — identical
indexing contract (index 0 is the unknown token, then reserved tokens,
then counter keys by descending frequency with alphabetical tie-break,
filtered by ``min_freq`` and capped by ``most_freq_count``).
"""
import collections

from . import _constants as C

__all__ = ["Vocabulary"]


class Vocabulary:
    """Indexes tokens of a corpus counter for text experiments.

    Index 0 maps to ``unknown_token``; reserved tokens follow; counter
    keys are indexed by descending frequency (ties broken
    alphabetically), skipping tokens with frequency below ``min_freq``
    and stopping after ``most_freq_count`` counter keys."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq <= 0:
            raise ValueError("`min_freq` must be set to a positive value.")
        if reserved_tokens is not None:
            reserved_set = set(reserved_tokens)
            if unknown_token in reserved_set:
                raise ValueError(
                    "`reserved_tokens` cannot contain `unknown_token`.")
            if len(reserved_set) != len(reserved_tokens):
                raise ValueError("`reserved_tokens` cannot contain "
                                 "duplicate reserved tokens.")

        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token]
        self._reserved_tokens = (None if reserved_tokens is None
                                 else list(reserved_tokens))
        if reserved_tokens:
            self._idx_to_token.extend(reserved_tokens)
        self._token_to_idx = {t: i for i, t in
                              enumerate(self._idx_to_token)}

        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        if not isinstance(counter, collections.Counter):
            raise TypeError("`counter` must be an instance of "
                            "collections.Counter.")
        special = set(self._idx_to_token)
        token_freqs = sorted(counter.items(), key=lambda x: x[0])
        token_freqs.sort(key=lambda x: x[1], reverse=True)
        cap = len(special) + (len(counter) if most_freq_count is None
                              else most_freq_count)
        for token, freq in token_freqs:
            if freq < min_freq or len(self._idx_to_token) == cap:
                break
            if token not in special:
                self._idx_to_token.append(token)
                self._token_to_idx[token] = len(self._idx_to_token) - 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        """dict: token -> index."""
        return self._token_to_idx

    @property
    def idx_to_token(self):
        """list of str: index -> token."""
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index/indices; unknown tokens map to index 0."""
        to_reduce = not isinstance(tokens, list)
        if to_reduce:
            tokens = [tokens]
        indices = [self._token_to_idx.get(t, C.UNKNOWN_IDX)
                   for t in tokens]
        return indices[0] if to_reduce else indices

    def to_tokens(self, indices):
        """Index/indices -> token(s); invalid indices raise ValueError."""
        to_reduce = not isinstance(indices, list)
        if to_reduce:
            indices = [indices]
        max_idx = len(self._idx_to_token) - 1
        tokens = []
        for idx in indices:
            if not isinstance(idx, int) or idx > max_idx or idx < 0:
                raise ValueError(
                    f"Token index {idx} in the provided `indices` is "
                    "invalid.")
            tokens.append(self._idx_to_token[idx])
        return tokens[0] if to_reduce else tokens
