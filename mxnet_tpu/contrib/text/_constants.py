"""Constants for contrib.text (reference contrib/text/_constants.py)."""

UNKNOWN_IDX = 0

# Known pretrained-file catalogs. The reference ships sha1 maps used to
# download from an S3 bucket (reference embedding.py:525-534,617); this
# environment has no egress, so these name lists exist only to validate
# `pretrained_file_name` and to answer `get_pretrained_file_names` — the
# files themselves must be placed under `embedding_root` by the user.
GLOVE_PRETRAINED_FILE_NAMES = [
    "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
    "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
    "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
    "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt",
]

FASTTEXT_PRETRAINED_FILE_NAMES = [
    "wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec", "wiki.de.vec",
    "wiki.fr.vec", "wiki.es.vec", "wiki.ja.vec", "wiki.ru.vec",
]
