"""Pretrained token embeddings: GloVe / FastText / CustomEmbedding.

Reference analog: python/mxnet/contrib/text/embedding.py (:133
_TokenEmbedding, :481 GloVe, :553 FastText, :635 CustomEmbedding, :677
CompositeEmbedding, :40/:63 register/create). Same loading contract —
one token per line, ``elem_delim``-separated floats, first-seen wins,
1-element lines treated as headers, unknown vector from the file when
present else ``init_unknown_vec`` — with one environment difference:
this image has no network egress, so pretrained files are resolved
ONLY against ``embedding_root`` (default ``~/.mxnet/embedding/<name>``)
and a missing file is an error telling the user where to place it,
instead of a download.
"""
import io
import logging
import os
import warnings

from ... import ndarray as nd
from . import _constants as C
from . import vocab

__all__ = ["register", "create", "get_pretrained_file_names",
           "GloVe", "FastText", "CustomEmbedding", "CompositeEmbedding"]


class _Registry:
    embeddings = {}


def register(embedding_cls):
    """Register a subclass of ``_TokenEmbedding`` for ``create``."""
    _Registry.embeddings[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Create an embedding instance by name, e.g.
    ``create('glove', pretrained_file_name='glove.6B.50d.txt')``."""
    name = embedding_name.lower()
    if name not in _Registry.embeddings:
        raise KeyError(
            f"Cannot find registered token embedding {embedding_name}. "
            f"Valid: {', '.join(sorted(_Registry.embeddings))}")
    return _Registry.embeddings[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names, per embedding or as a dict."""
    if embedding_name is not None:
        name = embedding_name.lower()
        if name not in _Registry.embeddings:
            raise KeyError(f"Cannot find registered token embedding "
                           f"{embedding_name}.")
        return list(_Registry.embeddings[name].pretrained_file_names)
    return {n: list(cls.pretrained_file_names)
            for n, cls in _Registry.embeddings.items()}


class _TokenEmbedding(vocab.Vocabulary):
    """Indexed tokens + their embedding vectors. Subclasses either load
    a catalogued pretrained file (GloVe/FastText) or a user file
    (CustomEmbedding); all expose ``idx_to_vec``, ``vec_len``,
    ``get_vecs_by_tokens`` and ``update_token_vectors``."""

    pretrained_file_names = ()

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = None
        self._idx_to_vec = None

    # ---------------- local-file resolution (no egress) ----------------
    @classmethod
    def _get_pretrained_file(cls, embedding_root, pretrained_file_name):
        embedding_name = cls.__name__.lower()
        root = os.path.expanduser(embedding_root)
        path = os.path.join(root, embedding_name, pretrained_file_name)
        if not os.path.isfile(path):
            raise ValueError(
                f"Pretrained file {pretrained_file_name} for embedding "
                f"{embedding_name} not found at {path}. This environment "
                "cannot download; place the file there or use "
                "CustomEmbedding with a local path.")
        return path

    @classmethod
    def _check_pretrained_file_names(cls, pretrained_file_name):
        if pretrained_file_name not in cls.pretrained_file_names:
            embedding_name = cls.__name__.lower()
            raise KeyError(
                f"Cannot find pretrained file {pretrained_file_name} for "
                f"token embedding {embedding_name}. Valid pretrained "
                f"files for embedding {embedding_name}: "
                f"{', '.join(cls.pretrained_file_names)}")

    # ---------------- loading ----------------
    def _load_embedding(self, pretrained_file_path, elem_delim,
                        init_unknown_vec, encoding="utf8"):
        pretrained_file_path = os.path.expanduser(pretrained_file_path)
        if not os.path.isfile(pretrained_file_path):
            raise ValueError(
                "`pretrained_file_path` must be a valid path to the "
                "pre-trained token embedding file.")
        logging.info("Loading pre-trained token embedding vectors from %s",
                     pretrained_file_path)
        vec_len = None
        all_elems = []
        # rows for EVERY pre-seeded token (unknown + reserved), so file
        # tokens' matrix rows stay aligned with their vocabulary indices
        # even when the embedding was built with reserved_tokens
        n_preseeded = len(self._idx_to_token)
        seen = set()
        loaded_unknown_vec = None
        with io.open(pretrained_file_path, "r", encoding=encoding) as f:
            for line_num, line in enumerate(f, start=1):
                elems = line.rstrip().split(elem_delim)
                if len(elems) <= 1:
                    raise ValueError(
                        f"At line {line_num} of the pre-trained text "
                        "embedding file: unexpected data format.")
                token, vals = elems[0], elems[1:]
                if token == self.unknown_token and \
                        loaded_unknown_vec is None:
                    loaded_unknown_vec = [float(v) for v in vals]
                    seen.add(token)
                elif token in seen:
                    warnings.warn(
                        f"At line {line_num}: duplicate embedding for "
                        f"token {token} is seen and skipped.")
                elif len(vals) == 1:
                    warnings.warn(
                        f"At line {line_num}: token {token} with "
                        "1-dimensional vector is likely a header; "
                        "skipped.")
                else:
                    vec = [float(v) for v in vals]
                    if vec_len is None:
                        vec_len = len(vec)
                        all_elems.extend([0.0] * vec_len * n_preseeded)
                    elif len(vec) != vec_len:
                        raise ValueError(
                            f"At line {line_num}: dimension of token "
                            f"{token} is {len(vec)} but previous tokens "
                            f"have {vec_len}. All dimensions must match.")
                    all_elems.extend(vec)
                    self._idx_to_token.append(token)
                    self._token_to_idx[token] = len(self._idx_to_token) - 1
                    seen.add(token)

        if vec_len is None:
            raise ValueError(
                f"No embedding vectors loaded from {pretrained_file_path} "
                "(note: 1-dimensional vectors are treated as header lines, "
                "matching the reference loader).")
        self._vec_len = vec_len
        mat = nd.array(all_elems).reshape((-1, self._vec_len))
        if loaded_unknown_vec is None:
            mat[C.UNKNOWN_IDX] = init_unknown_vec(shape=(self._vec_len,))
        else:
            mat[C.UNKNOWN_IDX] = nd.array(loaded_unknown_vec)
        # reserved tokens (indices 1..n_preseeded-1) get the init vector
        for i in range(1, n_preseeded):
            mat[i] = init_unknown_vec(shape=(self._vec_len,))
        self._idx_to_vec = mat

    # ---------------- vocabulary composition ----------------
    def _index_tokens_from_vocabulary(self, vocabulary):
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = (None if vocabulary.reserved_tokens is None
                                 else list(vocabulary.reserved_tokens))

    def _set_idx_to_vec_by_embeddings(self, token_embeddings, vocab_len,
                                      vocab_idx_to_token):
        new_vec_len = sum(e.vec_len for e in token_embeddings)
        new_idx_to_vec = nd.zeros((vocab_len, new_vec_len))
        col_start = 0
        for embed in token_embeddings:
            col_end = col_start + embed.vec_len
            new_idx_to_vec[0, col_start:col_end] = embed.idx_to_vec[0]
            new_idx_to_vec[1:, col_start:col_end] = \
                embed.get_vecs_by_tokens(vocab_idx_to_token[1:])
            col_start = col_end
        self._vec_len = new_vec_len
        self._idx_to_vec = new_idx_to_vec

    def _build_embedding_for_vocabulary(self, vocabulary):
        if vocabulary is not None:
            if not isinstance(vocabulary, vocab.Vocabulary):
                raise TypeError(
                    "The argument `vocabulary` must be an instance of "
                    "mxnet_tpu.contrib.text.vocab.Vocabulary.")
            self._set_idx_to_vec_by_embeddings(
                [self], len(vocabulary), vocabulary.idx_to_token)
            self._index_tokens_from_vocabulary(vocabulary)

    # ---------------- lookup / update ----------------
    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        """NDArray of shape (num_tokens, vec_len)."""
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Embedding vector(s) for token(s); unknown tokens get the
        unknown vector. 1-D out for a str, 2-D for a list."""
        to_reduce = not isinstance(tokens, list)
        if to_reduce:
            tokens = [tokens]
        if not lower_case_backup:
            indices = [self._token_to_idx.get(t, C.UNKNOWN_IDX)
                       for t in tokens]
        else:
            indices = [self._token_to_idx[t] if t in self._token_to_idx
                       else self._token_to_idx.get(t.lower(),
                                                   C.UNKNOWN_IDX)
                       for t in tokens]
        vecs = nd.Embedding(
            nd.array(indices, dtype="int32"), self._idx_to_vec,
            input_dim=self._idx_to_vec.shape[0],
            output_dim=self._idx_to_vec.shape[1])
        return vecs[0] if to_reduce else vecs

    def update_token_vectors(self, tokens, new_vectors):
        """Assign ``new_vectors`` to the rows of known ``tokens``;
        unknown tokens raise (update the unknown vector by naming
        ``unknown_token`` explicitly)."""
        if self._idx_to_vec is None:
            raise ValueError(
                "The property `idx_to_vec` has not been properly set.")
        if not isinstance(tokens, list) or len(tokens) == 1:
            if not (isinstance(new_vectors, nd.NDArray) and
                    len(new_vectors.shape) in (1, 2)):
                raise ValueError(
                    "`new_vectors` must be a 1-D or 2-D NDArray if "
                    "`tokens` is a singleton.")
            if not isinstance(tokens, list):
                tokens = [tokens]
            if len(new_vectors.shape) == 1:
                new_vectors = nd.expand_dims(new_vectors, axis=0)
        elif not (isinstance(new_vectors, nd.NDArray) and
                  len(new_vectors.shape) == 2):
            raise ValueError(
                "`new_vectors` must be a 2-D NDArray if `tokens` is a "
                "list of multiple strings.")
        if new_vectors.shape != (len(tokens), self.vec_len):
            raise ValueError(
                "The length of new_vectors must equal the number of "
                "tokens and its width the embedding dimension.")
        indices = []
        for token in tokens:
            if token in self._token_to_idx:
                indices.append(self._token_to_idx[token])
            else:
                raise ValueError(
                    f"Token {token} is unknown. To update the embedding "
                    "vector for an unknown token, please specify it "
                    "explicitly as the `unknown_token` "
                    f"{self._idx_to_token[C.UNKNOWN_IDX]} in `tokens`. "
                    "This is to avoid unintended updates.")
        for i, row in zip(indices, range(len(tokens))):
            self._idx_to_vec[i] = new_vectors[row]


@register
class GloVe(_TokenEmbedding):
    """GloVe embeddings (reference embedding.py:481), loaded from a
    local file under ``embedding_root``/glove/."""

    pretrained_file_names = tuple(C.GLOVE_PRETRAINED_FILE_NAMES)

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=os.path.join("~", ".mxnet", "embedding"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        GloVe._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = GloVe._get_pretrained_file(embedding_root,
                                          pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


@register
class FastText(_TokenEmbedding):
    """FastText embeddings (reference embedding.py:553), loaded from a
    local file under ``embedding_root``/fasttext/. FastText ``.vec``
    files start with a count/dim header line, which the loader already
    skips as a 1-element-vector warning case."""

    pretrained_file_names = tuple(C.FASTTEXT_PRETRAINED_FILE_NAMES)

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=os.path.join("~", ".mxnet", "embedding"),
                 init_unknown_vec=nd.zeros, vocabulary=None, **kwargs):
        FastText._check_pretrained_file_names(pretrained_file_name)
        super().__init__(**kwargs)
        path = FastText._get_pretrained_file(embedding_root,
                                             pretrained_file_name)
        self._load_embedding(path, " ", init_unknown_vec)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


@register
class CustomEmbedding(_TokenEmbedding):
    """User-provided embedding file: '<token><elem_delim><v0>...'
    per line (reference embedding.py:635)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=nd.zeros,
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding)
        if vocabulary is not None:
            self._build_embedding_for_vocabulary(vocabulary)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenation of multiple token embeddings over one vocabulary
    (reference embedding.py:677)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(vocabulary, vocab.Vocabulary):
            raise TypeError(
                "The argument `vocabulary` must be an instance of "
                "mxnet_tpu.contrib.text.vocab.Vocabulary.")
        if not isinstance(token_embeddings, list):
            token_embeddings = [token_embeddings]
        for embed in token_embeddings:
            if not isinstance(embed, _TokenEmbedding):
                raise TypeError(
                    "The argument `token_embeddings` must be an instance "
                    "or a list of instances of `_TokenEmbedding`.")
        super().__init__()
        self._set_idx_to_vec_by_embeddings(
            token_embeddings, len(vocabulary), vocabulary.idx_to_token)
        self._index_tokens_from_vocabulary(vocabulary)
