"""Text utilities: vocabulary + pretrained token embeddings.

Reference analog: python/mxnet/contrib/text/ — same module layout
(``vocab``, ``embedding``, ``utils``)."""
from . import embedding
from . import utils
from . import vocab
from .vocab import Vocabulary
