"""Text corpus utilities (reference contrib/text/utils.py:26)."""
import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Count tokens in ``source_str``, splitting sequences on
    ``seq_delim`` and tokens on ``token_delim`` (both regular
    expressions). Updates and returns ``counter_to_update`` when given,
    else a fresh ``collections.Counter``."""
    source_str = filter(
        None, re.split(token_delim + "|" + seq_delim, source_str))
    if to_lower:
        source_str = [t.lower() for t in source_str]
    counter = (collections.Counter() if counter_to_update is None
               else counter_to_update)
    counter.update(source_str)
    return counter
