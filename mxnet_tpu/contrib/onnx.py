"""ONNX export/import (reference: python/mxnet/contrib/onnx/ — mx2onnx
export_model + onnx2mx import_model).

The ``onnx`` package is not available in this environment and the
serialization backend is NOT implemented yet — the API surface is kept for
reference parity and raises a clear error at call time either way. Native
deployment checkpoints are ``HybridBlock.export`` / ``SymbolBlock.imports``.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["export_model", "import_model"]


def _require_onnx():
    try:
        import onnx  # noqa: F401
        return onnx
    except ImportError as e:
        raise MXNetError(
            "the 'onnx' package is not installed in this environment; "
            "mx.contrib.onnx keeps the reference API surface but needs "
            "onnx to serialize models") from e


def export_model(sym, params, in_shapes=None, in_types=None,
                 onnx_file_path="model.onnx", **kwargs):
    """Reference mx2onnx.export_model signature."""
    _require_onnx()
    raise MXNetError("ONNX serialization backend not implemented for the "
                     "TPU build yet; use HybridBlock.export (native "
                     "symbol.json + params checkpoint) for deployment")


def import_model(model_file: str):
    """Reference onnx2mx.import_model signature."""
    _require_onnx()
    raise MXNetError("ONNX import backend not implemented for the TPU "
                     "build yet; use SymbolBlock.imports for native "
                     "checkpoints")
