"""ONNX export/import (reference: python/mxnet/contrib/onnx/ — mx2onnx
``export_model`` + onnx2mx ``import_model``).

The environment ships no ``onnx`` package, so the serializer writes the
protobuf wire format directly (``onnx_proto.py``) with the spec's field
numbers — output files are standard ONNX models (opset 13) loadable by
onnxruntime. Coverage is the op surface of the Gluon layer zoo: Gemm/Conv/
BatchNormalization/pooling/activations/elementwise/shape ops; exotic ops
raise with the op name. Both directions round-trip through the ``mx.sym``
DAG: export walks a Symbol (reference mx2onnx/_export_onnx.py walks the
nnvm graph), import rebuilds a Symbol + params (reference
onnx2mx/import_onnx.py GraphProto translation).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as onp

from ..base import MXNetError
from . import onnx_proto as P

__all__ = ["export_model", "import_model", "get_model_metadata"]


# ---------------------------------------------------------------------------
# message builders
# ---------------------------------------------------------------------------

def _attr(name: str, value) -> P.MessageWriter:
    a = P.MessageWriter()
    a.write_string(1, name)
    if isinstance(value, bool):
        a.write_int(3, int(value))
        a.write_int(20, P.AttrType.INT)
    elif isinstance(value, int):
        a.write_int(3, value)
        a.write_int(20, P.AttrType.INT)
    elif isinstance(value, float):
        a.write_float(2, value)
        a.write_int(20, P.AttrType.FLOAT)
    elif isinstance(value, str):
        a.write_bytes(4, value.encode())
        a.write_int(20, P.AttrType.STRING)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], str):
            for v in value:  # AttributeProto.strings (field 9)
                a.write_bytes(9, v.encode())
            a.write_int(20, P.AttrType.STRINGS)
        elif value and isinstance(value[0], float):
            a.write_packed_floats(7, value)
            a.write_int(20, P.AttrType.FLOATS)
        else:
            a.write_packed_ints(8, [int(v) for v in value])
            a.write_int(20, P.AttrType.INTS)
    else:
        raise MXNetError(f"unsupported ONNX attribute value {value!r}")
    return a


def _node(op_type: str, inputs: List[str], outputs: List[str],
          name: str, attrs: Optional[Dict[str, Any]] = None) -> P.MessageWriter:
    n = P.MessageWriter()
    for i in inputs:
        n.write_string(1, i)
    for o in outputs:
        n.write_string(2, o)
    n.write_string(3, name)
    n.write_string(4, op_type)
    for k, v in (attrs or {}).items():
        n.write_message(5, _attr(k, v))
    return n


_NP2ONNX = {"float32": P.TensorDataType.FLOAT,
            "float64": P.TensorDataType.DOUBLE,
            "float16": P.TensorDataType.FLOAT16,
            "int32": P.TensorDataType.INT32,
            "int64": P.TensorDataType.INT64,
            "uint8": P.TensorDataType.UINT8,
            "int8": P.TensorDataType.INT8,
            "bool": P.TensorDataType.BOOL}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def _tensor(name: str, arr: onp.ndarray) -> P.MessageWriter:
    t = P.MessageWriter()
    for d in arr.shape:
        t.write_int(1, d)
    dt = _NP2ONNX.get(str(arr.dtype))
    if dt is None:  # bfloat16 and friends: store as float32
        arr = arr.astype("float32")
        dt = P.TensorDataType.FLOAT
    t.write_int(2, dt)
    t.write_string(8, name)
    t.write_bytes(9, onp.ascontiguousarray(arr).tobytes())
    return t


def _value_info(name: str, shape, elem_type=P.TensorDataType.FLOAT
                ) -> P.MessageWriter:
    tt = P.MessageWriter()
    tt.write_int(1, elem_type)
    if shape is not None:
        # shape omitted entirely when unknown: writing an empty
        # TensorShapeProto would declare a rank-0 scalar and trip
        # onnx shape inference on every non-scalar tensor
        dims = P.MessageWriter()
        for d in shape:
            dim = P.MessageWriter()
            dim.write_int(1, int(d))
            dims.write_message(1, dim)
        tt.write_message(2, dims)
    ty = P.MessageWriter()
    ty.write_message(1, tt)
    vi = P.MessageWriter()
    vi.write_string(1, name)
    vi.write_message(2, ty)
    return vi


# ---------------------------------------------------------------------------
# mx -> onnx op translation
# ---------------------------------------------------------------------------
# builder(node_name, attrs, in_names, out_name, extra) -> list of node
# MessageWriters; consts created along the way append to
# extra["initializers"].

_MX2ONNX = {}


def _mx2onnx(*opnames):
    def deco(fn):
        for n in opnames:
            _MX2ONNX[n] = fn
        return fn
    return deco


def _tup(attrs, key, default=None):
    v = attrs.get(key, default)
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return (int(v),)
    return tuple(int(x) for x in v)


@_mx2onnx("FullyConnected", "fully_connected")
def _fc(name, attrs, ins, out, extra):
    nodes = []
    data = ins[0]
    if attrs.get("flatten", True):
        flat = extra["unique"](f"{name}_flat")
        nodes.append(_node("Flatten", [data], [flat],
                           f"{name}_flatten", {"axis": 1}))
        data = flat
    gemm_in = [data, ins[1]] + (ins[2:] if len(ins) > 2 else [])
    nodes.append(_node("Gemm", gemm_in, [out], name,
                       {"alpha": 1.0, "beta": 1.0, "transB": 1}))
    return nodes


@_mx2onnx("Convolution", "convolution")
def _conv(name, attrs, ins, out, extra):
    kernel = _tup(attrs, "kernel")
    if kernel is None:
        raise MXNetError(f"ONNX export: Convolution {name} needs 'kernel'")
    k = len(kernel)
    a = {"kernel_shape": kernel,
         "strides": _tup(attrs, "stride") or (1,) * k,
         "dilations": _tup(attrs, "dilate") or (1,) * k,
         "pads": (_tup(attrs, "pad") or (0,) * k) * 2,
         "group": int(attrs.get("num_group", 1))}
    return [_node("Conv", ins, [out], name, a)]


@_mx2onnx("BatchNorm", "batch_norm")
def _bn(name, attrs, ins, out, extra):
    a = {"epsilon": float(attrs.get("eps", 1e-5)),
         "momentum": float(attrs.get("momentum", 0.9))}
    return [_node("BatchNormalization", ins, [out], name, a)]


@_mx2onnx("Activation")
def _act(name, attrs, ins, out, extra):
    act = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
           "softrelu": "Softplus", "softsign": "Softsign"}
    t = attrs.get("act_type", "relu")
    if t not in act:
        raise MXNetError(f"ONNX export: unsupported act_type {t!r}")
    return [_node(act[t], ins, [out], name)]


def _simple(op_type):
    def fn(name, attrs, ins, out, extra):
        return [_node(op_type, ins, [out], name)]
    return fn


for _mx, _ox in [("relu", "Relu"), ("sigmoid", "Sigmoid"),
                 ("tanh", "Tanh"), ("exp", "Exp"), ("log", "Log"),
                 ("sqrt", "Sqrt"), ("negative", "Neg"), ("abs", "Abs"),
                 ("add", "Add"), ("broadcast_add", "Add"),
                 ("sub", "Sub"), ("subtract", "Sub"),
                 ("broadcast_sub", "Sub"),
                 ("mul", "Mul"), ("multiply", "Mul"),
                 ("broadcast_mul", "Mul"),
                 ("div", "Div"), ("divide", "Div"),
                 ("broadcast_div", "Div"),
                 ("dot", "MatMul"), ("Flatten", "Flatten"),
                 ("identity", "Identity")]:
    _MX2ONNX[_mx] = _simple(_ox)


@_mx2onnx("softmax", "log_softmax")
def _softmax(name, attrs, ins, out, extra):
    op = "LogSoftmax" if "log" in extra["mx_op"] else "Softmax"
    return [_node(op, ins, [out], name,
                  {"axis": int(attrs.get("axis", -1))})]


@_mx2onnx("Pooling", "pooling", "global_pool")
def _pool(name, attrs, ins, out, extra):
    ptype = attrs.get("pool_type", "max")
    if attrs.get("global_pool", False) or extra["mx_op"] == "global_pool":
        op = {"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}.get(ptype)
        if op is None:
            raise MXNetError(f"ONNX export: global {ptype} pool unsupported")
        return [_node(op, ins, [out], name)]
    kernel = _tup(attrs, "kernel")
    if kernel is None:
        raise MXNetError(f"ONNX export: Pooling {name} needs 'kernel'")
    k = len(kernel)
    a = {"kernel_shape": kernel,
         "strides": _tup(attrs, "stride") or (1,) * k,
         "pads": (_tup(attrs, "pad") or (0,) * k) * 2}
    op = {"max": "MaxPool", "avg": "AveragePool"}.get(ptype)
    if op is None:
        raise MXNetError(f"ONNX export: pool_type {ptype!r} unsupported")
    if op == "AveragePool":
        a["count_include_pad"] = int(attrs.get("count_include_pad", True))
    return [_node(op, ins, [out], name, a)]


@_mx2onnx("Reshape", "reshape")
def _reshape(name, attrs, ins, out, extra):
    shape = _tup(attrs, "shape")
    sname = extra["unique"](f"{name}_shape")
    extra["initializers"].append(
        _tensor(sname, onp.asarray(shape, "int64")))
    return [_node("Reshape", [ins[0], sname], [out], name)]


@_mx2onnx("transpose")
def _transpose(name, attrs, ins, out, extra):
    a = {}
    if attrs.get("axes") is not None:
        a["perm"] = _tup(attrs, "axes")
    return [_node("Transpose", ins, [out], name, a)]


@_mx2onnx("Concat", "concat", "concatenate")
def _concat(name, attrs, ins, out, extra):
    return [_node("Concat", ins, [out], name,
                  {"axis": int(attrs.get("dim", attrs.get("axis", 1)))})]


@_mx2onnx("take", "embedding")
def _gather(name, attrs, ins, out, extra):
    # embedding is Gather(axis=0) over (weight, ids); take carries axis.
    # ONNX Gather treats out-of-range indices as undefined (and allows
    # negatives); take's clip/raise modes agree for all in-range indices,
    # but wrap semantics cannot be expressed
    if attrs.get("mode", "clip") == "wrap":
        raise MXNetError(
            f"ONNX export: take {name!r} with mode='wrap' has no Gather "
            f"equivalent (ONNX treats out-of-range as undefined)")
    if extra["mx_op"] == "embedding":
        data_in = [ins[1], ins[0]]
        axis = 0
    else:
        data_in = ins
        axis = int(attrs.get("axis", 0))
    return [_node("Gather", data_in, [out], name, {"axis": axis})]


@_mx2onnx("layer_norm", "LayerNorm")
def _layer_norm(name, attrs, ins, out, extra):
    # LayerNormalization entered ai.onnx at opset 17: the model's declared
    # opset is raised to match (other emitted ops are unchanged in 17)
    extra["min_opset"] = max(extra.get("min_opset", P.ONNX_OPSET), 17)
    return [_node("LayerNormalization", ins, [out], name,
                  {"axis": int(attrs.get("axis", -1)),
                   "epsilon": float(attrs.get("eps", 1e-5))})]


@_mx2onnx("mean", "sum")
def _reduce(name, attrs, ins, out, extra):
    op = "ReduceMean" if extra["mx_op"] == "mean" else "ReduceSum"
    if attrs.get("exclude", False):
        raise MXNetError(
            f"ONNX export: {extra['mx_op']} {name!r} with exclude=True "
            f"needs the input rank to compute complement axes; list the "
            f"axes explicitly instead")
    a = {"keepdims": int(attrs.get("keepdims", False))}
    axis = attrs.get("axis")
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        if op == "ReduceSum":
            # opset 13 moved ReduceSum axes to an input tensor
            aname = extra["unique"](f"{name}_axes")
            extra["initializers"].append(
                _tensor(aname, onp.asarray(axes, "int64")))
            return [_node(op, [ins[0], aname], [out], name, a)]
        a["axes"] = axes
    return [_node(op, ins, [out], name, a)]


@_mx2onnx("power", "broadcast_power")
def _pow(name, attrs, ins, out, extra):
    return [_node("Pow", ins, [out], name)]


@_mx2onnx("erf")
def _erf(name, attrs, ins, out, extra):
    return [_node("Erf", ins, [out], name)]


@_mx2onnx("squeeze", "expand_dims")
def _squeeze(name, attrs, ins, out, extra):
    # opset 13: axes ride an int64 input tensor for both ops
    op = "Squeeze" if extra["mx_op"] == "squeeze" else "Unsqueeze"
    axis = attrs.get("axis")
    if axis is None and op == "Squeeze":
        return [_node(op, ins, [out], name)]
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    aname = extra["unique"](f"{name}_axes")
    extra["initializers"].append(
        _tensor(aname, onp.asarray(axes, "int64")))
    return [_node(op, [ins[0], aname], [out], name)]


@_mx2onnx("clip")
def _clip(name, attrs, ins, out, extra):
    # opset 13: min/max ride input tensors; a missing bound is an empty
    # input slot (ONNX optional-input convention, e.g. ReLU6 = max-only).
    # Bounds take the graph's declared element type so Clip's same-type-T
    # constraint holds for non-float32 models.
    dt = extra.get("elem_np_dtype", "float32")
    names = [ins[0]]
    for suffix, key in (("min", "a_min"), ("max", "a_max")):
        val = attrs.get(key)
        if val is None or (onp.dtype(dt).kind in "iu"
                           and not onp.isfinite(val)):
            # absent bound — or an infinite bound that an integer T cannot
            # represent (a one-sided clip on int data): empty slot
            names.append("")
            continue
        nm = extra["unique"](f"{name}_{suffix}")
        extra["initializers"].append(
            _tensor(nm, onp.asarray(val, dt)))
        names.append(nm)
    while names and names[-1] == "":
        names.pop()  # trailing absent optionals are simply omitted
    return [_node("Clip", names, [out], name)]


@_mx2onnx("minimum", "broadcast_minimum", "maximum", "broadcast_maximum")
def _minmax(name, attrs, ins, out, extra):
    op = "Min" if "min" in extra["mx_op"] else "Max"
    return [_node(op, ins, [out], name)]


@_mx2onnx("LeakyReLU", "leaky_relu")
def _leaky(name, attrs, ins, out, extra):
    t = attrs.get("act_type", "leaky")
    if t == "leaky":
        return [_node("LeakyRelu", ins[:1], [out], name,
                      {"alpha": float(attrs.get("slope", 0.25))})]
    if t == "elu":
        return [_node("Elu", ins[:1], [out], name,
                      {"alpha": float(attrs.get("slope", 0.25))})]
    # prelu is deliberately not exported: ONNX PRelu's slope broadcast
    # (unidirectional from the left) differs from this op's gamma layout,
    # and an asymmetric export (no importer) would break the round-trip
    # contract — same-family Clip/LeakyRelu/Elu all have both directions
    raise MXNetError(f"ONNX export: LeakyReLU act_type {t!r} unsupported")


@_mx2onnx("slice_axis")
def _slice_axis(name, attrs, ins, out, extra):
    # opset 13 Slice: starts/ends/axes are input tensors
    axis = int(attrs["axis"])
    begin = int(attrs.get("begin", 0))
    end = attrs.get("end")
    end = int(end) if end is not None else (1 << 62)
    names = []
    for suffix, val in (("starts", begin), ("ends", end), ("axes", axis)):
        nm = extra["unique"](f"{name}_{suffix}")
        extra["initializers"].append(
            _tensor(nm, onp.asarray([val], "int64")))
        names.append(nm)
    return [_node("Slice", [ins[0]] + names, [out], name)]


@_mx2onnx("Dropout", "dropout")
def _dropout(name, attrs, ins, out, extra):
    # inference graph: Identity (reference exporter emits Dropout, which
    # inference consumers also treat as identity)
    return [_node("Identity", ins, [out], name)]


@_mx2onnx("UpSampling", "upsampling")
def _upsampling(name, attrs, ins, out, extra):
    # opset-13 Resize: X, roi(''), scales. Integer upscaling is identical
    # across coordinate conventions; asymmetric+floor states it exactly
    s = float(attrs.get("scale", 2))
    sname = extra["unique"](f"{name}_scales")
    extra["initializers"].append(
        _tensor(sname, onp.asarray([1.0, 1.0, s, s], "float32")))
    if attrs.get("sample_type", "nearest") == "nearest":
        # integer nearest upscaling is identical across coordinate
        # conventions; asymmetric+floor states it exactly
        a = {"mode": "nearest",
             "coordinate_transformation_mode": "asymmetric",
             "nearest_mode": "floor"}
    else:
        # the op lowers to jax.image.resize linear = half-pixel centers
        a = {"mode": "linear",
             "coordinate_transformation_mode": "half_pixel"}
    return [_node("Resize", [ins[0], "", sname], [out], name, a)]


# mx gate blocks -> ONNX gate blocks (row-block permutation of W/R/B):
# LSTM ours [i, f, g, o] -> ONNX [i, o, f, c]; GRU ours [r, z, n] ->
# ONNX [z, r, h]; vanilla RNN is single-gate
_RNN_GATE_PERM = {"lstm": [0, 3, 1, 2], "gru": [1, 0, 2],
                  "rnn_tanh": [0], "rnn_relu": [0]}
_RNN_ONNX_OP = {"lstm": "LSTM", "gru": "GRU",
                "rnn_tanh": "RNN", "rnn_relu": "RNN"}


def _rnn_gate_reorder(mat, perm, h):
    """Permute gate blocks (rows of size h) of a (G*h, ...) or (G*h,)
    array."""
    blocks = [mat[i * h:(i + 1) * h] for i in range(len(perm))]
    return onp.concatenate([blocks[p] for p in perm], axis=0)


@_mx2onnx("RNN")
def _rnn_export(name, attrs, ins, out, extra):
    """Reference RNN op -> chain of ONNX LSTM/GRU/RNN nodes (one per
    layer — ONNX recurrent nodes are single-layer). The packed cuDNN
    parameter vector is repacked into per-layer ONNX W (D, G*H, C) /
    R (D, G*H, H) / B (D, 2*G*H) tensors with the gate order translated;
    each layer's Y converts (T, D, N, H) -> (T, N, D*H) to feed the
    next."""
    from ..ndarray.nn_ops import _rnn_layout, _rnn_unpack
    mode = attrs.get("mode", "lstm")
    num_layers = int(attrs.get("num_layers", 1))
    if attrs.get("state_outputs") or attrs.get("onnx_outputs"):
        raise MXNetError("ONNX export: RNN with state/onnx outputs has no "
                         "single-output translation; export the output-"
                         "only form")
    if num_layers > 1 and len(ins) > 2:
        raise MXNetError("ONNX export: multi-layer RNN with explicit "
                         "initial states needs per-layer state slicing — "
                         "export the zero-state form")
    h = int(attrs["state_size"])
    bi = bool(attrs.get("bidirectional", False))
    dirs = 2 if bi else 1
    g = {"lstm": 4, "gru": 3, "rnn_tanh": 1, "rnn_relu": 1}[mode]
    pv = extra.get("param_values", {}).get(ins[1])
    if pv is None:
        raise MXNetError("ONNX export: RNN parameters must be a bound "
                         "parameter (initializer), not a graph input")
    total = pv.size
    # invert rnn_packed_param_size: layer 0 sees C inputs, deeper layers
    # see H*dirs -> C = total/(D*G*H) - (L-1)*(H*D + H + 2) - H - 2
    c_in = (total // (dirs * g * h)
            - (num_layers - 1) * (h * dirs + h + 2) - h - 2)
    order, expect = _rnn_layout(mode, int(c_in), h, num_layers, bi)
    if c_in < 1 or expect != total:
        raise MXNetError(f"ONNX export: RNN packed size {total} does not "
                         f"factor as {num_layers} layer(s) (inferred "
                         f"C={c_in})")
    perm = _RNN_GATE_PERM[mode]
    flat = _rnn_unpack(pv, order)
    extra.setdefault("drop_initializers", set()).add(ins[1])

    shp = extra["unique"](f"{name}_Yshape")
    extra["initializers"].append(
        _tensor(shp, onp.asarray([0, 0, -1], "int64")))
    nodes = []
    layer_in = ins[0]
    for layer in range(num_layers):
        Ws, Rs, Bs = [], [], []
        for d in range(dirs):
            base = 4 * (layer * dirs + d)
            w_ih, w_hh, b_ih, b_hh = flat[base:base + 4]
            Ws.append(_rnn_gate_reorder(w_ih, perm, h))
            Rs.append(_rnn_gate_reorder(w_hh, perm, h))
            Bs.append(onp.concatenate(
                [_rnn_gate_reorder(b_ih, perm, h),
                 _rnn_gate_reorder(b_hh, perm, h)]))
        names = {}
        for key, arr in (("W", onp.stack(Ws)), ("R", onp.stack(Rs)),
                         ("B", onp.stack(Bs))):
            nm = extra["unique"](f"{name}_l{layer}_{key}")
            extra["initializers"].append(_tensor(nm, arr.astype("float32")))
            names[key] = nm
        node_in = [layer_in, names["W"], names["R"], names["B"], ""]
        if num_layers == 1:
            node_in.append(ins[2] if len(ins) > 2 else "")   # initial_h
            if mode == "lstm":
                node_in.append(ins[3] if len(ins) > 3 else "")
        while node_in and node_in[-1] == "":
            node_in.pop()
        a: Dict[str, Any] = {
            "hidden_size": h,
            "direction": "bidirectional" if bi else "forward"}
        if mode == "gru":
            a["linear_before_reset"] = 1  # r applies to (h W_hh + b)
        if mode == "rnn_relu":
            a["activations"] = ["Relu"] * dirs
        y_raw = extra["unique"](f"{name}_l{layer}_Y")
        nodes.append(_node(_RNN_ONNX_OP[mode], node_in, [y_raw],
                           f"{name}_l{layer}" if num_layers > 1 else name,
                           a))
        # ONNX Y is (T, D, N, H); the op/next layer wants (T, N, D*H)
        y_tr = extra["unique"](f"{name}_l{layer}_Ytr")
        nodes.append(_node("Transpose", [y_raw], [y_tr],
                           f"{name}_l{layer}_tr", {"perm": [0, 2, 1, 3]}))
        last = layer == num_layers - 1
        y_out = out if last else extra["unique"](f"{name}_l{layer}_Yflat")
        nodes.append(_node("Reshape", [y_tr, shp], [y_out],
                           f"{name}_l{layer}_rs"))
        layer_in = y_out
    return nodes


@_mx2onnx("add_scalar", "sub_scalar", "mul_scalar", "div_scalar")
def _scalar_arith(name, attrs, ins, out, extra):
    op = {"add": "Add", "sub": "Sub", "mul": "Mul",
          "div": "Div"}[extra["mx_op"].split("_")[0]]
    cname = extra["unique"](f"{name}_const")
    # ONNX arithmetic is same-type-T on both operands: type the scalar
    # like the graph's element dtype (same signal _clip uses)
    dt = extra.get("elem_np_dtype", "float32")
    scalar = float(attrs["scalar"])
    try:
        with onp.errstate(over="ignore"):  # MXNetError raised below
            cast = onp.asarray(scalar, dt)
        bad_int = onp.dtype(dt).kind in "iu" and float(cast) != scalar
        bad_float = onp.isfinite(scalar) and not onp.all(onp.isfinite(cast))
    except (OverflowError, ValueError):
        # numpy raises eagerly for int dtypes (out-of-range / NaN scalars)
        bad_int, bad_float = True, False
    if bad_int or bad_float:
        # an integer T cannot carry a fractional/overflowing scalar, and a
        # narrow float T overflows large scalars to inf — either way the
        # const would make a silently wrong graph (in-range float rounding
        # is fine: normal lossy representation)
        raise MXNetError(
            f"ONNX export: scalar {scalar} is not representable in the "
            f"graph element type {dt} ({extra['mx_op']} node {name!r})")
    extra["initializers"].append(_tensor(cname, cast))
    return [_node(op, [ins[0], cname], [out], name)]


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def export_model(sym, params, in_shapes=None, in_types=None,
                 onnx_file_path="model.onnx", verbose=False,
                 opset_version=P.ONNX_OPSET, **kwargs):
    """Export a Symbol (+params dict name->NDArray) to an ONNX file
    (reference mx2onnx.export_model). Returns the file path."""
    from ..symbol.symbol import Symbol, StableHLOSymbol
    if isinstance(sym, StableHLOSymbol):
        raise MXNetError("ONNX export needs an op-level Symbol (mx.sym "
                         "graph); StableHLO exports already ARE a portable "
                         "compiler format")
    if not isinstance(sym, Symbol):
        raise MXNetError("export_model expects a Symbol")
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}

    graph = P.MessageWriter()
    extra: Dict[str, Any] = {"initializers": []}
    # element type for typed scalar consts (Clip bounds must match the
    # tensor type T they clamp). Without per-node type inference the best
    # available signal is, in order: a single float dtype shared by every
    # PARAMETER (weights type the activations — covers int-token-id models
    # with float weights), else a single dtype shared by every declared
    # input (covers all-int graphs whose clip genuinely runs on ints),
    # else the float32 default. Documented limitation for mixed graphs.
    param_dts = set()
    any_float_params = False
    for v in params.values():
        try:
            dt = onp.dtype(v.dtype)
        except TypeError:
            continue
        if dt.kind == "f":
            any_float_params = True
            param_dts.add(str(dt))
    if len(param_dts) == 1:
        extra["elem_np_dtype"] = next(iter(param_dts))
    elif in_types and not any_float_params:
        # no float weights anywhere: the declared input dtype (when
        # uniform) IS the tensor type clip runs on — safe even for ints
        try:
            dts = {str(onp.dtype(t)) for t in in_types if t}
            if len(dts) == 1:
                extra["elem_np_dtype"] = next(iter(dts))
        except TypeError:
            pass
    emitted: Dict[int, str] = {}
    used_names: set = set()
    input_vis = []
    in_shapes = list(in_shapes or [])
    in_types = list(in_types or [])
    var_idx = [0]

    def unique(nm: str) -> str:
        # ONNX graphs are SSA: every value name must be unique, while
        # symbol-factory default names (f"{op}_{n_inputs}") collide freely
        base, k = nm, 1
        while nm in used_names:
            nm = f"{base}_{k}"
            k += 1
        used_names.add(nm)
        return nm

    extra["unique"] = unique  # builders reserve helper value names too

    def visit(s) -> str:
        if id(s) in emitted:
            return emitted[id(s)]
        if s._op is None:
            nm = unique(s._name)
            emitted[id(s)] = nm
            if s._name in params:
                arr = onp.asarray(params[s._name].asnumpy())
                t = _tensor(nm, arr)
                extra["initializers"].append(t)
                # translators that REPACK a parameter (RNN's packed
                # vector) need its value and may drop the raw tensor
                extra.setdefault("param_values", {})[nm] = arr
                extra.setdefault("param_tensors", {})[nm] = t
            else:
                shape = s._attrs.get("shape")
                if shape is None and var_idx[0] < len(in_shapes):
                    shape = in_shapes[var_idx[0]]
                elem = P.TensorDataType.FLOAT
                if var_idx[0] < len(in_types) and in_types[var_idx[0]]:
                    elem = _NP2ONNX.get(
                        str(onp.dtype(in_types[var_idx[0]])), elem)
                var_idx[0] += 1
                input_vis.append(_value_info(nm, shape, elem))
            return nm
        ins = [visit(i) for i in s._inputs]
        for nm in ins:  # consumer counts gate drop_initializers below
            refs = extra.setdefault("input_refs", {})
            refs[nm] = refs.get(nm, 0) + 1
        builder = _MX2ONNX.get(s._op)
        if builder is None:
            raise MXNetError(
                f"ONNX export: no translation for op {s._op!r} "
                f"(node {s._name!r})")
        out = unique(s._name)
        extra["mx_op"] = s._op
        attrs = {k: v for k, v in s._attrs.items() if v is not None}
        # pass the uniquified name so helper nodes/consts a builder emits
        # (f"{name}_flat", f"{name}_shape") inherit uniqueness
        for nd_msg in builder(out, attrs, ins, out, extra):
            graph.write_message(1, nd_msg)
        emitted[id(s)] = out
        return out

    head = visit(sym)
    graph.write_string(2, "mxnet_tpu")
    # drop repacked parameters (RNN packed vector) ONLY when the
    # repacking node was their sole consumer — another node may still
    # reference the raw tensor
    refs = extra.get("input_refs", {})
    dropped = {extra.get("param_tensors", {}).get(n)
               for n in extra.get("drop_initializers", ())
               if refs.get(n, 0) <= 1}
    for t in extra["initializers"]:
        if t in dropped:
            continue
        graph.write_message(5, t)
    for vi in input_vis:
        graph.write_message(11, vi)
    graph.write_message(12, _value_info(head, None))

    model = P.MessageWriter()
    model.write_int(1, P.ONNX_IR_VERSION)
    model.write_string(2, "mxnet_tpu")
    model.write_string(3, "2.0")
    opset = P.MessageWriter()
    opset.write_string(1, "")
    # ops introduced after the requested opset raise the declared version
    # (e.g. LayerNormalization -> 17); earlier ops are unchanged there
    opset.write_int(2, max(opset_version,
                           extra.get("min_opset", opset_version)))
    model.write_message(8, opset)
    model.write_message(7, graph)
    with open(onnx_file_path, "wb") as f:
        f.write(model.tobytes())
    if verbose:
        print(f"exported ONNX model to {onnx_file_path}")
    return onnx_file_path


# ---------------------------------------------------------------------------
# import
# ---------------------------------------------------------------------------

def _get_str(fields, num, default=""):
    for wire, val in fields.get(num, []):
        return val.decode()
    return default


def _get_int(fields, num, default=0):
    for wire, val in fields.get(num, []):
        return val
    return default


def _parse_tensor(data: bytes) -> Tuple[str, onp.ndarray]:
    f = P.parse_message(data)
    dims = P.unpack_ints(f.get(1, []))
    dt = _get_int(f, 2, P.TensorDataType.FLOAT)
    name = _get_str(f, 8)
    np_dt = _ONNX2NP.get(dt)
    if np_dt is None:
        raise MXNetError(f"ONNX import: unsupported tensor dtype {dt}")
    raw = f.get(9)
    if raw:
        arr = onp.frombuffer(raw[0][1], dtype=np_dt).reshape(dims)
    elif dt == P.TensorDataType.FLOAT and f.get(4):
        import struct as _s
        blob = f[4][0][1]
        arr = onp.asarray(_s.unpack(f"<{len(blob) // 4}f", blob),
                          "float32").reshape(dims)
    elif dt == P.TensorDataType.INT64 and f.get(7):
        arr = onp.asarray([P.signed64(v) for v in P.unpack_ints(f[7])],
                          "int64").reshape(dims)
    elif dt in (P.TensorDataType.INT32, P.TensorDataType.UINT8,
                P.TensorDataType.INT8, P.TensorDataType.BOOL) and f.get(5):
        # int32_data (field 5) also carries uint8/int8/bool per the spec
        arr = onp.asarray([P.signed64(v) for v in P.unpack_ints(f[5])]
                          ).astype(np_dt).reshape(dims)
    elif int(onp.prod(dims)) == 0:
        arr = onp.zeros(dims, np_dt)
    else:
        raise MXNetError(
            f"ONNX import: tensor {name!r} uses an unsupported data "
            f"encoding (dtype {dt}; raw_data/float_data/int64_data/"
            f"int32_data are handled, external data is not)")
    return name, arr


def _parse_attrs(entries) -> Dict[str, Any]:
    import struct as _s
    out = {}
    for wire, data in entries:
        f = P.parse_message(data)
        name = _get_str(f, 1)
        atype = _get_int(f, 20, 0)
        if atype == P.AttrType.INT or (atype == 0 and 3 in f):
            out[name] = P.signed64(_get_int(f, 3))
        elif atype == P.AttrType.FLOAT or (atype == 0 and 2 in f):
            out[name] = _s.unpack("<f", f[2][0][1])[0]
        elif atype == P.AttrType.STRING or (atype == 0 and 4 in f):
            out[name] = f[4][0][1].decode()
        elif atype == P.AttrType.INTS or (atype == 0 and 8 in f):
            out[name] = tuple(P.signed64(v)
                              for v in P.unpack_ints(f.get(8, [])))
        elif atype == P.AttrType.FLOATS or (atype == 0 and 7 in f):
            blob = f[7][0][1]
            out[name] = tuple(_s.unpack(f"<{len(blob) // 4}f", blob))
        elif atype == P.AttrType.STRINGS or (atype == 0 and 9 in f):
            out[name] = tuple(v.decode() for w, v in f.get(9, []))
        elif atype == P.AttrType.TENSOR:
            out[name] = _parse_tensor(f[5][0][1])[1]
    return out


def _onnx_pads(attrs, k):
    pads = attrs.get("pads")
    if pads is None:
        return (0,) * k
    begin, end = pads[:k], pads[k:]
    if tuple(begin) != tuple(end):
        raise MXNetError("ONNX import: asymmetric pads unsupported")
    return tuple(begin)


def import_model(model_file: str):
    """Parse an ONNX file into (sym, arg_params, aux_params) (reference
    onnx2mx.import_model)."""
    from ..symbol.symbol import Variable
    from ..ndarray.ndarray import NDArray

    with open(model_file, "rb") as f:
        model = P.parse_message(f.read())
    if 7 not in model:
        raise MXNetError(f"{model_file!r} is not an ONNX ModelProto")
    g = P.parse_message(model[7][0][1])

    inits: Dict[str, onp.ndarray] = {}
    for wire, t in g.get(5, []):
        name, arr = _parse_tensor(t)
        inits[name] = arr

    sym_of: Dict[str, Any] = {}
    const_of: Dict[str, onp.ndarray] = dict(inits)

    for wire, vi in g.get(11, []):
        f = P.parse_message(vi)
        nm = _get_str(f, 1)
        if nm not in inits:
            sym_of[nm] = Variable(nm)

    def sym_in(nm):
        if nm not in sym_of:
            sym_of[nm] = Variable(nm)
        return sym_of[nm]

    last_out = None
    for wire, nd_bytes in g.get(1, []):
        f = P.parse_message(nd_bytes)
        ins = [v.decode() for w, v in f.get(1, [])]
        outs = [v.decode() for w, v in f.get(2, [])]
        name = _get_str(f, 3) or outs[0]
        op = _get_str(f, 4)
        attrs = _parse_attrs(f.get(5, []))
        if op == "Constant":
            # fold into the const table: exporters commonly feed Reshape
            # shapes / Clip bounds / Slice starts via Constant nodes.
            # Also register as an initializer (so a Constant consumed as a
            # tensor operand, e.g. Add, surfaces in arg_params like any
            # other weight) and as a Variable (so a Constant feeding the
            # graph output directly still resolves)
            const_of[outs[0]] = inits[outs[0]] = _constant_value(name, attrs)
            sym_of.setdefault(outs[0], Variable(outs[0]))
            last_out = outs[0]
            continue
        s = _import_node(op, name, ins, outs, attrs, sym_in, const_of)
        if isinstance(s, dict):      # multi-output node (Split)
            sym_of.update(s)
        else:
            sym_of[outs[0]] = s
        last_out = outs[0]

    # values synthesized by node importers (RNN's repacked parameter
    # vector) surface as parameters like any initializer
    for k, v in const_of.items():
        inits.setdefault(k, v)

    out_names = [_get_str(P.parse_message(vi), 1)
                 for w, vi in g.get(12, [])]
    head = sym_of[out_names[0] if out_names and out_names[0] in sym_of
                  else last_out]

    used = set(head.list_arguments())
    arg_params, aux_params = {}, {}
    for nm, arr in inits.items():
        if nm not in used:
            continue  # consumed as a constant (e.g. Reshape shape input)
        dest = aux_params if ("moving_" in nm or "running_" in nm) \
            else arg_params
        dest[nm] = NDArray(onp.ascontiguousarray(arr))
    return head, arg_params, aux_params


def _constant_value(name, attrs) -> onp.ndarray:
    """Evaluate an ONNX Constant node's single value attribute."""
    if "value" in attrs:               # TENSOR attr, parsed to ndarray
        return onp.asarray(attrs["value"])
    if "value_float" in attrs:
        return onp.asarray(attrs["value_float"], "float32")
    if "value_int" in attrs:
        return onp.asarray(attrs["value_int"], "int64")
    if "value_floats" in attrs:
        return onp.asarray(attrs["value_floats"], "float32")
    if "value_ints" in attrs:
        return onp.asarray(attrs["value_ints"], "int64")
    raise MXNetError(f"ONNX import: Constant node {name!r} carries an "
                     "unsupported value attribute (value/value_float[s]/"
                     "value_int[s] are handled)")


def _import_node(op, name, ins, outs, attrs, sym_in, consts):
    from ..symbol.symbol import Symbol

    def S(mx_op, inputs, a=None):
        return Symbol(mx_op, name, [sym_in(i) for i in inputs], a or {})

    simple = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
              "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Neg": "negative",
              "Abs": "abs", "Add": "broadcast_add", "Sub": "broadcast_sub",
              "Mul": "broadcast_mul", "Div": "broadcast_div",
              "MatMul": "dot", "Flatten": "Flatten",
              "Identity": "identity", "Softplus": "softrelu",
              "Pow": "broadcast_power", "Erf": "erf"}
    if op in simple:
        return S(simple[op], ins)
    if op == "Clip":
        a_min = a_max = None
        dynamic = False
        if len(ins) > 1 and ins[1]:
            v = consts.get(ins[1])
            a_min = float(v) if v is not None else None
            dynamic |= v is None
        if len(ins) > 2 and ins[2]:
            v = consts.get(ins[2])
            a_max = float(v) if v is not None else None
            dynamic |= v is None
        if "min" in attrs:  # pre-opset-11 attribute form
            a_min = float(attrs["min"])
        if "max" in attrs:
            a_max = float(attrs["max"])
        if dynamic:
            raise MXNetError("ONNX import: Clip with non-constant bounds "
                             "unsupported")
        # one-sided clip (ReLU6 etc.): encode the absent bound as ∓inf —
        # numerically identical, and it survives the executor's
        # None-attr-means-unset filtering
        return S("clip", ins[:1],
                 {"a_min": float("-inf") if a_min is None else a_min,
                  "a_max": float("inf") if a_max is None else a_max})
    if op in ("Min", "Max"):
        if len(ins) < 1:
            raise MXNetError(f"ONNX import: {op} needs at least one input")
        mx_op = "broadcast_minimum" if op == "Min" else "broadcast_maximum"
        if len(ins) == 1:
            return S("identity", ins)
        # variadic form folds left as a chain of pairwise ops; the final
        # link carries the ONNX node's name
        acc = sym_in(ins[0])
        last = len(ins) - 2
        for j, nxt in enumerate(ins[1:]):
            acc = Symbol(mx_op, name if j == last else f"{name}_fold{j}",
                         [acc, sym_in(nxt)], {})
        return acc
    if op == "LeakyRelu":
        return S("LeakyReLU", ins, {"act_type": "leaky",
                                    "slope": float(attrs.get("alpha", 0.01))})
    if op == "Elu":
        return S("LeakyReLU", ins, {"act_type": "elu",
                                    "slope": float(attrs.get("alpha", 1.0))})
    if op == "Gather":
        # mode='wrap': ONNX Gather permits negative indices (from the end);
        # modulo indexing reproduces that exactly for indices in [-n, n)
        return S("take", [ins[0], ins[1]],
                 {"axis": int(attrs.get("axis", 0)), "mode": "wrap"})
    if op == "LayerNormalization":
        return S("LayerNorm", ins,
                 {"axis": int(attrs.get("axis", -1)),
                  "eps": float(attrs.get("epsilon", 1e-5))})
    if op in ("ReduceMean", "ReduceSum"):
        a = {"keepdims": bool(attrs.get("keepdims", 1))}
        if len(ins) > 1:  # opset-13 axes input tensor
            axes = consts.get(ins[1])
            if axes is None:
                raise MXNetError("ONNX import: dynamic reduce axes "
                                 "unsupported")
            a["axis"] = tuple(int(v) for v in axes)
        elif "axes" in attrs:
            a["axis"] = tuple(attrs["axes"])
        return S("mean" if op == "ReduceMean" else "sum", ins[:1], a)
    if op in ("Squeeze", "Unsqueeze"):
        axes = None
        if len(ins) > 1:
            axes = consts.get(ins[1])
            if axes is None:
                raise MXNetError(f"ONNX import: dynamic {op} axes "
                                 "unsupported")
            axes = tuple(int(v) for v in axes)
        elif "axes" in attrs:
            axes = tuple(attrs["axes"])
        if op == "Unsqueeze":
            if axes is None or len(axes) != 1:
                raise MXNetError("ONNX import: Unsqueeze needs one axis")
            return S("expand_dims", ins[:1], {"axis": axes[0]})
        a = {"axis": axes if axes is None or len(axes) > 1
             else axes[0]} if axes is not None else {}
        return S("squeeze", ins[:1], a)
    if op == "Slice":
        vals = [consts.get(i) for i in ins[1:]]
        if any(v is None for v in vals[:2]):
            raise MXNetError("ONNX import: dynamic Slice unsupported")
        starts, ends = vals[0], vals[1]
        axes = vals[2] if len(vals) > 2 and vals[2] is not None \
            else list(range(len(starts)))
        if len(vals) > 3 and vals[3] is not None \
                and any(int(s) != 1 for s in vals[3]):
            raise MXNetError(
                "ONNX import: Slice with steps != 1 unsupported")
        if len(starts) != 1:
            raise MXNetError("ONNX import: multi-axis Slice unsupported")
        end = int(ends[0])
        return S("slice_axis", ins[:1],
                 {"axis": int(axes[0]), "begin": int(starts[0]),
                  "end": None if end >= (1 << 60) else end})
    if op == "Gemm":
        beta = attrs.get("beta", 1.0)
        if attrs.get("transB", 0) != 1 or attrs.get("alpha", 1.0) != 1.0 \
                or not (beta == 1.0 or (beta == 0.0 and len(ins) < 3)):
            raise MXNetError("ONNX import: general Gemm unsupported; "
                             "expected transB=1 alpha=1 beta=1")
        return S("FullyConnected", ins,
                 {"no_bias": len(ins) < 3, "flatten": False})
    if op == "Conv":
        k = len(attrs["kernel_shape"])
        return S("Convolution", ins, {
            "kernel": tuple(attrs["kernel_shape"]),
            "stride": tuple(attrs.get("strides", (1,) * k)),
            "dilate": tuple(attrs.get("dilations", (1,) * k)),
            "pad": _onnx_pads(attrs, k),
            "num_group": int(attrs.get("group", 1)),
            "no_bias": len(ins) < 3})
    if op == "BatchNormalization":
        return S("BatchNorm", ins, {
            "eps": float(attrs.get("epsilon", 1e-5)),
            "momentum": float(attrs.get("momentum", 0.9)),
            "use_global_stats": True})
    if op in ("MaxPool", "AveragePool"):
        k = len(attrs["kernel_shape"])
        a = {"kernel": tuple(attrs["kernel_shape"]),
             "stride": tuple(attrs.get("strides", (1,) * k)),
             "pad": _onnx_pads(attrs, k),
             "pool_type": "max" if op == "MaxPool" else "avg"}
        if op == "AveragePool":
            # ONNX spec default EXCLUDES padding from the average
            a["count_include_pad"] = bool(
                attrs.get("count_include_pad", 0))
        return S("Pooling", ins, a)
    if op in ("GlobalMaxPool", "GlobalAveragePool"):
        return S("Pooling", ins, {
            "pool_type": "max" if op == "GlobalMaxPool" else "avg",
            "global_pool": True, "kernel": (1, 1)})
    if op in ("Softmax", "LogSoftmax"):
        return S("softmax" if op == "Softmax" else "log_softmax", ins,
                 {"axis": int(attrs.get("axis", -1))})
    if op == "Reshape":
        shape = consts.get(ins[1])
        if shape is None:
            raise MXNetError("ONNX import: dynamic Reshape unsupported")
        return S("reshape", ins[:1],
                 {"shape": tuple(int(v) for v in shape)})
    if op == "Resize":
        mode = attrs.get("mode", "nearest")
        if isinstance(mode, bytes):
            mode = mode.decode()
        ctm = attrs.get("coordinate_transformation_mode", "half_pixel")
        if isinstance(ctm, bytes):
            ctm = ctm.decode()
        scales = consts.get(ins[2]) if len(ins) > 2 and ins[2] else None
        sizes = consts.get(ins[3]) if len(ins) > 3 and ins[3] else None
        if scales is None and sizes is None:
            raise MXNetError("ONNX import: Resize needs constant scales "
                             "or sizes")
        # supported numerics only — NEVER silently substitute another
        # interpolation: linear requires half-pixel (what jax.image.resize
        # computes); nearest requires equal integer scales (convention-
        # independent). Everything else raises.
        if mode == "linear" and ctm != "half_pixel":
            raise MXNetError(
                f"ONNX import: Resize linear with coordinate mode {ctm!r} "
                "unsupported (half_pixel only; align_corners/asymmetric "
                "would import with different interior numerics)")
        if mode not in ("nearest", "linear"):
            raise MXNetError(f"ONNX import: Resize mode {mode!r} "
                             "unsupported (nearest/linear)")
        if sizes is not None:
            if mode != "linear":
                raise MXNetError("ONNX import: Resize with explicit sizes "
                                 "supports mode=linear only (nearest needs"
                                 " shape inference this importer skips)")
            h, w = int(sizes[-2]), int(sizes[-1])
            return S("BilinearResize2D", ins[:1],
                     {"height": h, "width": w})
        sc = [float(v) for v in scales]
        if len(sc) != 4 or sc[0] != 1 or sc[1] != 1:
            raise MXNetError("ONNX import: Resize scales must be "
                             "[1,1,sh,sw] (NCHW spatial resize)")
        if mode == "nearest":
            if not (sc[2] == sc[3] and float(sc[2]).is_integer()
                    and sc[2] >= 1):
                raise MXNetError(
                    "ONNX import: nearest Resize supports equal integer "
                    f"upscale factors only, got {sc[2:]} (substituting "
                    "linear would silently change the numerics)")
            # integer upscaling equals pixel replication ONLY under
            # asymmetric or half_pixel coordinates with floor /
            # round_prefer_floor rounding (the defaults); ceil and
            # align_corners shift the mapping
            nm_attr = attrs.get("nearest_mode", "round_prefer_floor")
            if isinstance(nm_attr, bytes):
                nm_attr = nm_attr.decode()
            # exact-replication PAIRS only: asymmetric+floor maps dst i ->
            # floor(i/s); half_pixel+round_prefer_floor maps to
            # round_pf((i+.5)/s - .5) — both equal replication for every
            # integer s. Mixed pairs (half_pixel+floor, asymmetric+round)
            # shift sources at some scales
            if (ctm, nm_attr) not in (("asymmetric", "floor"),
                                      ("half_pixel",
                                       "round_prefer_floor")):
                raise MXNetError(
                    f"ONNX import: nearest Resize with coordinate mode "
                    f"{ctm!r} / nearest_mode {nm_attr!r} is not pixel "
                    "replication — unsupported")
            return S("UpSampling", ins[:1],
                     {"scale": int(sc[2]), "sample_type": "nearest"})
        return S("BilinearResize2D", ins[:1],
                 {"scale_height": sc[2], "scale_width": sc[3],
                  "mode": "scale"})
    if op == "Transpose":
        a = {}
        if "perm" in attrs:
            a["axes"] = tuple(attrs["perm"])
        return S("transpose", ins, a)
    if op == "Concat":
        return S("concat", ins, {"dim": int(attrs.get("axis", 1))})
    if op == "Dropout":
        return S("identity", ins[:1])
    if op == "Split":
        num = len(outs)
        if len(ins) > 1 and ins[1]:   # opset-13 split-sizes input tensor
            sizes = consts.get(ins[1])
            if sizes is None:
                raise MXNetError("ONNX import: dynamic Split sizes "
                                 "unsupported")
            if len(set(int(v) for v in sizes)) != 1:
                raise MXNetError("ONNX import: unequal Split sizes "
                                 "unsupported (equal chunks only)")
        elif "split" in attrs and \
                len(set(int(v) for v in attrs["split"])) != 1:
            raise MXNetError("ONNX import: unequal Split sizes unsupported")
        axis = int(attrs.get("axis", 0))
        src = sym_in(ins[0])
        group = object()  # one shared eval of the split per forward
        result = {}
        for i, o in enumerate(outs):
            node = Symbol("split", name, [src],
                          {"num_outputs": num, "axis": axis}, out_index=i)
            node._group_key = group
            result[o] = node
        return result
    if op in ("LSTM", "GRU", "RNN"):
        g = {"LSTM": 4, "GRU": 3, "RNN": 1}[op]
        h = int(attrs["hidden_size"])
        W = consts.get(ins[1]) if len(ins) > 1 else None
        R = consts.get(ins[2]) if len(ins) > 2 else None
        has_b = len(ins) > 3 and ins[3]
        B = consts.get(ins[3]) if has_b else None
        if W is None or R is None:
            raise MXNetError("ONNX import: recurrent W/R must be constant "
                             "initializers")
        if has_b and B is None:
            # a PRESENT but non-constant B must not silently become zeros
            raise MXNetError("ONNX import: recurrent B must be a constant "
                             "initializer when given")
        if len(ins) > 4 and ins[4]:
            raise MXNetError("ONNX import: recurrent sequence_lens is "
                             "unsupported (the backend runs full length "
                             "T — importing would silently change padded-"
                             "batch numerics)")
        if op == "LSTM" and len(ins) > 7 and ins[7]:
            raise MXNetError("ONNX import: LSTM peephole weights (P) "
                             "unsupported")
        if attrs.get("clip") is not None:
            raise MXNetError("ONNX import: recurrent cell clip "
                             "unsupported")
        direction = attrs.get("direction", "forward")
        if isinstance(direction, bytes):
            direction = direction.decode()
        if direction == "reverse":
            raise MXNetError("ONNX import: direction=reverse unsupported")
        bi = direction == "bidirectional"
        dirs = W.shape[0]
        acts = tuple(a.lower() if isinstance(a, str) else a.decode().lower()
                     for a in attrs.get("activations", ()))
        if op == "RNN":
            if acts and len(set(acts)) > 1:
                raise MXNetError(f"ONNX import: per-direction RNN "
                                 f"activations {acts} unsupported "
                                 "(uniform only)")
            a0 = acts[0] if acts else "tanh"
            if a0 == "tanh":
                mode = "rnn_tanh"
            elif a0 == "relu":
                mode = "rnn_relu"
            else:
                raise MXNetError(f"ONNX import: RNN activation {a0!r} "
                                 "unsupported")
        else:
            mode = op.lower()
            default = (("sigmoid", "tanh", "tanh") if mode == "lstm"
                       else ("sigmoid", "tanh")) * dirs
            if acts and acts != default:
                raise MXNetError(f"ONNX import: {op} custom activations "
                                 f"{acts} unsupported")
        if mode == "gru" and int(attrs.get("linear_before_reset", 0)) != 1:
            raise MXNetError(
                "ONNX import: GRU linear_before_reset=0 applies the reset "
                "gate before the hidden projection — different recurrence "
                "than this backend computes (=1 supported)")
        perm = _RNN_GATE_PERM[mode]
        inv = [perm.index(i) for i in range(len(perm))]
        ws, bs = [], []
        for d in range(dirs):
            ws.append(_rnn_gate_reorder(W[d], inv, h).astype("float32"))
            ws.append(_rnn_gate_reorder(R[d], inv, h).astype("float32"))
            if B is not None:
                half = B[d][:g * h], B[d][g * h:2 * g * h]
                bs.append(_rnn_gate_reorder(half[0], inv, h)
                          .astype("float32"))
                bs.append(_rnn_gate_reorder(half[1], inv, h)
                          .astype("float32"))
            else:
                bs.append(onp.zeros(g * h, "float32"))
                bs.append(onp.zeros(g * h, "float32"))
        packed = onp.concatenate([a.ravel() for a in ws + bs])
        pname = f"{name}_parameters"
        while pname in consts:  # anonymous nodes could collide
            pname += "_"
        consts[pname] = packed
        initial_h = ins[5] if len(ins) > 5 and ins[5] else None
        initial_c = ins[6] if len(ins) > 6 and ins[6] else None
        if initial_c and not initial_h:
            raise MXNetError("ONNX import: LSTM initial_c without "
                             "initial_h unsupported")
        sym_inputs = [ins[0], pname]
        if initial_h:
            sym_inputs.append(initial_h)
        if initial_c:
            sym_inputs.append(initial_c)
        a = {"state_size": h, "mode": mode, "num_layers": 1,
             "bidirectional": bi, "onnx_outputs": True}
        group = object()
        result = {}
        for i, o in enumerate(outs):
            if not o:
                continue
            node = Symbol("RNN", name, [sym_in(n) for n in sym_inputs],
                          dict(a), out_index=i)
            node._group_key = group
            result[o] = node
        return result
    raise MXNetError(f"ONNX import: unsupported op {op!r} (node {name!r})")


def get_model_metadata(model_file: str):
    """Reference onnx2mx.get_model_metadata: input/output names + shapes."""
    with open(model_file, "rb") as f:
        model = P.parse_message(f.read())
    g = P.parse_message(model[7][0][1])

    def vis(num):
        out = []
        for w, vi in g.get(num, []):
            f = P.parse_message(vi)
            nm = _get_str(f, 1)
            shape = ()
            if 2 in f:
                ty = P.parse_message(f[2][0][1])
                if 1 in ty:
                    tt = P.parse_message(ty[1][0][1])
                    if 2 in tt:
                        sh = P.parse_message(tt[2][0][1])
                        dims = []
                        for w2, d in sh.get(1, []):
                            df = P.parse_message(d)
                            dims.append(_get_int(df, 1, 0))
                        shape = tuple(dims)
            out.append((nm, shape))
        return out

    return {"input_tensor_data": vis(11), "output_tensor_data": vis(12)}
