"""INT8 quantization (reference: python/mxnet/contrib/quantization.py +
src/operator/quantization/ — quantize_v2/dequantize/requantize ops,
min-max ("naive") and KL-entropy calibration, QuantizeGraph pass swapping in
quantized conv/FC).

TPU-native design: symmetric per-tensor int8 (zero-point 0). The MXU
multiplies int8 natively with int32 accumulation — ``lax.dot_general(...,
preferred_element_type=int32)`` is the whole "quantized kernel"; XLA fuses
the dequantize scale into the surrounding graph. ``quantize_net`` replaces
Dense/Conv children with quantized equivalents after range calibration
(the role of the reference's QuantizeGraph pass,
quantize_graph_pass.cc:581).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as onp

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray
from ..ops.registry import invoke_raw

__all__ = ["quantize_v2", "dequantize", "requantize", "quantize_net",
           "QuantizedDense", "QuantizedConv"]


def _sym_scale(mn, mx):
    return jnp.maximum(jnp.maximum(jnp.abs(mn), jnp.abs(mx)), 1e-10) / 127.0


def quantize_v2(data, min_calib_range: Optional[float] = None,
                max_calib_range: Optional[float] = None,
                out_type: str = "int8"):
    """f32 → (int8, min, max) with symmetric scaling (reference
    quantize_v2, src/operator/quantization/quantize_v2.cc)."""
    if out_type != "int8":
        raise MXNetError("only int8 quantization is supported")

    def fn(x):
        mn = jnp.float32(min_calib_range) if min_calib_range is not None \
            else x.min()
        mx_ = jnp.float32(max_calib_range) if max_calib_range is not None \
            else x.max()
        scale = _sym_scale(mn, mx_)
        q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        return q, mn.reshape(1), mx_.reshape(1)

    return invoke_raw("quantize_v2", fn, [data], n_outputs=3)


def dequantize(qdata, min_range, max_range, out_type: str = "float32"):
    """(int8, min, max) → f32 (reference dequantize op)."""
    def fn(q, mn, mx_):
        return q.astype(jnp.float32) * _sym_scale(mn, mx_)
    return invoke_raw("dequantize", fn, [qdata, min_range, max_range])


def requantize(qdata32, min_range, max_range):
    """int32 accumulators → int8 with recomputed range (reference
    requantize op)."""
    def fn(q, mn, mx_):
        real = q.astype(jnp.float32) * _sym_scale(mn, mx_)
        rmn, rmx = real.min(), real.max()
        scale = _sym_scale(rmn, rmx)
        return (jnp.clip(jnp.round(real / scale), -127, 127).astype(jnp.int8),
                rmn.reshape(1), rmx.reshape(1))
    return invoke_raw("requantize", fn, [qdata32, min_range, max_range],
                      n_outputs=3)


class QuantizedDense(HybridBlock):
    """INT8 Dense: int8×int8 → int32 on the MXU, fused dequantize
    (reference quantized_fully_connected.cc)."""

    def __init__(self, dense: nn.Dense, in_min: float, in_max: float,
                 **kwargs):
        super().__init__(**kwargs)
        w = dense.weight.data()._data
        w_scale = float(jnp.maximum(jnp.abs(w).max(), 1e-10) / 127.0)
        self._qw = jnp.clip(jnp.round(w / w_scale), -127,
                            127).astype(jnp.int8)
        self._w_scale = w_scale
        self._in_scale = max(abs(in_min), abs(in_max), 1e-10) / 127.0
        self._bias = None if dense.bias is None \
            else dense.bias.data()._data
        self._units = dense._units
        self._flatten = dense._flatten
        self._act = dense._activation

    def forward(self, x):
        qw, ws, xs, b = self._qw, self._w_scale, self._in_scale, self._bias
        act = self._act

        def fn(xd):
            shape = xd.shape
            if self._flatten and xd.ndim > 2:
                xd = xd.reshape(shape[0], -1)
            qx = jnp.clip(jnp.round(xd / xs), -127, 127).astype(jnp.int8)
            acc = lax.dot_general(qx, qw, (((qx.ndim - 1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (xs * ws)
            if b is not None:
                out = out + b
            if act:
                out = getattr(jax.nn, act)(out)
            return out

        return invoke_raw("quantized_dense", fn, [x])


class QuantizedConv(HybridBlock):
    """INT8 convolution: int8 conv with int32 accumulation (reference
    quantized_conv.cc)."""

    def __init__(self, conv, in_min: float, in_max: float, **kwargs):
        super().__init__(**kwargs)
        w = conv.weight.data()._data
        w_scale = float(jnp.maximum(jnp.abs(w).max(), 1e-10) / 127.0)
        self._qw = jnp.clip(jnp.round(w / w_scale), -127,
                            127).astype(jnp.int8)
        self._w_scale = w_scale
        self._in_scale = max(abs(in_min), abs(in_max), 1e-10) / 127.0
        self._bias = None if conv.bias is None else conv.bias.data()._data
        self._conv = conv

    def forward(self, x):
        from ..ops import nn as K
        c = self._conv
        qw, ws, xs, b = self._qw, self._w_scale, self._in_scale, self._bias

        def fn(xd):
            qx = jnp.clip(jnp.round(xd / xs), -127, 127).astype(jnp.int8)
            ndim = qx.ndim - 2
            sp = "DHW"[3 - ndim:]
            dn = lax.conv_dimension_numbers(
                qx.shape, qw.shape, ("NC" + sp, "OI" + sp, "NC" + sp))
            acc = lax.conv_general_dilated(
                qx, qw, window_strides=c._strides,
                padding=[(p, p) for p in c._padding],
                rhs_dilation=c._dilation, dimension_numbers=dn,
                feature_group_count=c._groups,
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (xs * ws)
            if b is not None:
                out = out + b.reshape((1, -1) + (1,) * ndim)
            if c._activation:
                out = getattr(jax.nn, c._activation)(out)
            return out

        return invoke_raw("quantized_conv", fn, [x])


def _smooth_distribution(p: onp.ndarray, eps: float = 1e-4) -> onp.ndarray:
    """Shift a little mass onto zero bins so KL(p||q) is defined
    (reference calibrate.cc SmoothDistribution)."""
    is_zero = p == 0
    n_zero = int(is_zero.sum())
    n_nonzero = p.size - n_zero
    if n_nonzero == 0:
        return p
    eps1 = eps * n_zero / n_nonzero
    out = p.astype("float64").copy()
    out[is_zero] = eps
    out[~is_zero] -= eps1
    # bins smaller than the borrowed mass would go negative -> log() NaN
    # and the candidate would be silently discarded; floor instead
    return onp.maximum(out, 1e-12)


def _optimal_threshold(arr: onp.ndarray, num_bins: int = 8001,
                       num_quantized_bins: int = 255,
                       max_candidates: int = 512) -> float:
    """KL-entropy threshold search (reference calibrate.cc
    LayerHistogramCollector + GetOptimalThreshold; the TensorRT-style
    algorithm): over candidate clip thresholds, pick the one whose
    255-level quantized distribution has minimum KL divergence from the
    clipped reference distribution. Symmetric int8: the histogram is over
    |x|; bin resolution follows the reference's 8001 so coarsening cost
    at the full range genuinely competes with clipping cost."""
    a = onp.abs(onp.asarray(arr, "float64").ravel())
    amax = float(a.max()) if a.size else 0.0
    if amax <= 0:
        return 1e-8
    hist, edges = onp.histogram(a, bins=num_bins, range=(0.0, amax))
    hist = hist.astype("float64")
    width = edges[1] - edges[0]
    # tail[i] == hist[i:].sum(); tail[num_bins] == 0 (nothing clipped)
    tail = onp.concatenate([onp.cumsum(hist[::-1])[::-1], [0.0]])
    nonzero = hist != 0
    stride = max(1, (num_bins - num_quantized_bins) // max_candidates)
    best_kl, best_th = onp.inf, amax
    for i in range(num_quantized_bins, num_bins + 1, stride):
        p = hist[:i].copy()
        p[-1] += tail[i]  # clipped outlier mass lands on the edge bin
        total = p.sum()
        if total == 0:
            continue
        # quantize the i reference bins down to num_quantized_bins levels,
        # then expand each level's mass evenly over its NONZERO source
        # bins (segment sums via reduceat)
        bounds = onp.round(onp.arange(num_quantized_bins + 1)
                           * (i / num_quantized_bins)).astype("int64")
        seg_sum = onp.add.reduceat(hist[:i], bounds[:-1])
        seg_cnt = onp.add.reduceat(nonzero[:i].astype("float64"),
                                   bounds[:-1])
        level = onp.where(seg_cnt > 0, seg_sum / onp.maximum(seg_cnt, 1),
                          0.0)
        q = onp.repeat(level, onp.diff(bounds))
        q[~nonzero[:i]] = 0.0
        qsum = q.sum()
        if qsum == 0:
            continue
        ps = _smooth_distribution(p / total)
        qs = _smooth_distribution(q / qsum)
        kl = float(onp.sum(ps * onp.log(ps / qs)))
        if kl < best_kl:
            best_kl = kl
            best_th = (i + 0.5) * width
    return best_th


def _collect_ranges(net, calib_data, max_batches: int,
                    mode: str, percentile: float,
                    max_samples_per_layer: int = 1 << 21
                    ) -> Dict[int, tuple]:
    """Run calibration batches, recording per-layer input statistics via
    forward hooks (the reference's calibration pass, calibrate.cc).
    naive/percentile fold batches into running ranges; entropy keeps a
    bounded activation sample per layer — an equal per-batch budget of
    max_samples_per_layer/max_batches random elements, so every
    calibration batch contributes uniformly (ordered calibration data
    cannot skew the histogram toward early batches) — and runs the KL
    threshold search at the end."""
    ranges: Dict[int, List] = {}
    samples: Dict[int, List[onp.ndarray]] = {}
    hooks = []
    rng = onp.random.RandomState(0)
    per_batch_budget = max(1, max_samples_per_layer // max(1, max_batches))

    def make_hook(key):
        def hook(block, inputs):
            x = inputs[0]
            arr = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if mode == "entropy":
                flat = arr.ravel()
                if flat.size > per_batch_budget:
                    flat = flat[rng.randint(0, flat.size,
                                            size=per_batch_budget)]
                samples.setdefault(key, []).append(
                    flat.astype("float32", copy=True))
                return
            if mode == "percentile":
                lo = float(onp.percentile(arr, 100 - percentile))
                hi = float(onp.percentile(arr, percentile))
            else:  # naive min/max
                lo, hi = float(arr.min()), float(arr.max())
            st = ranges.setdefault(key, [onp.inf, -onp.inf])
            st[0] = min(st[0], lo)
            st[1] = max(st[1], hi)
        return hook

    for blk in _quantizable_blocks(net):
        hooks.append(blk.register_forward_pre_hook(make_hook(id(blk))))
    n = 0
    for batch in calib_data:
        x = batch[0] if isinstance(batch, (list, tuple)) else batch
        net(x)
        n += 1
        if n >= max_batches:
            break
    for h in hooks:
        h.detach()
    if mode == "entropy":
        for key, chunks in samples.items():
            th = _optimal_threshold(onp.concatenate(chunks))
            ranges[key] = [-th, th]
    return {k: tuple(v) for k, v in ranges.items()}


def _quantizable_blocks(net):
    out = []
    stack = [net]
    while stack:
        b = stack.pop()
        if isinstance(b, nn.Dense) or type(b).__name__.startswith("Conv"):
            out.append(b)
        stack.extend(getattr(b, "_children", {}).values())
    return out


def quantize_net(net, calib_data, calib_mode: str = "naive",
                 num_calib_batches: int = 10, percentile: float = 99.99,
                 exclude_first: bool = False):
    """Calibrate + swap Dense/Conv children for INT8 versions, in place
    (reference quantize_net, contrib/quantization.py)."""
    if calib_mode not in ("naive", "percentile", "entropy"):
        raise MXNetError("calib_mode must be 'naive', 'percentile' or "
                         "'entropy'")
    ranges = _collect_ranges(net, calib_data, num_calib_batches,
                             calib_mode, percentile)

    def swap(parent):
        for name, child in list(parent._children.items()):
            key = id(child)
            if key in ranges:
                lo, hi = ranges[key]
                if isinstance(child, nn.Dense):
                    q = QuantizedDense(child, lo, hi)
                elif type(child).__name__ in ("Conv1D", "Conv2D", "Conv3D"):
                    q = QuantizedConv(child, lo, hi)
                else:
                    continue
                parent._children[name] = q
                if getattr(parent, name, None) is child:
                    object.__setattr__(parent, name, q)
            else:
                swap(child)

    swap(net)
    return net
