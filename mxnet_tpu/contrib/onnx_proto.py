"""Minimal ONNX protobuf wire-format writer/reader (no deps).

The environment ships no ``onnx``/``protobuf`` package, so serialization is
implemented directly against the protobuf wire format (varint + tagged
fields) using the stable field numbers of ``onnx.proto3``. The subset
covers what export/import needs: ModelProto, GraphProto, NodeProto,
AttributeProto, TensorProto, ValueInfoProto, TypeProto, TensorShapeProto,
OperatorSetIdProto. Files written here are valid ONNX models loadable by
the official ``onnx`` package / onnxruntime (field numbers and wire types
follow the spec verbatim).

Reference analog: python/mxnet/contrib/onnx/mx2onnx/_export_onnx.py builds
the same messages through the onnx python API.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

__all__ = ["MessageWriter", "parse_message", "TensorDataType",
           "AttrType", "ONNX_IR_VERSION", "ONNX_OPSET"]

ONNX_IR_VERSION = 8
ONNX_OPSET = 13


class TensorDataType:
    FLOAT = 1
    UINT8 = 2
    INT8 = 3
    INT32 = 6
    INT64 = 7
    BOOL = 9
    FLOAT16 = 10
    DOUBLE = 11
    BFLOAT16 = 16


class AttrType:
    FLOAT = 1
    INT = 2
    STRING = 3
    TENSOR = 4
    FLOATS = 6
    INTS = 7
    STRINGS = 8


def _varint(value: int) -> bytes:
    if value < 0:
        value &= (1 << 64) - 1  # two's complement, 64-bit
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class MessageWriter:
    """Builds one protobuf message; nested messages via sub-writers."""

    def __init__(self):
        self._buf = bytearray()

    # wire type 0
    def write_int(self, field: int, value: int):
        self._buf += _varint(field << 3 | 0)
        self._buf += _varint(int(value))

    # wire type 5 (float fields like AttributeProto.f)
    def write_float(self, field: int, value: float):
        self._buf += _varint(field << 3 | 5)
        self._buf += struct.pack("<f", float(value))

    # wire type 2
    def write_bytes(self, field: int, data: bytes):
        self._buf += _varint(field << 3 | 2)
        self._buf += _varint(len(data))
        self._buf += data

    def write_string(self, field: int, s: str):
        self.write_bytes(field, s.encode("utf-8"))

    def write_message(self, field: int, msg: "MessageWriter"):
        self.write_bytes(field, bytes(msg._buf))

    def write_packed_ints(self, field: int, values):
        payload = b"".join(_varint(int(v)) for v in values)
        self.write_bytes(field, payload)

    def write_packed_floats(self, field: int, values):
        self.write_bytes(field, struct.pack(f"<{len(values)}f",
                                            *[float(v) for v in values]))

    def tobytes(self) -> bytes:
        return bytes(self._buf)


# ---------------------------------------------------------------------------
# Generic reader
# ---------------------------------------------------------------------------

def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7


def parse_message(data: bytes) -> Dict[int, List[Tuple[int, Any]]]:
    """Parse one message into {field_number: [(wire_type, value), ...]}.
    wire 0 -> int, wire 2 -> bytes (caller decides: submessage / string /
    packed), wire 5 -> raw 4 bytes, wire 1 -> raw 8 bytes."""
    fields: Dict[int, List[Tuple[int, Any]]] = {}
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = _read_varint(data, pos)
        elif wire == 2:
            ln, pos = _read_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = data[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = data[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append((wire, val))
    return fields


def unpack_ints(blob_or_entries) -> List[int]:
    """Decode a packed-varint payload or repeated unpacked entries."""
    out: List[int] = []
    for wire, val in blob_or_entries:
        if wire == 0:
            out.append(val)
        else:
            pos = 0
            while pos < len(val):
                v, pos = _read_varint(val, pos)
                out.append(v)
    return out


def signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v
