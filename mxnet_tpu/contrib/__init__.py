"""mx.contrib (reference: python/mxnet/contrib/ — amp, quantization, onnx,
tensorboard). AMP lives at mxnet_tpu.amp; re-exported here for parity."""
from .. import amp  # noqa: F401  (reference path: mx.contrib.amp)
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import tensorboard  # noqa: F401
from . import text  # noqa: F401
