"""TensorBoard logging bridge (reference: python/mxnet/contrib/
tensorboard.py — LogMetricsCallback writing EvalMetric values through a
SummaryWriter).

Works with any writer exposing ``add_scalar(tag, value, step)`` (e.g.
``torch.utils.tensorboard.SummaryWriter``, tensorboardX, or jax's
TensorBoard profile dir via mx.profiler tensorboard_dir for device traces).
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["LogMetricsCallback"]


class LogMetricsCallback:
    """Per-batch/epoch callback pushing metric values to a summary writer
    (reference tensorboard.py LogMetricsCallback)."""

    def __init__(self, logging_dir: str = None, prefix: str = None,
                 summary_writer=None):
        self.prefix = prefix
        self.step = 0
        if summary_writer is not None:
            self.summary_writer = summary_writer
            return
        try:
            from torch.utils.tensorboard import SummaryWriter
        except ImportError as e:
            raise MXNetError(
                "no SummaryWriter available; pass summary_writer= or "
                "install a tensorboard writer") from e
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        """Accepts an object with ``eval_metric`` (reference
        BatchEndParam) or an EvalMetric directly."""
        metric = getattr(param, "eval_metric", param)
        if metric is None:
            return
        self.step += 1
        for name, value in metric.get_name_value():
            if self.prefix:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)
