"""Logging utilities with the framework's glog-style line format.

Reference analog: python/mxnet/log.py — ``get_logger`` returns a logger
whose lines look like ``I0505 00:29:47 3525 file:func:1] message``
(level letter, date, PID, location), colorized on TTYs.
"""
import logging
import sys
import warnings

__all__ = ["get_logger", "getLogger", "CRITICAL", "ERROR", "WARNING",
           "INFO", "DEBUG", "NOTSET"]

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET

_LEVEL_CHAR = {logging.CRITICAL: "C", logging.ERROR: "E",
               logging.WARNING: "W", logging.INFO: "I",
               logging.DEBUG: "D"}


class _Formatter(logging.Formatter):
    """glog-style formatter (reference log.py:34)."""

    def __init__(self, colored=True):
        super().__init__(datefmt="%m%d %H:%M:%S")
        self._colored = colored

    def _color(self, level):
        if level >= logging.WARNING:
            return "\x1b[31m"
        if level >= logging.INFO:
            return "\x1b[32m"
        return "\x1b[34m"

    def format(self, record):
        label = _LEVEL_CHAR.get(record.levelno, "U")
        loc = "%(asctime)s %(process)d %(pathname)s:%(funcName)s:%(lineno)d"
        if self._colored:
            fmt = self._color(record.levelno) + label + loc + "]\x1b[0m"
        else:
            fmt = label + loc + "]"
        self._style._fmt = fmt + " %(message)s"
        return super().format(record)


def get_logger(name=None, filename=None, filemode=None, level=WARNING):
    """A logger with the framework formatter installed once per name
    (reference log.py:84). ``filename`` attaches a FileHandler
    (mode ``filemode`` or 'a'); otherwise a stderr StreamHandler."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", None):
        logger._init_done = True
        if filename:
            hdlr = logging.FileHandler(filename, filemode or "a")
            hdlr.setFormatter(_Formatter(colored=False))
        else:
            hdlr = logging.StreamHandler(sys.stderr)
            hdlr.setFormatter(_Formatter(
                colored=getattr(sys.stderr, "isatty", lambda: False)()))
        logger.addHandler(hdlr)
        logger.setLevel(level)
    return logger


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Deprecated alias of :func:`get_logger` (reference log.py:74)."""
    warnings.warn("getLogger is deprecated, use get_logger instead.",
                  DeprecationWarning, stacklevel=2)
    return get_logger(name, filename, filemode, level)
