"""Profiler: per-op event recording + Chrome-trace dump + XProf bridge.

Reference analog: src/profiler/ (Profiler singleton with mode bitmask,
per-device stat queues, Chrome tracing JSON via DumpProfile — profiler.h:251,
:299) and python/mxnet/profiler.py (set_config/set_state/dump/dumps).

TPU-native split: XLA owns device-side timing, so device kernels are
profiled with the JAX/XProf tracer (``tensorboard_dir`` option → TensorBoard
'Profile' tab). What this module records natively is the *host-side* op
stream — every imperative invoke, with dispatch wall time — dumped in Chrome
tracing format (chrome://tracing / Perfetto), plus aggregate tables like the
reference's ``dumps(); aggregate_stats=True``.

Async attribution (the reference's "dispatch vs run" distinction, made
explicit in the events rather than a docstring caveat): under the default
async engine an op event's duration is host DISPATCH time — the op
returns before the device ran it — so every per-op event carries
``args.phase = "dispatch"`` (``"sync"`` under MXNET_ENGINE_TYPE=
NaiveEngine, where ops block until complete and the duration is true
wall time). The moments work actually COMPLETES appear on the same
timeline as the step-phase spans the telemetry subsystem records
(``cat: "step"``: window residency push→retire and the blocking retire
wait, stamped from ``engine.DispatchWindow``'s retire timestamps, plus
batch_fetch/h2d_wait/dispatch/checkpoint) — see docs/OBSERVABILITY.md.
So a Chrome trace of a pipelined run is honest: dispatch-time op slices,
retire-time step boundaries, one merged stream.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

from .base import MXNetError
from .ops import registry as _registry

__all__ = ["set_config", "set_state", "state", "dump", "dumps", "pause",
           "resume", "scope", "Profiler", "dump_memory", "memory_summary",
           "Domain", "Task", "Frame", "Event", "Counter", "Marker",
           "profiler_set_config", "profiler_set_state", "dump_profile",
           "set_kvstore_handle"]


class Profiler:
    """Process-global profiler (reference Profiler singleton)."""

    _instance = None
    # bare on purpose: profiler sits below the audit layer; leaf lock
    _lock = threading.Lock()  # mx-lint: allow=MXA009

    def __init__(self):
        self.filename = "profile.json"
        self.aggregate_stats = False
        self.tensorboard_dir: Optional[str] = None
        self.running = False
        self.paused = False
        self._events = []
        # bare on purpose: profiler sits below the audit layer; leaf lock
        self._ev_lock = threading.Lock()  # mx-lint: allow=MXA009
        self._scope = ""
        self._hook_installed = False
        self._tb_active = False

    @classmethod
    def get(cls) -> "Profiler":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = Profiler()
        return cls._instance

    # -- recording ---------------------------------------------------------

    def record(self, name: str, t_start: float, t_end: float,
               cat: str = "operator", args: Optional[dict] = None):
        """Append one complete ('X') slice; ``args`` lands in the Chrome
        event's args field — per-op events carry the dispatch/sync phase,
        step spans carry {step, phase} (docs/OBSERVABILITY.md)."""
        if not self.running or self.paused:
            return
        ev = {
            "name": (self._scope + name) if self._scope else name,
            "cat": cat, "ph": "X",
            "ts": t_start * 1e6, "dur": (t_end - t_start) * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident() % 100000,
        }
        if args:
            ev["args"] = args
        with self._ev_lock:
            self._events.append(ev)

    @staticmethod
    def _op_phase() -> str:
        """Honest attribution for per-op durations: host 'dispatch' time
        under the async engine (the op returned before the device ran
        it), true 'sync' wall time under NaiveEngine."""
        from .engine import get as _engine_get
        return "sync" if _engine_get().is_naive else "dispatch"

    def _invoke_wrapper(self, name, fn):
        prof = self

        def wrapped(*args, **kwargs):
            if not prof.running or prof.paused:
                return fn(*args, **kwargs)
            import jax
            t0 = time.perf_counter()
            try:
                # host-side XProf event per framework op; device kernels are
                # attributed via the named_scope in the invoke funnel
                with jax.profiler.TraceAnnotation(name):
                    return fn(*args, **kwargs)
            finally:
                prof.record(name, t0, time.perf_counter(),
                            args={"phase": prof._op_phase()})
        return wrapped

    def _install_hook(self):
        if not self._hook_installed:
            _registry.add_invoke_wrapper(self._invoke_wrapper)
            self._hook_installed = True

    # -- state -------------------------------------------------------------

    def set_config(self, **kwargs):
        known = {"filename", "aggregate_stats", "tensorboard_dir",
                 # reference mode flags, accepted for parity (host stream
                 # records all imperative ops; XLA owns device timing):
                 "profile_all", "profile_symbolic", "profile_imperative",
                 "profile_memory", "profile_api", "continuous_dump"}
        for k, v in kwargs.items():
            if k not in known:
                raise MXNetError(f"unknown profiler option {k!r}")
            if k in ("filename", "aggregate_stats", "tensorboard_dir"):
                setattr(self, k, v)

    def set_state(self, state: str):
        if state not in ("run", "stop"):
            raise MXNetError("profiler state must be 'run' or 'stop'")
        if state == "run":
            self._install_hook()
            self.running = True
            if self.tensorboard_dir and not self._tb_active:
                import jax
                jax.profiler.start_trace(self.tensorboard_dir)
                self._tb_active = True
        else:
            self.running = False
            if self._tb_active:
                import jax
                jax.profiler.stop_trace()
                self._tb_active = False

    def dump(self, finished: bool = True):
        """Write accumulated events as Chrome tracing JSON."""
        with self._ev_lock:
            events = list(self._events)
            if finished:
                self._events.clear()
        with open(self.filename, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)

    def dumps(self, reset: bool = False) -> str:
        """Aggregate per-op table (reference aggregate_stats output)."""
        with self._ev_lock:
            events = list(self._events)
            if reset:
                self._events.clear()
        agg = {}
        for e in events:
            st = agg.setdefault(e["name"], [0, 0.0, float("inf"), 0.0])
            st[0] += 1
            st[1] += e["dur"]
            st[2] = min(st[2], e["dur"])
            st[3] = max(st[3], e["dur"])
        lines = [f"{'Name':<40s}{'Calls':>8s}{'Total(us)':>14s}"
                 f"{'Min(us)':>12s}{'Max(us)':>12s}{'Avg(us)':>12s}"]
        for name in sorted(agg, key=lambda n: -agg[n][1]):
            c, tot, mn, mx = agg[name]
            lines.append(f"{name:<40s}{c:>8d}{tot:>14.1f}{mn:>12.1f}"
                         f"{mx:>12.1f}{tot / c:>12.1f}")
        return "\n".join(lines)


def set_config(**kwargs):
    Profiler.get().set_config(**kwargs)


def set_state(state: str = "stop"):
    Profiler.get().set_state(state)


def state() -> str:
    return "run" if Profiler.get().running else "stop"


def dump(finished: bool = True):
    Profiler.get().dump(finished)


def dumps(reset: bool = False) -> str:
    return Profiler.get().dumps(reset)


def dump_memory(path: str = "memory.pprof") -> str:
    """Write a device-memory profile (reference storage profiler,
    src/profiler/storage_profiler.cc + pooled_storage_manager.h:207 hook;
    here the allocator is XLA's, so the profile is jax's pprof-format
    device memory snapshot — inspect with `pprof` or upload to
    TensorBoard's memory viewer)."""
    import jax
    ver = str(getattr(jax.devices()[0].client, "platform_version", ""))
    if "axon" in ver:
        # the tunneled axon PjRt plugin aborts the PROCESS (uncatchable
        # C++ LOG(FATAL): PJRT_Executable_SizeOfGeneratedCodeInBytes not
        # implemented) inside HeapProfile — refuse instead of crashing
        raise MXNetError(
            "device memory profiling is not supported on the tunneled "
            "axon PjRt plugin; use memory_summary() or run on direct "
            "TPU/CPU runtimes")
    blob = jax.profiler.device_memory_profile()
    with open(path, "wb") as f:
        f.write(blob)
    return path


def memory_summary() -> dict:
    """Per-device memory totals (the aggregate the reference printed
    from its storage profiler), routed through the telemetry catalog:
    each read refreshes the ``mx_mem_device_bytes_in_use`` /
    ``_peak_bytes`` / ``_limit_bytes`` gauges instead of living in an
    ad-hoc dict only this call ever saw.

    Backends with allocator counters (TPU/GPU BFC) report
    ``{bytes_in_use, peak_bytes_in_use, bytes_limit, source:
    "allocator"}``. XLA:CPU exposes NO allocator stats — the documented
    fallback prices every live ``jax.Array`` shard on its device
    (``source: "live_arrays"``; peak/limit stay None because live
    accounting has no high-water mark) rather than returning the silent
    Nones this function used to."""
    from .telemetry.memory import device_memory_stats
    return device_memory_stats()


def pause():
    Profiler.get().paused = True


def resume():
    Profiler.get().paused = False


@contextlib.contextmanager
def scope(name: str):
    """Prefix recorded op names (reference __profiler_scope__ attr,
    c_api_ndarray.cc:104); also emits a JAX trace annotation so the scope
    shows up in XProf device traces."""
    prof = Profiler.get()
    old = prof._scope
    prof._scope = old + name.rstrip(":") + ":"
    try:
        import jax
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        prof._scope = old


# ---------------------------------------------------------------------------
# instrumentation object API (reference profiler.py:228-520: Domain,
# Task, Frame, Event, Counter, Marker over the MXProfile* C API). Here
# each object writes straight into the profiler's Chrome-trace event
# stream: durations as 'X' slices categorized by domain, counters as
# 'C' samples, markers as 'i' instants — visible in chrome://tracing
# next to the per-op events.
# ---------------------------------------------------------------------------

class Domain:
    """Category grouping for instrumentation objects (reference :228)."""

    def __init__(self, name):
        self.name = name

    def __str__(self):
        return self.name

    def new_task(self, name):
        return Task(self, name)

    def new_frame(self, name):
        return Frame(self, name)

    def new_counter(self, name, value=None):
        return Counter(self, name, value)

    def new_marker(self, name):
        return Marker(self, name)


class _DurationObject:
    """start()/stop() pair recording one Chrome-trace slice."""

    _cat_suffix = ""

    def __init__(self, domain, name):
        self.name = name
        self._domain = domain
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        if self._t0 is None:
            raise MXNetError(f"{type(self).__name__} {self.name!r}: "
                             "stop() before start()")
        Profiler.get().record(self.name, self._t0, time.perf_counter(),
                              cat=str(self._domain) + self._cat_suffix)
        self._t0 = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def __str__(self):
        return self.name


class Task(_DurationObject):
    """Accumulated logical unit of work (reference :287)."""


class Frame(_DurationObject):
    """Per-pass discrete duration, e.g. one video frame
    (reference :329)."""

    _cat_suffix = ":frame"


class Event(_DurationObject):
    """Per-thread demarcated event without a domain (reference :371)."""

    def __init__(self, name):
        super().__init__(_EVENT_DOMAIN, name)


_EVENT_DOMAIN = Domain("event")


class Counter:
    """Numeric counter sampled into the trace (reference :420):
    set_value/increment/decrement emit Chrome 'C' events."""

    def __init__(self, domain, name, value=None):
        self.name = name
        self._domain = domain
        self._value = 0
        if value is not None:
            self.set_value(value)

    def _emit(self):
        prof = Profiler.get()
        if not prof.running or prof.paused:
            return
        with prof._ev_lock:
            prof._events.append({
                "name": self.name, "cat": str(self._domain), "ph": "C",
                "ts": time.perf_counter() * 1e6, "pid": os.getpid(),
                "tid": threading.get_ident() % 100000,
                "args": {"value": self._value},
            })

    def set_value(self, value):
        self._value = value
        self._emit()

    def increment(self, delta=1):
        self._value += delta
        self._emit()

    def decrement(self, delta=1):
        self._value -= delta
        self._emit()

    def __iadd__(self, v):
        self.increment(v)
        return self

    def __isub__(self, v):
        self.decrement(v)
        return self

    def __str__(self):
        return self.name


class Marker:
    """Instant marker (reference :470): mark(scope) emits a Chrome 'i'
    event with the given scope ('process'|'thread'|'global')."""

    _SCOPES = {"process": "p", "thread": "t", "global": "g"}

    def __init__(self, domain, name):
        self.name = name
        self._domain = domain

    def mark(self, scope="process"):
        prof = Profiler.get()
        if not prof.running or prof.paused:
            return
        with prof._ev_lock:
            prof._events.append({
                "name": self.name, "cat": str(self._domain), "ph": "i",
                "ts": time.perf_counter() * 1e6, "pid": os.getpid(),
                "tid": threading.get_ident() % 100000,
                "s": self._SCOPES.get(scope, "p"),
            })


# deprecated 1.x aliases (reference profiler.py keeps them with warnings)
def profiler_set_config(mode="symbolic", filename="profile.json"):
    import warnings
    warnings.warn("profiler.profiler_set_config is deprecated; use "
                  "profiler.set_config", DeprecationWarning, stacklevel=2)
    set_config(filename=filename)


def profiler_set_state(state="stop"):
    import warnings
    warnings.warn("profiler.profiler_set_state is deprecated; use "
                  "profiler.set_state", DeprecationWarning, stacklevel=2)
    set_state(state)


def dump_profile():
    import warnings
    warnings.warn("profiler.dump_profile is deprecated; use "
                  "profiler.dump", DeprecationWarning, stacklevel=2)
    dump(True)


def set_kvstore_handle(handle=None):
    """No-op shim (reference wires the kvstore's server-side profiler
    over the C API; kvstore here is in-process, so its ops already land
    in this profiler's stream)."""
    return None
