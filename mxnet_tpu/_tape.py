"""Autograd tape: record-mode flags, tape nodes, backward engine.

TPU-native re-design of the reference's imperative autograd
(reference: src/imperative/imperative.cc:377-630 ``Imperative::Backward``,
include/mxnet/imperative.h:54-92 ``AGInfo``). The reference attaches an nnvm
node to every recorded array and later runs the ``MXGradient`` graph pass;
here each recorded op captures a ``jax.vjp`` closure, and ``backward`` walks
the tape in reverse record order, so XLA differentiates each op while the
tape supplies the cross-op chain rule.

Higher-order gradients (``create_graph=True``): instead of calling the saved
vjp closure, the backward of each node is re-invoked *through the tape* as a
fresh differentiable op (``jax.vjp`` of the stored primal fn), so the backward
computation is itself recorded — the analog of the reference re-recording
backward nodes when ``is_recording`` (imperative.cc:457 + RecordOp).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError

__all__ = [
    "is_recording", "is_training", "set_recording", "set_training",
    "is_taping_suspended", "set_taping_suspended", "suspend_taping",
    "TapeNode", "record_op", "backward", "grad", "mark_variables",
]


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        # Hard override used by whole-graph functionalization (cached ops,
        # Trainer.compile_step): while suspended, is_recording() reports
        # False even if user code inside the traced region enters
        # autograd.record() — tape nodes must never be attached to tracers.
        self.suspended = False


_state = _State()
_node_counter = [0]


def is_recording() -> bool:
    return _state.recording and not _state.suspended


def is_training() -> bool:
    return _state.training


def set_recording(flag: bool) -> bool:
    old, _state.recording = _state.recording, flag
    return old


def set_training(flag: bool) -> bool:
    old, _state.training = _state.training, flag
    return old


def is_taping_suspended() -> bool:
    return _state.suspended


def set_taping_suspended(flag: bool) -> bool:
    old, _state.suspended = _state.suspended, flag
    return old


class suspend_taping:
    """Context manager: force is_recording() False for the duration, even
    across user calls to set_recording(True)/autograd.record() inside the
    scope. The functionalized-trace analog of the reference's
    Imperative::DCInfo scope (deferred compute forbids nested recording)."""

    def __enter__(self):
        self._prev = set_taping_suspended(True)
        return self

    def __exit__(self, *exc):
        set_taping_suspended(self._prev)
        return False


class TapeNode:
    """One recorded op: inputs (NDArray handles), primal fn, vjp closure.

    ``fn`` is a pure function jax arrays -> (tuple of) jax arrays with all
    non-tensor attrs already bound. ``vjp_fn`` is the fast-path closure from
    ``jax.vjp``; ``fn`` is retained for create_graph re-derivation.
    """

    __slots__ = ("id", "name", "inputs", "fn", "vjp_fn", "out_avals",
                 "n_outputs", "input_entries", "out_is_tuple")

    def __init__(self, name, inputs, fn, vjp_fn, out_avals, out_is_tuple=False):
        _node_counter[0] += 1
        self.id = _node_counter[0]
        self.name = name
        self.inputs = list(inputs)          # NDArray handles (strong refs = saved tensors)
        # Snapshot each input's tape entry NOW: later in-place mutation of an
        # input handle must not rewire this node's ancestry (write-after-read
        # ordering the reference engine enforces via versioned vars).
        self.input_entries = [getattr(x, "_tape_entry", None) for x in inputs]
        self.fn = fn
        self.vjp_fn = vjp_fn
        self.out_avals = out_avals          # list of jax.ShapeDtypeStruct
        self.n_outputs = len(out_avals)
        self.out_is_tuple = out_is_tuple    # fn returned a tuple (vjp wants one)


# Optional post-record hook on the concrete primal outputs — the tape-side
# attachment point for the inspector's NaN guard: under record the kernel
# runs inside jax.vjp tracing (invoke wrappers only see Tracers), while the
# primal values surfacing here are concrete (reference check_value through
# the engine's on-complete hook).
_output_check: Optional[Callable] = None


def set_output_check(fn: Optional[Callable]) -> Optional[Callable]:
    global _output_check
    old, _output_check = _output_check, fn
    return old


def record_op(name: str, fn: Callable, inputs: Sequence[Any],
              out_arrays: Sequence[Any]) -> None:
    """Attach a TapeNode to ``out_arrays``. ``out_arrays`` are the NDArray
    handles wrapping the outputs that ``fn(*input_datas)`` produced via vjp.
    Called by the op-invoke layer (ops/registry.py) when recording."""
    in_datas = [x._data for x in inputs]
    outs, vjp_fn = jax.vjp(fn, *in_datas)
    if _output_check is not None:
        _output_check(name, outs if isinstance(outs, (tuple, list))
                      else (outs,))
    out_is_tuple = isinstance(outs, (tuple, list))
    if not out_is_tuple:
        outs = (outs,)
    avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]
    node = TapeNode(name, inputs, fn, vjp_fn, avals, out_is_tuple)
    for i, arr in enumerate(out_arrays):
        arr._data = outs[i]
        arr._tape_entry = (node, i)
    return node


def _zeros_like_aval(aval):
    return jnp.zeros(aval.shape, aval.dtype)


def _collect_graph(heads) -> Tuple[List[TapeNode], Dict[int, TapeNode]]:
    """DFS from head arrays over snapshotted input entries; return reachable
    nodes sorted by record id (valid topological order)."""
    seen: Dict[int, TapeNode] = {}
    stack = [h._tape_entry[0] for h in heads
             if getattr(h, "_tape_entry", None) is not None]
    while stack:
        node = stack.pop()
        if node.id in seen:
            continue
        seen[node.id] = node
        for ent in node.input_entries:
            if ent is not None and ent[0].id not in seen:
                stack.append(ent[0])
    order = sorted(seen.values(), key=lambda n: n.id)
    return order, seen


def _ct_sum(a, b):
    """Sum two cotangents. Raw jax arrays and NDArray-typed cotangents
    (create_graph handles, row_sparse embedding grads) can meet on a shared
    input; a mixed pair densifies the NDArray side."""
    a_nd, b_nd = hasattr(a, "_data"), hasattr(b, "_data")
    if a_nd and not b_nd:
        return a._data + b
    if b_nd and not a_nd:
        return a + b._data
    return a + b


def _accumulate(store: Dict[Tuple[int, int], Any], key, val):
    if val is None:
        return
    if key in store:
        store[key] = _ct_sum(store[key], val)
    else:
        store[key] = val


def backward(heads, head_grads=None, retain_graph=False, create_graph=False,
             train_mode=True, variables=None):
    """Run reverse-mode through the tape.

    If ``variables`` is None: write into each reachable leaf's ``.grad``
    honoring grad_req write/add (reference Imperative::Backward semantics);
    returns None. Else: return the gradient arrays (jax arrays) w.r.t.
    ``variables`` without touching ``.grad`` (reference MXAutogradBackwardEx
    with var handles → autograd.grad).
    """
    heads = list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    head_grads = list(head_grads)
    if len(head_grads) != len(heads):
        raise MXNetError("heads and head_grads length mismatch")

    for h in heads:
        if getattr(h, "_tape_entry", None) is None and variables is None \
                and getattr(h, "_grad", None) is None:
            raise MXNetError(
                "cannot differentiate a head that is not in the recorded "
                "graph and has no grad attached")

    order, _ = _collect_graph(heads)

    # cotangent store keyed by (node_id, out_index); leaves handled separately
    ct: Dict[Tuple[int, int], Any] = {}
    # seed heads. In create_graph mode the cotangent store holds NDArray
    # handles (so accumulation itself is recorded); otherwise raw jax arrays.
    for h, hg in zip(heads, head_grads):
        ent = getattr(h, "_tape_entry", None)
        if hg is None:
            seed = jnp.ones(h._data.shape, h._data.dtype)
        else:
            seed = hg._data if hasattr(hg, "_data") else jnp.asarray(hg)
        if create_graph:
            from .ndarray.ndarray import NDArray  # lazy
            seed = NDArray(seed)
        if ent is not None:
            _accumulate(ct, (ent[0].id, ent[1]), seed)
        elif variables is None and getattr(h, "_grad", None) is not None:
            _write_leaf_grad(h, seed)

    leaf_grads: Dict[int, Any] = {}   # id(ndarray handle) -> jax array
    var_ids = {id(v): i for i, v in enumerate(variables)} if variables else None

    if create_graph:
        _backward_create_graph(order, ct, leaf_grads, var_ids, variables)
    else:
        prev = set_recording(False)
        prev_t = set_training(train_mode)
        try:
            for node in reversed(order):
                cts = [ct.pop((node.id, i), None) for i in range(node.n_outputs)]
                if all(c is None for c in cts):
                    continue
                if node.vjp_fn is None:
                    raise MXNetError(
                        "cannot run backward: the graph has already been "
                        "freed. Call backward(retain_graph=True) to backward "
                        "through the graph a second time")
                # unwrap NDArray-typed cotangents (row_sparse embedding
                # grads) to raw jax arrays before entering the vjp closure
                cts = [(c._data if hasattr(c, "_data") else c)
                       if c is not None else _zeros_like_aval(a)
                       for c, a in zip(cts, node.out_avals)]
                arg = tuple(cts) if node.out_is_tuple else cts[0]
                in_cts = node.vjp_fn(arg)
                _scatter_input_cts(node, in_cts, ct, leaf_grads, var_ids)
                if not retain_graph:
                    node.vjp_fn = None  # free residuals ASAP
        finally:
            set_recording(prev)
            set_training(prev_t)

    if variables is not None:
        out = []
        for v in variables:
            g = leaf_grads.get(id(v))
            if g is None:
                g = jnp.zeros(v._data.shape, v._data.dtype)
            out.append(g)
        return out

    # write leaf grads honoring grad_req
    for node in order:
        for x in node.inputs:
            gid = id(x)
            if gid in leaf_grads and getattr(x, "_grad", None) is not None:
                _write_leaf_grad(x, leaf_grads.pop(gid))
    return None


def _scatter_input_cts(node, in_cts, ct, leaf_grads, var_ids):
    # zip with snapshotted entries (handle duplicates positionally)
    for pos, g in enumerate(in_cts):
        if g is None:
            continue
        x = node.inputs[pos]
        ent = node.input_entries[pos]
        if var_ids is not None and id(x) in var_ids:
            _accumulate_by_id(leaf_grads, id(x), g)
            continue
        if ent is not None:
            _accumulate(ct, (ent[0].id, ent[1]), g)
        else:
            _accumulate_by_id(leaf_grads, id(x), g)


def _accumulate_by_id(store: Dict[int, Any], key: int, val):
    if key in store:
        store[key] = _ct_sum(store[key], val)
    else:
        store[key] = val


def _write_leaf_grad(x, g):
    """Honor grad_req: 'write' overwrites, 'add' accumulates across backward
    calls, 'null' drops (reference grad_req handling, imperative.cc:490)."""
    req = getattr(x, "_grad_req", "write")
    if req == "null" or x._grad is None:
        return
    from .ndarray.sparse import RowSparseNDArray  # lazy: import cycle
    if isinstance(g, RowSparseNDArray) and req == "write":
        # keep the row_sparse structure on the leaf (reference grad_stype
        # row_sparse, FInferStorageType): the optimizer's lazy path reads
        # (indices, values); any dense consumer reads the dense mirror
        x._grad = g
        x._fresh_grad = True
        return
    if isinstance(x._grad, RowSparseNDArray):
        # dense gradient arriving on a leaf whose previous grad was sparse
        # (e.g. tied weights summed to dense this step): REPLACE the handle —
        # writing _data in place would leave the old (indices, values) aux
        # stale and the lazy optimizer would re-apply last step's rows
        from .ndarray.ndarray import NDArray
        gdata = g._data if hasattr(g, "_data") else g
        base = x._grad._data if req == "add" else None
        gdata = jnp.asarray(gdata, x._grad._data.dtype) \
            .reshape(x._grad._data.shape)
        x._grad = NDArray(gdata if base is None else base + gdata)
        x._fresh_grad = True
        return
    gdata = g._data if hasattr(g, "_data") else g
    gdata = jnp.asarray(gdata, x._grad._data.dtype)
    if gdata.shape != x._grad._data.shape:
        gdata = gdata.reshape(x._grad._data.shape)
    if req == "add":
        x._grad._data = x._grad._data + gdata
    else:
        x._grad._data = gdata
    x._fresh_grad = True


def _backward_create_graph(order, ct, leaf_grads, var_ids, variables):
    """Differentiable backward: each node's grad computation is re-invoked as
    a recorded op so second-order ``backward`` works."""
    from .ops.registry import invoke_raw  # lazy: avoids import cycle

    for node in reversed(order):
        cts = [ct.pop((node.id, i), None) for i in range(node.n_outputs)]
        if all(c is None for c in cts):
            continue
        n_in = len(node.inputs)
        fn = node.fn

        def grad_fn(*args, _fn=fn, _n_in=n_in, _tup=node.out_is_tuple):
            xs, gs = args[:_n_in], args[_n_in:]
            _, vjp_fn = jax.vjp(_fn, *xs)
            arg = tuple(gs) if _tup else gs[0]
            return tuple(vjp_fn(arg))

        ct_handles = []
        from .ndarray.ndarray import NDArray  # lazy
        for c, a in zip(cts, node.out_avals):
            if c is None:
                c = _zeros_like_aval(a)
            ct_handles.append(c if isinstance(c, NDArray) else NDArray(c))
        in_grads = invoke_raw(f"_backward_{node.name}", grad_fn,
                              list(node.inputs) + ct_handles, n_outputs=n_in)
        if not isinstance(in_grads, (list, tuple)):
            in_grads = [in_grads]
        _scatter_input_cts(node, list(in_grads), ct, leaf_grads, var_ids)


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    if retain_graph is None:
        retain_graph = create_graph
    return backward(heads, head_grads, retain_graph, create_graph,
                    train_mode, variables=variables)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Reference autograd.mark_variables (python/mxnet/autograd.py:197):
    associate grads/reqs with arrays, making them tape leaves."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, r in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = r
        v._tape_entry = None
