"""Symbol attribute scoping.

Reference analog: python/mxnet/attribute.py:23 — ``with
mx.AttrScope(ctx_group='stage1'):`` attaches string attributes to every
symbol created inside the scope (used for context grouping, subgraph
marking). Scopes nest by dict-merge, inner keys winning.
"""
import contextvars

__all__ = ["AttrScope", "current"]


class AttrScope:
    """Attribute manager for scoping; all values must be strings
    (they travel through the symbol's serialized attr dict)."""

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be string")
        self._attr = kwargs

    def get(self, attr):
        """Merge the scope's attributes under the user-passed ``attr``
        dict (user keys win). Always returns a fresh dict — the result
        is stored on the symbol, so caller state must not alias in —
        and enforces the strings-only rule on user attrs too."""
        if attr:
            for value in attr.values():
                if not isinstance(value, str):
                    raise ValueError("Attributes need to be string")
        ret = self._attr.copy()
        if attr:
            ret.update(attr)
        return ret

    def __enter__(self):
        # merge for the scope's duration only; restored on exit so a
        # reused AttrScope instance never leaks an old enclosing scope
        self._saved_attr = self._attr
        attr = _current.get()._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        self._old_scope = _current.get()
        _current.set(self)
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope
        _current.set(self._old_scope)
        self._attr = self._saved_attr


_current = contextvars.ContextVar("attrscope", default=AttrScope())


def current():
    """The active attribute scope."""
    return _current.get()
