"""Foundation operator set for the legacy ``mx.nd`` namespace.

Reference analog: src/operator/tensor/ (elemwise/broadcast/reduce/dot/
indexing/ordering/matrix-manip, ~38k LoC of CPU/CUDA kernels) and the
generated Python wrappers in python/mxnet/ndarray/. Every op here is a thin
pure-JAX function: XLA emits the TPU kernel and handles fusion (the job the
reference's ``Kernel<OP,xpu>::Launch`` + pointwise-fusion JIT did by hand).
"""
from __future__ import annotations

import functools
from builtins import slice as builtins_slice
from typing import Optional

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import MXNetError, jx_dtype
from ..ops.registry import invoke_raw, register
from .ndarray import NDArray, _norm_axis

__all__: list = []  # populated by _export


def _export(fn):
    __all__.append(fn.__name__)
    return fn


def _wrap(x):
    return x if isinstance(x, NDArray) else NDArray(x)


def _unary(name, jfn):
    @register(name)
    def _kernel(x, **kw):
        return jfn(x, **kw) if kw else jfn(x)

    def op(data, **kwargs):
        f = functools.partial(jfn, **kwargs) if kwargs else jfn
        return invoke_raw(name, f, [_wrap(data)])
    op.__name__ = name
    return op


def _binary(name, jfn):
    @register(name)
    def _kernel(a, b):
        return jfn(a, b)

    def op(lhs, rhs, **kwargs):
        if isinstance(rhs, (int, float)):
            return invoke_raw(name + "_scalar",
                              lambda a, _s=rhs: jfn(a, _s), [_wrap(lhs)])
        if isinstance(lhs, (int, float)):
            return invoke_raw(name + "_scalar",
                              lambda b, _s=lhs: jfn(_s, b), [_wrap(rhs)])
        return invoke_raw(name, jfn, [_wrap(lhs), _wrap(rhs)])
    op.__name__ = name
    return op


# ---- elementwise unary (reference: src/operator/tensor/elemwise_unary_op*) ----
exp = _unary("exp", jnp.exp)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
cbrt = _unary("cbrt", jnp.cbrt)
rcbrt = _unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", lambda x: 1.0 / x)
negative = _unary("negative", jnp.negative)
abs = _unary("abs", jnp.abs)  # noqa: A001 — matches mx.nd.abs
sign = _unary("sign", jnp.sign)
ceil = _unary("ceil", jnp.ceil)
floor = _unary("floor", jnp.floor)
trunc = _unary("trunc", jnp.trunc)
rint = _unary("rint", jnp.rint)
round = _unary("round", jnp.round)  # noqa: A001
fix = _unary("fix", jnp.trunc)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
arcsin = _unary("arcsin", jnp.arcsin)
arccos = _unary("arccos", jnp.arccos)
arctan = _unary("arctan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
arcsinh = _unary("arcsinh", jnp.arcsinh)
arccosh = _unary("arccosh", jnp.arccosh)
arctanh = _unary("arctanh", jnp.arctanh)
degrees = _unary("degrees", jnp.degrees)
radians = _unary("radians", jnp.radians)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
log_sigmoid = _unary("log_sigmoid", jax.nn.log_sigmoid)
softsign = _unary("softsign", jax.nn.soft_sign)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
gamma = _unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
gammaln = _unary("gammaln", jax.scipy.special.gammaln)
logical_not = _unary("logical_not", lambda x: jnp.logical_not(x).astype(x.dtype))
relu = _unary("relu", jax.nn.relu)
softrelu = _unary("softrelu", jax.nn.softplus)
gelu = _unary("gelu", jax.nn.gelu)
silu = _unary("silu", jax.nn.silu)
isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)

# ---- elementwise binary (+ broadcast; reference elemwise_binary_broadcast_op*) ----
add = _binary("add", jnp.add)
subtract = _binary("sub", jnp.subtract)
multiply = _binary("mul", jnp.multiply)
divide = _binary("div", jnp.divide)
modulo = _binary("mod", jnp.mod)
power = _binary("pow", jnp.power)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
hypot = _binary("hypot", jnp.hypot)
arctan2 = _binary("arctan2", jnp.arctan2)
broadcast_add = add
broadcast_sub = subtract
broadcast_mul = multiply
broadcast_div = divide
broadcast_mod = modulo
broadcast_power = power
broadcast_maximum = maximum
broadcast_minimum = minimum
broadcast_hypot = hypot
__all__ += ["broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
            "broadcast_mod", "broadcast_power", "broadcast_maximum",
            "broadcast_minimum", "broadcast_hypot"]


def _cmp(name, jfn):
    def op(lhs, rhs):
        if isinstance(rhs, (int, float)):
            return invoke_raw(name, lambda a, _s=rhs: jfn(a, _s).astype(a.dtype),
                              [_wrap(lhs)], record=False)
        return invoke_raw(name, lambda a, b: jfn(a, b).astype(a.dtype),
                          [_wrap(lhs), _wrap(rhs)], record=False)
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater = _cmp("greater", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
lesser = _cmp("lesser", jnp.less)
lesser_equal = _cmp("lesser_equal", jnp.less_equal)
broadcast_equal = equal
broadcast_not_equal = not_equal
broadcast_greater = greater
broadcast_greater_equal = greater_equal
broadcast_lesser = lesser
broadcast_lesser_equal = lesser_equal
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
broadcast_logical_and = logical_and
broadcast_logical_or = logical_or
broadcast_logical_xor = logical_xor
__all__ += ["broadcast_equal", "broadcast_not_equal", "broadcast_greater",
            "broadcast_greater_equal", "broadcast_lesser",
            "broadcast_lesser_equal", "broadcast_logical_and",
            "broadcast_logical_or", "broadcast_logical_xor"]


# ---- reductions (reference: src/operator/tensor/broadcast_reduce_op*) ----
def _reduction(name, jfn):
    def op(data, axis=None, keepdims=False, exclude=False, **kwargs):
        data = _wrap(data)
        ax = _norm_axis(axis)
        if exclude and ax is not None:
            axt = (ax,) if isinstance(ax, int) else tuple(ax)
            ax = tuple(i for i in range(data.ndim) if i not in axt)
        fn = lambda x: jfn(x, axis=ax, keepdims=keepdims)
        return invoke_raw(name, fn, [data])
    op.__name__ = name
    return op


sum = _reduction("sum", jnp.sum)  # noqa: A001
mean = _reduction("mean", jnp.mean)
prod = _reduction("prod", jnp.prod)
nansum = _reduction("nansum", jnp.nansum)
nanprod = _reduction("nanprod", jnp.nanprod)
max = _reduction("max", jnp.max)  # noqa: A001
min = _reduction("min", jnp.min)  # noqa: A001


@_export
def norm(data, ord=2, axis=None, keepdims=False):
    return _wrap(data).norm(ord=ord, axis=axis, keepdims=keepdims)


@_export
def argmax(data, axis=None, keepdims=False):
    return _wrap(data).argmax(axis=axis, keepdims=keepdims)


@_export
def argmin(data, axis=None, keepdims=False):
    return _wrap(data).argmin(axis=axis, keepdims=keepdims)


@_export
def sum_axis(data, axis=None, keepdims=False):
    return sum(data, axis=axis, keepdims=keepdims)


# ---- dot / linalg (reference: src/operator/tensor/dot*, la_op*) ----
@_export
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """MXNet dot: contract last axis of lhs with first axis of rhs
    (reference dot-inl.h semantics, not numpy matmul)."""
    lhs, rhs = _wrap(lhs), _wrap(rhs)

    def fn(a, b):
        if transpose_a:
            a = jnp.transpose(a)
        if transpose_b:
            b = jnp.transpose(b)
        if a.ndim == 1 and b.ndim == 1:
            return jnp.dot(a, b)
        return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))
    return invoke_raw("dot", fn, [lhs, rhs])


@_export
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    lhs, rhs = _wrap(lhs), _wrap(rhs)

    def fn(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)
    return invoke_raw("batch_dot", fn, [lhs, rhs])


@_export
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0):
    def fn(a, b):
        if transpose_a:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_b:
            b = jnp.swapaxes(b, -1, -2)
        return alpha * jnp.matmul(a, b)
    return invoke_raw("linalg_gemm2", fn, [_wrap(A), _wrap(B)])


@_export
def linalg_potrf(A):
    return invoke_raw("linalg_potrf", lambda a: jnp.linalg.cholesky(a), [_wrap(A)])


@_export
def linalg_syrk(A, transpose=False, alpha=1.0):
    def fn(a):
        at = jnp.swapaxes(a, -1, -2)
        return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))
    return invoke_raw("linalg_syrk", fn, [_wrap(A)])


@_export
def linalg_inverse(A):
    """Matrix inverse (reference la_op _linalg_inverse)."""
    return invoke_raw("linalg_inverse", jnp.linalg.inv, [_wrap(A)])


@_export
def linalg_det(A):
    return invoke_raw("linalg_det", jnp.linalg.det, [_wrap(A)])


@_export
def linalg_slogdet(A):
    return invoke_raw("linalg_slogdet",
                      lambda a: tuple(jnp.linalg.slogdet(a)), [_wrap(A)],
                      n_outputs=2)


@_export
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular solve (reference la_op _linalg_trsm)."""
    def fn(a, b):
        import jax.scipy.linalg as jsl
        if rightside:
            # solve X A = alpha B  ->  A^T X^T = alpha B^T
            x = jsl.solve_triangular(jnp.swapaxes(a, -1, -2),
                                     jnp.swapaxes(alpha * b, -1, -2),
                                     lower=not lower, trans=1 if transpose
                                     else 0)
            return jnp.swapaxes(x, -1, -2)
        return jsl.solve_triangular(a, alpha * b, lower=lower,
                                    trans=1 if transpose else 0)
    return invoke_raw("linalg_trsm", fn, [_wrap(A), _wrap(B)])


@_export
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    """Triangular matmul (reference la_op _linalg_trmm)."""
    def fn(a, b):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        if transpose:
            tri = jnp.swapaxes(tri, -1, -2)
        return alpha * (jnp.matmul(b, tri) if rightside
                        else jnp.matmul(tri, b))
    return invoke_raw("linalg_trmm", fn, [_wrap(A), _wrap(B)])


@_export
def linalg_syevd(A):
    """Symmetric eigendecomposition (reference la_op _linalg_syevd):
    returns (U, L) with rows of U the eigenvectors (A = U^T diag(L) U)."""
    def fn(a):
        l, u = jnp.linalg.eigh(a)
        return jnp.swapaxes(u, -1, -2), l
    return invoke_raw("linalg_syevd", fn, [_wrap(A)], n_outputs=2)


@_export
def linalg_sumlogdiag(A):
    """Sum of log of diagonal (reference la_op _linalg_sumlogdiag)."""
    def fn(a):
        return jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)).sum(-1)
    return invoke_raw("linalg_sumlogdiag", fn, [_wrap(A)])


# ---- shape / layout manipulation (reference: matrix_op*) ----
@_export
def reshape(data, shape, reverse=False):
    return _wrap(data).reshape(shape, reverse=reverse)


@_export
def reshape_like(lhs, rhs):
    return _wrap(lhs).reshape(_wrap(rhs).shape)


@_export
def transpose(data, axes=None):
    d = _wrap(data)
    return d.transpose(axes) if axes else d.transpose()


@_export
def swapaxes(data, dim1=0, dim2=0):
    return _wrap(data).swapaxes(dim1, dim2)


@_export
def flip(data, axis):
    return _wrap(data).flip(axis)


@_export
def reverse(data, axis):
    return _wrap(data).flip(axis)


@_export
def tile(data, reps):
    return _wrap(data).tile(reps)


@_export
def repeat(data, repeats, axis=None):
    return _wrap(data).repeat(repeats, axis)


@_export
def pad(data, mode="constant", pad_width=None, constant_value=0.0):
    """Reference Pad op: pad_width is flat (before, after) per axis."""
    data = _wrap(data)
    pw = list(zip(pad_width[0::2], pad_width[1::2]))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if mode == "constant":
        fn = lambda x: jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    else:
        fn = lambda x: jnp.pad(x, pw, mode=jmode)
    return invoke_raw("pad", fn, [data])


@_export
def expand_dims(data, axis):
    return _wrap(data).expand_dims(axis)


@_export
def squeeze(data, axis=None):
    return _wrap(data).squeeze(axis)


@_export
def broadcast_to(data, shape):
    return _wrap(data).broadcast_to(shape)


@_export
def broadcast_like(lhs, rhs):
    return _wrap(lhs).broadcast_to(_wrap(rhs).shape)


@_export
def broadcast_axis(data, axis, size):
    data = _wrap(data)
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a] = s
    return data.broadcast_to(tuple(tgt))


@_export
def concat(*data, dim=1):
    return invoke_raw("concat", lambda *xs: jnp.concatenate(xs, axis=dim),
                      [_wrap(d) for d in data])


@_export
def stack(*data, axis=0):
    return invoke_raw("stack", lambda *xs: jnp.stack(xs, axis=axis),
                      [_wrap(d) for d in data])


@_export
def split(data, num_outputs, axis=1, squeeze_axis=False):
    data = _wrap(data)

    def fn(x):
        parts = jnp.split(x, num_outputs, axis=axis)
        if squeeze_axis:
            parts = [jnp.squeeze(p, axis=axis) for p in parts]
        return tuple(parts)
    out = invoke_raw("split", fn, [data], n_outputs=num_outputs)
    return list(out) if isinstance(out, tuple) else [out]


slice_channel = split
__all__.append("slice_channel")


@_export
def slice(data, begin, end, step=None):  # noqa: A001 — mx.nd.slice
    data = _wrap(data)
    step = step or [1] * len(begin)
    key = tuple(builtins_slice(b, e, s) for b, e, s in zip(begin, end, step))
    return invoke_raw("slice", lambda x, _k=key: x[_k], [data])


@_export
def slice_axis(data, axis, begin, end):
    data = _wrap(data)
    if end is None:
        end = data.shape[axis]
    key = [builtins_slice(None)] * data.ndim
    key[axis] = builtins_slice(begin, end)
    key = tuple(key)
    return invoke_raw("slice_axis", lambda x, _k=key: x[_k], [data])


@_export
def slice_like(data, shape_like, axes=None):
    data, like = _wrap(data), _wrap(shape_like)
    tgt = list(data.shape)
    axes = axes if axes is not None else range(data.ndim)
    for a in axes:
        tgt[a] = like.shape[a]
    key = tuple(builtins_slice(0, t) for t in tgt)
    return invoke_raw("slice_like", lambda x, _k=key: x[_k], [data])


# ---- indexing (reference: indexing_op*) ----
@_export
def take(a, indices, axis=0, mode="clip"):
    a, indices = _wrap(a), _wrap(indices)

    def fn(x, idx):
        idx = idx.astype(jnp.int32)
        n = x.shape[axis]
        if mode == "clip":
            idx = jnp.clip(idx, 0, n - 1)
        elif mode == "wrap":
            idx = jnp.mod(idx, n)
        return jnp.take(x, idx, axis=axis)
    return invoke_raw("take", fn, [a, indices])


@_export
def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False):
    """Reference Embedding op (src/operator/tensor/indexing_op.cc).

    ``sparse_grad=True`` (reference indexing_op.cc SparseEmbedding +
    FInferStorageType row_sparse grad): on the eager recording path the
    weight gradient is produced as a RowSparseNDArray whose values are
    segment-summed cotangent rows over the UNIQUE token ids — O(rows
    touched) gradient math instead of a dense scatter over the whole
    vocabulary, feeding the optimizer's lazy row update. Inside a jit trace
    (hybridized) gradients are dense by construction and the standard path
    is used."""
    data, weight = _wrap(data), _wrap(weight)
    if sparse_grad:
        from .. import _tape
        if _tape.is_recording() and not isinstance(data._data,
                                                   jax.core.Tracer):
            return _embedding_sparse_grad(data, weight)
    # mode='clip': out-of-range ids clamp to the nearest row. The reference
    # CPU kernel raises and its GPU kernel reads out of bounds
    # (indexing_op.h); neither is expressible under jit, and jnp.take's
    # default fill-with-NaN poisons gradients silently — clamping is the
    # deterministic TPU-native choice (documented deviation).
    return invoke_raw("embedding",
                      lambda idx, w: jnp.take(w, idx.astype(jnp.int32),
                                              axis=0, mode="clip"),
                      [data, weight])


def _embedding_sparse_grad(data, weight):
    """Record an embedding lookup whose weight cotangent is row_sparse.

    The unique-id set and inverse map are computed on host at forward time
    (token ids are host-produced by the data pipeline, so this sync is
    effectively free); backward is then a pure XLA segment_sum over the
    looked-up rows."""
    from .. import _tape
    from .sparse import _make_row_sparse_lazy

    ids_host = onp.asarray(data._data).astype("int32").reshape(-1)
    uids, inv = onp.unique(ids_host, return_inverse=True)
    uids_j = jnp.asarray(uids, jnp.int32)
    inv_j = jnp.asarray(inv.astype("int32"))
    n_u = int(uids.shape[0])
    vocab, dim = weight._data.shape
    out_shape = tuple(data.shape) + (dim,)

    def fwd(idx, w):
        return jnp.take(w, idx.astype(jnp.int32), axis=0)

    out_data = jnp.take(weight._data, jnp.asarray(ids_host),
                        axis=0).reshape(out_shape)

    def vjp_fn(ct):
        ctd = ct._data if isinstance(ct, NDArray) else ct
        vals = ctd.reshape(-1, dim)
        summed = jax.ops.segment_sum(vals, inv_j, num_segments=n_u)
        # LAZY dense mirror: the O(vocab) scatter runs only if a dense
        # consumer reads it; the sparse path (lazy optimizer, kvstore
        # identity round-trip) stays O(rows) end-to-end
        thunk = (lambda s=summed: jnp.zeros((vocab, dim), s.dtype)
                 .at[uids_j].add(s))
        return (None, _make_row_sparse_lazy(thunk, uids_j, summed))

    node = _tape.TapeNode(
        "embedding_sparse", [data, weight], fwd, vjp_fn,
        [jax.ShapeDtypeStruct(out_data.shape, out_data.dtype)])
    out = NDArray(out_data)
    out._tape_entry = (node, 0)
    return out


@_export
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    data, index = _wrap(data), _wrap(index)

    def fn(x, idx):
        idx = idx.astype(jnp.int32)
        n = x.shape[axis]
        idx = jnp.clip(idx, 0, n - 1) if mode == "clip" else jnp.mod(idx, n)
        out = jnp.take_along_axis(x, jnp.expand_dims(idx, axis), axis=axis)
        return out if keepdims else jnp.squeeze(out, axis=axis)
    return invoke_raw("pick", fn, [data, index])


@_export
def gather_nd(data, indices):
    data, indices = _wrap(data), _wrap(indices)

    def fn(x, idx):
        idx = idx.astype(jnp.int32)
        return x[tuple(idx[i] for i in range(idx.shape[0]))]
    return invoke_raw("gather_nd", fn, [data, indices])


@_export
def scatter_nd(data, indices, shape):
    data, indices = _wrap(data), _wrap(indices)

    def fn(d, idx):
        idx = idx.astype(jnp.int32)
        out = jnp.zeros(tuple(shape), d.dtype)
        return out.at[tuple(idx[i] for i in range(idx.shape[0]))].add(d)
    return invoke_raw("scatter_nd", fn, [data, indices])


@_export
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _wrap(indices).one_hot(depth, on_value, off_value, dtype)


@_export
def where(condition, x, y):
    condition, x, y = _wrap(condition), _wrap(x), _wrap(y)
    return invoke_raw("where",
                      lambda c, a, b: jnp.where(c.astype(jnp.bool_), a, b),
                      [condition, x, y])


@_export
def boolean_mask(data, index, axis=0):
    data, index = _wrap(data), _wrap(index)
    idx = onp.asarray(index.asnumpy(), dtype=bool)
    sel = onp.nonzero(idx)[0]

    def fn(x, _sel=jnp.asarray(sel)):
        return jnp.take(x, _sel, axis=axis)
    return invoke_raw("boolean_mask", fn, [data])


# ---- ordering (reference: ordering_op*) ----
@_export
def sort(data, axis=-1, is_ascend=True):
    def fn(x):
        out = jnp.sort(x, axis=axis)
        return out if is_ascend else jnp.flip(out, axis=axis)
    return invoke_raw("sort", fn, [_wrap(data)])


@_export
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    dt = jx_dtype(dtype)

    def fn(x):
        out = jnp.argsort(x, axis=axis)
        if not is_ascend:
            out = jnp.flip(out, axis=axis)
        return out.astype(dt)
    return invoke_raw("argsort", fn, [_wrap(data)], record=False)


@_export
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    data = _wrap(data)
    dt = jx_dtype(dtype)

    if ret_typ not in ("value", "indices", "both", "mask"):
        raise MXNetError(f"unknown topk ret_typ {ret_typ!r}")

    def fn(x):
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = jax.lax.top_k(-xm if is_ascend else xm, k)
        if is_ascend:
            vals = -vals
        if ret_typ == "mask":
            onehots = jax.nn.one_hot(idx, xm.shape[-1], dtype=dt).sum(axis=-2)
            return jnp.moveaxis(onehots, -1, axis)
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
        if ret_typ == "value":
            return vals
        if ret_typ == "both":
            return vals, idx.astype(dt)
        return idx.astype(dt)
    n_out = 2 if ret_typ == "both" else 1
    return invoke_raw("topk", fn, [data], n_outputs=n_out,
                      record=(ret_typ == "value"))


# ---- casts / misc ----
@_export
def cast(data, dtype):
    return _wrap(data).astype(dtype)


@_export
def clip(data, a_min, a_max):
    return _wrap(data).clip(a_min, a_max)


@_export
def amp_cast(data, dtype):
    return cast(data, dtype)


@_export
def amp_multicast(*data, num_outputs=None):
    arrs = [_wrap(d) for d in data]
    widest = jnp.result_type(*[a._data.dtype for a in arrs])
    return [a.astype(widest) for a in arrs]


@_export
def zeros_like(data):
    return invoke_raw("zeros_like", jnp.zeros_like, [_wrap(data)], record=False)


@_export
def ones_like(data):
    return invoke_raw("ones_like", jnp.ones_like, [_wrap(data)], record=False)


@_export
def full_like(data, fill_value):
    return invoke_raw("full_like",
                      lambda x: jnp.full_like(x, fill_value), [_wrap(data)],
                      record=False)


@_export
def identity(data):
    return invoke_raw("identity", lambda x: x, [_wrap(data)])


@_export
def stop_gradient(data):
    return invoke_raw("stop_gradient", jax.lax.stop_gradient, [_wrap(data)])


BlockGrad = stop_gradient
__all__.append("BlockGrad")


@_export
def make_loss(data):
    return invoke_raw("make_loss", lambda x: x, [_wrap(data)])


@_export
def add_n(*args):
    return invoke_raw("add_n", lambda *xs: functools.reduce(jnp.add, xs),
                      [_wrap(a) for a in args])


ElementWiseSum = add_n
__all__.append("ElementWiseSum")


@_export
def unique(data):
    d = _wrap(data)
    arr = onp.unique(d.asnumpy())
    return NDArray(arr)


@_export
def histogram(data, bins=10, range=None):  # noqa: A002
    d = _wrap(data)
    cnt, edges = onp.histogram(d.asnumpy(), bins=bins, range=range)
    return NDArray(cnt), NDArray(edges)


@_export
def diag(data, k=0):
    return _wrap(data).diag(k)


@_export
def shape_array(data):
    return NDArray(onp.array(_wrap(data).shape, dtype=onp.int64))


@_export
def size_array(data):
    return NDArray(onp.array([_wrap(data).size], dtype=onp.int64))


@_export
def moments(data, axes=None, keepdims=False):
    data = _wrap(data)
    ax = _norm_axis(axes)

    def fn(x):
        m = jnp.mean(x, axis=ax, keepdims=keepdims)
        v = jnp.var(x, axis=ax, keepdims=keepdims)
        return m, v
    return invoke_raw("moments", fn, [data], n_outputs=2)


# ---- cumulative ----
@_export
def cumsum(data, axis=None, dtype=None):
    def fn(x):
        out = jnp.cumsum(x, axis=axis)
        return out.astype(jx_dtype(dtype)) if dtype else out
    return invoke_raw("cumsum", fn, [_wrap(data)])


# ---- sequence ops (reference: src/operator/sequence_*-inl.h) ----
@_export
def SequenceMask(data, sequence_length=None, use_sequence_length=False,
                 value=0.0, axis=0):
    data = _wrap(data)
    if not use_sequence_length or sequence_length is None:
        return identity(data)
    seq_len = _wrap(sequence_length)

    def fn(x, sl):
        T = x.shape[axis]
        pos = jnp.arange(T)
        shape = [1] * x.ndim
        shape[axis] = T
        pos = pos.reshape(shape)
        batch_axis = 1 - axis if axis in (0, 1) else 0
        slshape = [1] * x.ndim
        slshape[batch_axis] = x.shape[batch_axis]
        mask = pos < sl.reshape(slshape)
        return jnp.where(mask, x, jnp.asarray(value, x.dtype))
    return invoke_raw("sequence_mask", fn, [data, seq_len])


sequence_mask = SequenceMask
__all__ += ["SequenceMask", "sequence_mask"]


@_export
def SequenceLast(data, sequence_length=None, use_sequence_length=False, axis=0):
    data = _wrap(data)
    if not use_sequence_length or sequence_length is None:
        return invoke_raw("sequence_last",
                          lambda x: jnp.take(x, x.shape[axis] - 1, axis=axis),
                          [data])
    seq_len = _wrap(sequence_length)

    def fn(x, sl):
        idx = (sl.astype(jnp.int32) - 1)
        xm = jnp.moveaxis(x, axis, 0)  # (T, B, ...)
        return jnp.take_along_axis(
            xm, idx.reshape((1, -1) + (1,) * (xm.ndim - 2)), axis=0)[0]
    return invoke_raw("sequence_last", fn, [data, seq_len])


@_export
def SequenceReverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    data = _wrap(data)
    if not use_sequence_length or sequence_length is None:
        return flip(data, axis)
    seq_len = _wrap(sequence_length)

    def fn(x, sl):
        T = x.shape[0]
        pos = jnp.arange(T)[:, None]
        sl_i = sl.astype(jnp.int32)[None, :]
        rev_idx = jnp.where(pos < sl_i, sl_i - 1 - pos, pos)
        return jnp.take_along_axis(
            x, rev_idx.reshape(rev_idx.shape + (1,) * (x.ndim - 2)), axis=0)
    return invoke_raw("sequence_reverse", fn, [data, seq_len])


# ---- softmax family (reference: src/operator/nn/softmax*) ----
@_export
def softmax(data, axis=-1, temperature=None, length=None):
    data = _wrap(data)
    t = temperature or 1.0
    if length is not None:
        ln = _wrap(length)

        def fn(x, l):
            T = x.shape[axis]
            mask = jnp.arange(T) < l[..., None]
            x = jnp.where(mask, x / t, -jnp.inf)
            return jax.nn.softmax(x, axis=axis)
        return invoke_raw("softmax", fn, [data, ln])
    return invoke_raw("softmax", lambda x: jax.nn.softmax(x / t, axis=axis), [data])


@_export
def log_softmax(data, axis=-1, temperature=None):
    t = temperature or 1.0
    return invoke_raw("log_softmax",
                      lambda x: jax.nn.log_softmax(x / t, axis=axis), [_wrap(data)])


@_export
def softmin(data, axis=-1):
    return invoke_raw("softmin", lambda x: jax.nn.softmax(-x, axis=axis), [_wrap(data)])


@_export
def softmax_cross_entropy(data, label):
    data, label = _wrap(data), _wrap(label)

    def fn(x, y):
        logp = jax.nn.log_softmax(x, axis=-1)
        y = y.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, y[:, None], axis=-1)
        return -jnp.sum(picked)
    return invoke_raw("softmax_cross_entropy", fn, [data, label])


@_export
def SoftmaxOutput(data, label, grad_scale=1.0, ignore_label=-1,
                  use_ignore=False, multi_output=False, preserve_shape=False,
                  normalization="null", out_grad=False, smooth_alpha=0.0):
    """Legacy SoftmaxOutput: forward is softmax; backward injects CE grad
    (reference src/operator/softmax_output*). We model forward-only here; the
    gradient flows via softmax_cross_entropy in training loops."""
    return softmax(_wrap(data), axis=-1)


# ---- LeakyReLU/Activation op forms ----
@_export
def LeakyReLU(data, act_type="leaky", slope=0.25, gamma=None,
              lower_bound=0.125, upper_bound=0.334):
    data = _wrap(data)
    if act_type == "leaky":
        return invoke_raw("leaky_relu",
                          lambda x: jnp.where(x > 0, x, slope * x), [data])
    if act_type == "elu":
        return invoke_raw("elu", lambda x: jax.nn.elu(x, alpha=slope), [data])
    if act_type == "selu":
        return invoke_raw("selu", jax.nn.selu, [data])
    if act_type == "gelu":
        return invoke_raw("gelu", lambda x: jax.nn.gelu(x, approximate=False), [data])
    if act_type == "prelu":
        g = _wrap(gamma)
        return invoke_raw("prelu",
                          lambda x, gm: jnp.where(x > 0, x, gm * x), [data, g])
    if act_type == "rrelu":
        s = (lower_bound + upper_bound) / 2.0
        return invoke_raw("rrelu", lambda x: jnp.where(x > 0, x, s * x), [data])
    raise MXNetError(f"unknown LeakyReLU act_type {act_type}")


@_export
def Activation(data, act_type="relu"):
    fns = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
           "softrelu": jax.nn.softplus, "softsign": jax.nn.soft_sign,
           "log_sigmoid": jax.nn.log_sigmoid,
           "mish": lambda x: x * jnp.tanh(jax.nn.softplus(x)),
           "gelu": lambda x: jax.nn.gelu(x, approximate=False),
           "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
           "silu": jax.nn.silu, "swish": jax.nn.silu}
    if act_type not in fns:
        raise MXNetError(f"unknown Activation act_type {act_type!r}")
    return invoke_raw(f"activation_{act_type}", fns[act_type], [_wrap(data)])


@_export
def FullyConnected(data, weight, bias=None, num_hidden=None, no_bias=False,
                   flatten=True):
    """Reference FullyConnected (src/operator/nn/fully_connected.cc):
    out = X W^T + b; flatten collapses trailing axes."""
    data, weight = _wrap(data), _wrap(weight)

    if no_bias or bias is None:
        def fn(x, w):
            if flatten and x.ndim > 2:
                x = x.reshape(x.shape[0], -1)
            return jnp.dot(x, w.T)
        return invoke_raw("fully_connected", fn, [data, weight])

    bias = _wrap(bias)

    def fnb(x, w, b):
        if flatten and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        return jnp.dot(x, w.T) + b
    return invoke_raw("fully_connected", fnb, [data, weight, bias])


@_export
def Dropout(data, p=0.5, mode="training", axes=None, cudnn_off=False):
    from .. import _tape as tape
    from . import random as nd_random
    data = _wrap(data)
    if not tape.is_training() and mode != "always":
        return identity(data)
    key = nd_random.next_key()
    axes = axes or ()

    def fn(x, _key=key):
        shape = list(x.shape)
        for a in axes:
            shape[a] = 1
        keep = jax.random.bernoulli(_key, 1.0 - p, tuple(shape))
        return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    return invoke_raw("dropout", fn, [data])


@_export
def Embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    return embedding(data, weight, sparse_grad=sparse_grad)


@_export
def Flatten(data):
    return _wrap(data).flatten()


@_export
def Cast(data, dtype):
    return cast(data, dtype)


# ---- NN layer ops used by gluon (conv/pool/norm) live in ops/nn.py and are
# re-exported via ndarray/__init__ ----

# Rebuild __all__ from module globals so helper-created ops export under
# their bound python names (e.g. ``subtract = _binary("sub", ...)``).
__all__ = sorted({
    n for n, v in list(globals().items())
    if not n.startswith("_") and callable(v)
    and getattr(v, "__module__", __name__) in (__name__, None)
    and n not in ("NDArray", "invoke_raw", "register", "jx_dtype",
                  "MXNetError", "builtins_slice", "functools", "onp",
                  "jax", "jnp")
})
