"""Control-flow operators: foreach / while_loop / cond.

Reference analog: src/operator/control_flow.cc (`_foreach` :1094,
`_while_loop` :1155, `_cond` :1216) — subgraph-holding stateful ops with full
backward, exposed as ``mx.nd.contrib.*`` (python/mxnet/ndarray/contrib.py).

TPU-native design: the body/cond/branch callables trace into ``lax.scan`` /
``lax.cond`` — XLA compiles the body ONCE regardless of trip count (the
reference re-executes the subgraph per step through the engine). while_loop
lowers to a masked fixed-trip ``lax.scan`` rather than ``lax.while_loop``:
scan is reverse-differentiable and maps to a static TPU program; the mask
reproduces data-dependent termination. All three integrate with autograd via
the op-invoke funnel, so gradients flow through loop bodies and branches.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

from .. import _tape, autograd
from ..base import MXNetError
from ..ops.registry import invoke_raw
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]

# grid-sampling / detection family lives in vision_ops; the reference
# exposes these under mx.nd.contrib.* (contrib/deformable_convolution.cc,
# deformable_psroi_pooling.cc, proposal.cc, count_sketch.cc,
# sync_batch_norm.cc)
from .vision_ops import (DeformableConvolution,  # noqa: E402,F401
                         ModulatedDeformableConvolution,
                         DeformablePSROIPooling,
                         Proposal, MultiProposal, count_sketch,
                         SyncBatchNorm, BilinearSampler, GridGenerator,
                         SpatialTransformer, Correlation)
__all__ += ["DeformableConvolution", "ModulatedDeformableConvolution",
            "DeformablePSROIPooling", "Proposal",
            "MultiProposal", "count_sketch", "SyncBatchNorm",
            "BilinearSampler", "GridGenerator", "SpatialTransformer",
            "Correlation"]


def _as_list(x) -> Tuple[List, bool]:
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False


def _wrap(arrs):
    return [NDArray(a) if not isinstance(a, NDArray) else a for a in arrs]


def _datas(arrs):
    return [a._data if isinstance(a, NDArray) else a for a in arrs]


def _call_sub(fn, *nd_args):
    """Run a user subgraph callable with recording off (the whole control-flow
    op records as ONE tape node; jax.vjp differentiates through the body)."""
    prev = _tape.set_recording(False)
    try:
        return fn(*nd_args)
    finally:
        _tape.set_recording(prev)


def foreach(body, data, init_states):
    """Scan ``body`` over the leading axis of ``data``
    (reference _foreach, control_flow.cc:1094; python frontend
    python/mxnet/ndarray/contrib.py foreach).

    body(step_data, states) -> (outputs, new_states). Returns
    (stacked_outputs, final_states) with input list/single structure
    preserved.
    """
    data_list, data_is_list = _as_list(data)
    states, states_is_list = _as_list(init_states)
    n_d, n_s = len(data_list), len(states)

    # probe the body once to learn the output structure (the reference infers
    # the same from the traced subgraph)
    step0 = [d.take(0, axis=0) for d in data_list]
    with autograd.pause():
        probe_out, probe_states = _call_sub(
            body,
            step0 if data_is_list else step0[0],
            list(states) if states_is_list else states[0])
    probe_outs, out_is_list = _as_list(probe_out)
    probe_new_states, _ = _as_list(probe_states)
    if len(probe_new_states) != n_s:
        raise MXNetError("foreach body must return the same number of states")
    n_o = len(probe_outs)

    def fn(*arrs):
        xs = arrs[:n_d]
        st = list(arrs[n_d:])

        def step(carry, x_t):
            d_nd = _wrap(list(x_t))
            s_nd = _wrap(list(carry))
            out, new_st = _call_sub(
                body,
                d_nd if data_is_list else d_nd[0],
                s_nd if states_is_list else s_nd[0])
            outs, _ = _as_list(out)
            new_states, _ = _as_list(new_st)
            return tuple(_datas(new_states)), tuple(_datas(outs))

        carry, ys = lax.scan(step, tuple(st), tuple(xs))
        return tuple(ys) + tuple(carry)

    res = invoke_raw("_foreach", fn, data_list + states,
                     n_outputs=n_o + n_s)
    res = res if isinstance(res, tuple) else (res,)
    outs = list(res[:n_o])
    fin = list(res[n_o:])
    return (outs if out_is_list else outs[0],
            fin if states_is_list else fin[0])


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Differentiable while (reference _while_loop, control_flow.cc:1155).

    cond(*loop_vars) -> boolean scalar; func(*loop_vars) ->
    (step_output, new_loop_vars). Returns (stacked_outputs, final_loop_vars);
    outputs rows beyond termination are zero (the reference leaves them
    undefined). Lowered as a masked fixed-trip lax.scan: reverse-mode
    differentiable and a static TPU program, unlike lax.while_loop.
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    loop_list, vars_is_list = _as_list(loop_vars)
    n_v = len(loop_list)

    with autograd.pause():
        probe_out, probe_vars = _call_sub(func, *loop_list)
    probe_outs, out_is_list = _as_list(probe_out)
    probe_new_vars, _ = _as_list(probe_vars)
    if len(probe_new_vars) != n_v:
        raise MXNetError("while_loop func must preserve loop_vars arity")
    n_o = len(probe_outs)

    def fn(*arrs):
        def step(carry, _):
            active, vs = carry
            vs_nd = _wrap(list(vs))
            c = _call_sub(cond, *vs_nd)
            c = (c._data if isinstance(c, NDArray) else c).reshape(())
            active = jnp.logical_and(active, c.astype(bool))
            out, new_vs = _call_sub(func, *vs_nd)
            outs = _datas(_as_list(out)[0])
            new_vs = _datas(_as_list(new_vs)[0])
            sel = lambda n, o: jnp.where(
                active.reshape((1,) * n.ndim), n, o)
            kept = tuple(sel(n, o) for n, o in zip(new_vs, vs))
            step_out = tuple(jnp.where(active.reshape((1,) * o.ndim), o,
                                       jnp.zeros_like(o)) for o in outs)
            return (active, kept), step_out

        init = (jnp.asarray(True), tuple(arrs))
        (_, final), ys = lax.scan(step, init, None, length=max_iterations)
        return tuple(ys) + tuple(final)

    res = invoke_raw("_while_loop", fn, loop_list, n_outputs=n_o + n_v)
    res = res if isinstance(res, tuple) else (res,)
    outs = list(res[:n_o])
    fin = list(res[n_o:])
    return (outs if out_is_list else outs[0],
            fin if vars_is_list else fin[0])


def cond(pred, then_func, else_func, inputs=None):
    """Two-branch conditional (reference _cond, control_flow.cc:1216).

    pred: boolean scalar NDArray (or a callable over ``inputs``); both
    branches must return the same structure. Lowers to ``lax.cond`` — only
    the taken branch executes on device.
    """
    ins, ins_is_list = _as_list(inputs if inputs is not None else [])

    if callable(pred):
        with autograd.pause():
            pred = _call_sub(pred, *ins)
    with autograd.pause():
        probe = _call_sub(then_func, *ins) if callable(then_func) else None
    probe_outs, out_is_list = _as_list(probe)
    n_o = len(probe_outs)

    def fn(p, *arrs):
        def run(branch):
            def f(xs):
                out = _call_sub(branch, *_wrap(list(xs)))
                return tuple(_datas(_as_list(out)[0]))
            return f

        return lax.cond(p.reshape(()).astype(bool),
                        run(then_func), run(else_func), tuple(arrs))

    res = invoke_raw("_cond", fn, [pred] + ins, n_outputs=n_o)
    res = list(res) if isinstance(res, tuple) else [res]
    return res if out_is_list else res[0]


# ---------------------------------------------------------------------------
# Bounding-box / detection ops
# Reference analog: src/operator/contrib/bounding_box.cc (box_iou, box_nms)
# and src/operator/contrib/roi_align.cc. TPU-native: fixed-shape vectorized
# jnp programs — NMS is a masked greedy scan (static trip count compiles to
# one XLA program; the reference's CUDA kernel sorted + suppressed in-place).
# ---------------------------------------------------------------------------

__all__ += ["box_iou", "box_nms", "ROIAlign"]


def _corner_iou(a, b):
    """IoU between (..., N, 4) and (..., M, 4) corner boxes → (..., N, M)."""
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.clip(br - tl, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[..., 2] - a[..., 0], 0) * \
        jnp.clip(a[..., 3] - a[..., 1], 0)
    area_b = jnp.clip(b[..., 2] - b[..., 0], 0) * \
        jnp.clip(b[..., 3] - b[..., 1], 0)
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


def _to_corner(x, fmt):
    if fmt == "corner":
        return x
    # center: (cx, cy, w, h) -> (x1, y1, x2, y2)
    half = x[..., 2:] / 2
    return jnp.concatenate([x[..., :2] - half, x[..., :2] + half], -1)


def _to_center(x):
    # corner (x1, y1, x2, y2) -> (cx, cy, w, h)
    wh = x[..., 2:] - x[..., :2]
    return jnp.concatenate([x[..., :2] + wh / 2, wh], -1)


def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference _contrib_box_iou, bounding_box.cc)."""
    def fn(a, b):
        return _corner_iou(_to_corner(a, format), _to_corner(b, format))
    return invoke_raw("box_iou", fn, [lhs, rhs])


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Non-maximum suppression (reference _contrib_box_nms,
    bounding_box.cc): rows are [id, score, x1, y1, x2, y2, ...]; suppressed
    rows have all entries set to -1. Batch-aware on (B, N, K) or (N, K)."""
    def fn(x):
        squeeze = x.ndim == 2
        if squeeze:
            x = x[None]
        b, n, k = x.shape
        scores = x[..., score_index]
        ids = x[..., id_index] if id_index >= 0 else jnp.zeros_like(scores)
        boxes = _to_corner(
            lax.dynamic_slice_in_dim(x, coord_start, 4, axis=2), in_format)
        valid = scores > valid_thresh
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf), axis=1)
        if topk > 0:
            keep_rank = jnp.arange(n) < topk
        else:
            keep_rank = jnp.ones((n,), bool)
        sboxes = jnp.take_along_axis(boxes, order[..., None], 1)
        svalid = jnp.take_along_axis(valid, order, 1) & keep_rank[None, :]
        sids = jnp.take_along_axis(ids, order, 1)
        iou = _corner_iou(sboxes, sboxes)          # (b, n, n)
        same_cls = (sids[..., :, None] == sids[..., None, :]) | force_suppress

        def body(i, keep):
            sup = (iou[:, i] > overlap_thresh) & same_cls[:, i] & \
                keep[:, i][:, None] & svalid[:, i][:, None] & \
                (jnp.arange(n) > i)[None, :]
            return keep & ~sup

        keep = lax.fori_loop(0, n, body, jnp.ones((b, n), bool))
        keep = keep & svalid
        sx = jnp.take_along_axis(x, order[..., None], 1)
        if in_format != out_format:
            coords = lax.dynamic_slice_in_dim(sx, coord_start, 4, axis=2)
            coords = _to_corner(coords, in_format) if out_format == "corner" \
                else _to_center(coords)
            sx = lax.dynamic_update_slice_in_dim(sx, coords, coord_start,
                                                 axis=2)
        out = jnp.where(keep[..., None], sx, -jnp.ones_like(sx))
        return out[0] if squeeze else out

    return invoke_raw("box_nms", fn, [data])


def ROIAlign(data, rois, pooled_size, spatial_scale, sample_ratio=2,
             position_sensitive=False):
    """ROI Align with bilinear sampling (reference roi_align.cc; Mask R-CNN
    semantics: no coordinate rounding, out-of-image samples contribute
    zero, negative batch index → all-zero output for that ROI).

    data (N, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2] in image
    coords. Plain: out (R, C, PH, PW). ``position_sensitive``: channels
    are grouped per output bin (C must be divisible by PH*PW) and out is
    (R, C/(PH*PW), PH, PW) — PS-ROIAlign.

    ``sample_ratio <= 0``: the reference samples ceil(roi/pooled) points
    per bin *per ROI* (dynamic); XLA needs a static grid, so this build
    uses the feature-map upper bound ceil(H/PH) × ceil(W/PW) — at least as
    dense as the reference everywhere.
    """
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else tuple(pooled_size)

    def fn(x, r):
        n, c, h, w = x.shape
        if sample_ratio > 0:
            sry = srx = int(sample_ratio)
        else:
            sry = max(1, -(-h // ph))
            srx = max(1, -(-w // pw))
        if position_sensitive and c % (ph * pw):
            raise MXNetError(f"position_sensitive needs channels ({c}) "
                             f"divisible by PH*PW ({ph * pw})")

        def one_roi(roi):
            bi = roi[0].astype(jnp.int32)
            x1, y1, x2, y2 = [roi[i + 1] * spatial_scale for i in range(4)]
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            bin_w, bin_h = rw / pw, rh / ph
            gy = (y1 + (jnp.arange(ph)[:, None] +
                        (jnp.arange(sry)[None, :] + 0.5) / sry) * bin_h
                  ).reshape(-1)                    # (ph*sry,)
            gx = (x1 + (jnp.arange(pw)[:, None] +
                        (jnp.arange(srx)[None, :] + 0.5) / srx) * bin_w
                  ).reshape(-1)                    # (pw*srx,)
            img = x[jnp.clip(bi, 0, n - 1)]        # (c, h, w)

            # reference bilinear_interpolate: points past [-1, size] are 0
            in_y = (gy >= -1.0) & (gy <= h)
            in_x = (gx >= -1.0) & (gx <= w)
            cy = jnp.clip(gy, 0, h - 1)
            cx = jnp.clip(gx, 0, w - 1)
            y0 = jnp.floor(cy)
            x0 = jnp.floor(cx)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
            wy = cy - y0
            wx = cx - x0
            r0 = img[:, y0i]                       # (c, ph*sry, w)
            r1 = img[:, y1i]
            top = r0[:, :, x0i] * (1 - wx) + r0[:, :, x1i] * wx
            bot = r1[:, :, x0i] * (1 - wx) + r1[:, :, x1i] * wx
            val = top * (1 - wy)[None, :, None] + bot * wy[None, :, None]
            val = val * (in_y[:, None] & in_x[None, :])[None]
            val = jnp.where(bi >= 0, val, 0.0)     # padded ROI → zeros
            val = val.reshape(c, ph, sry, pw, srx).mean((2, 4))
            if position_sensitive:
                cg = c // (ph * pw)
                # channel block (i,j) feeds output bin (i,j)
                val = val.reshape(ph, pw, cg, ph, pw)
                ii = jnp.arange(ph)[:, None]
                jj = jnp.arange(pw)[None, :]
                val = val[ii, jj, :, ii, jj]       # (ph, pw, cg)
                val = jnp.moveaxis(val, -1, 0)
            return val

        return jax.vmap(one_roi)(r)

    return invoke_raw("ROIAlign", fn, [data, rois])


# ---------------------------------------------------------------------------
# SSD MultiBox ops
# Reference analog: src/operator/contrib/multibox_prior.cc / multibox_target.cc
# / multibox_detection.cc (anchor generation, gt matching with variance-
# encoded regression targets, decode+NMS). Encoding uses the standard SSD
# variances (0.1, 0.1, 0.2, 0.2).
# ---------------------------------------------------------------------------

__all__ += ["MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection"]

_SSD_VAR = (0.1, 0.1, 0.2, 0.2)


def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                  steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor boxes for one feature map (reference multibox_prior.cc).
    data (B, C, H, W) → (1, H*W*num_anchors, 4) corner boxes in [0,1];
    num_anchors = len(sizes) + len(ratios) - 1."""
    sizes = tuple(float(s) for s in (sizes if isinstance(sizes, (list, tuple))
                                     else (sizes,)))
    ratios = tuple(float(r) for r in (ratios if isinstance(ratios,
                                                           (list, tuple))
                                      else (ratios,)))

    def fn(x):
        h, w = x.shape[2], x.shape[3]
        step_y = steps[0] if steps[0] > 0 else 1.0 / h
        step_x = steps[1] if steps[1] > 0 else 1.0 / w
        cy = (jnp.arange(h) + offsets[0]) * step_y
        cx = (jnp.arange(w) + offsets[1]) * step_x
        # anchor shapes: all sizes at ratio 1, then ratios[1:] at sizes[0]
        ws, hs = [], []
        for s in sizes:
            ws.append(s * jnp.sqrt(ratios[0]))
            hs.append(s / jnp.sqrt(ratios[0]))
        for r in ratios[1:]:
            ws.append(sizes[0] * jnp.sqrt(r))
            hs.append(sizes[0] / jnp.sqrt(r))
        aw = jnp.asarray(ws)                      # (A,)
        ah = jnp.asarray(hs)
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)
        cxg = cxg[..., None]
        cyg = cyg[..., None]
        x1 = cxg - aw / 2
        y1 = cyg - ah / 2
        x2 = cxg + aw / 2
        y2 = cyg + ah / 2
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # (H, W, A, 4)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        return boxes.reshape(1, -1, 4)

    return invoke_raw("MultiBoxPrior", fn, [data])


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=_SSD_VAR):
    """Assign gt to anchors (reference multibox_target.cc).
    anchor (1, N, 4); label (B, M, 5) rows [cls, x1, y1, x2, y2] (cls<0 =
    padding); cls_pred (B, num_cls+1, N) (used for hard negative mining).
    Returns (box_target (B, N*4), box_mask (B, N*4), cls_target (B, N))
    where cls_target is 0 for background, gt_cls+1 for matched."""
    v = jnp.asarray(variances)

    def fn(anc, lab, cp):
        anc = anc[0]                              # (N, 4)
        n = anc.shape[0]
        aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-12)
        ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-12)
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2

        def one(lb, cp_b):
            valid = lb[:, 0] >= 0                 # (M,)
            gt = lb[:, 1:5]
            iou = _corner_iou(anc, gt)            # (N, M)
            iou = jnp.where(valid[None, :], iou, -1.0)
            best_gt = jnp.argmax(iou, axis=1)     # per anchor
            best_iou = jnp.max(iou, axis=1)
            matched = best_iou >= overlap_threshold
            # force-match: each VALID gt's best anchor. Padding rows must
            # not participate: their argmax lands on some real anchor and
            # a duplicate-index scatter would clobber a valid gt's forced
            # match — route them to index n and drop.
            best_anchor = jnp.argmax(iou, axis=0)  # (M,)
            safe_anchor = jnp.where(valid, best_anchor, n)
            forced = jnp.zeros((n,), bool).at[safe_anchor].set(
                True, mode="drop")
            forced_gt = jnp.zeros((n,), jnp.int32).at[safe_anchor].set(
                jnp.arange(lb.shape[0], dtype=jnp.int32), mode="drop")
            gt_idx = jnp.where(forced, forced_gt, best_gt)
            matched = matched | forced

            g = gt[gt_idx]                        # (N, 4)
            gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-12)
            gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-12)
            gcx = (g[:, 0] + g[:, 2]) / 2
            gcy = (g[:, 1] + g[:, 3]) / 2
            tx = (gcx - acx) / aw / v[0]
            ty = (gcy - acy) / ah / v[1]
            tw = jnp.log(gw / aw) / v[2]
            th = jnp.log(gh / ah) / v[3]
            bt = jnp.stack([tx, ty, tw, th], 1)   # (N, 4)
            bt = jnp.where(matched[:, None], bt, 0.0)
            mask = jnp.where(matched[:, None], 1.0,
                             0.0) * jnp.ones((1, 4))
            cls_t = jnp.where(matched, lb[gt_idx, 0] + 1.0, 0.0)
            if negative_mining_ratio > 0:
                # hard negatives: most-confused background anchors first;
                # near-misses (IoU >= negative_mining_thresh) are excluded
                # from the candidate pool (reference multibox_target.cc)
                bg_prob = jax.nn.softmax(cp_b, axis=0)[0]  # (N,)
                candidate = (~matched) & \
                    (best_iou < negative_mining_thresh)
                neg_score = jnp.where(candidate, bg_prob, jnp.inf)
                n_pos = jnp.maximum(matched.sum(), 1)
                n_neg = jnp.maximum(
                    (negative_mining_ratio * n_pos).astype(jnp.int32),
                    jnp.int32(minimum_negative_samples))
                n_neg = jnp.minimum(n_neg, candidate.sum())
                order = jnp.argsort(neg_score)    # most-confused first
                rank = jnp.zeros((n,), jnp.int32).at[order].set(
                    jnp.arange(n, dtype=jnp.int32))
                keep_neg = candidate & (rank < n_neg)
                cls_t = jnp.where(matched | keep_neg, cls_t,
                                  jnp.float32(ignore_label))
            return bt.reshape(-1), mask.reshape(-1), cls_t

        bt, mask, ct = jax.vmap(one)(lab, cp)
        return bt, mask, ct

    return invoke_raw("MultiBoxTarget", fn, [anchor, label, cls_pred],
                      n_outputs=3)


def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5,
                      force_suppress=False, variances=_SSD_VAR,
                      nms_topk=-1):
    """Decode predictions + per-class NMS (reference multibox_detection.cc).
    cls_prob (B, num_cls+1, N); loc_pred (B, N*4); anchor (1, N, 4) →
    (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], suppressed rows -1;
    cls_id excludes background (0-based after removing background_id)."""
    v = jnp.asarray(variances)

    def fn(cp, lp, anc):
        b = cp.shape[0]
        anc = anc[0]
        n = anc.shape[0]
        aw = jnp.maximum(anc[:, 2] - anc[:, 0], 1e-12)
        ah = jnp.maximum(anc[:, 3] - anc[:, 1], 1e-12)
        acx = (anc[:, 0] + anc[:, 2]) / 2
        acy = (anc[:, 1] + anc[:, 3]) / 2
        loc = lp.reshape(b, n, 4)
        cx = loc[..., 0] * v[0] * aw + acx
        cy = loc[..., 1] * v[1] * ah + acy
        w = jnp.exp(loc[..., 2] * v[2]) * aw
        h = jnp.exp(loc[..., 3] * v[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          -1)                     # (B, N, 4)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor (reference picks argmax)
        scores_all = jnp.moveaxis(cp, 1, 2)       # (B, N, C+1)
        fg = jnp.concatenate([scores_all[..., :background_id],
                              scores_all[..., background_id + 1:]], -1)
        cls_id = jnp.argmax(fg, axis=-1).astype(jnp.float32)
        score = jnp.max(fg, axis=-1)
        keep = score > threshold
        rows = jnp.concatenate(
            [jnp.where(keep, cls_id, -1.0)[..., None],
             jnp.where(keep, score, -1.0)[..., None], boxes], -1)
        return rows

    raw = invoke_raw("MultiBoxDetection_decode", fn,
                     [cls_prob, loc_pred, anchor])
    return box_nms(raw, overlap_thresh=nms_threshold, valid_thresh=threshold,
                   topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                   force_suppress=force_suppress)


# ---------------------------------------------------------------------------
# misc contrib ops (reference src/operator/contrib/: quadratic_op.cc,
# gradient_multiplier_op.cc, allclose_op.cc, index_copy.cc, index_array.cc,
# boolean_mask.cc, hawkes_ll.cc, dgl_graph.cc, krprod.cc)
# ---------------------------------------------------------------------------

__all__ += ["quadratic", "gradientmultiplier", "allclose", "index_copy",
            "index_array", "boolean_mask", "arange_like", "getnnz",
            "edge_id", "dgl_adjacency", "dgl_csr_neighbor_uniform_sample",
            "hawkes_ll"]


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """f(x) = a x^2 + b x + c (reference contrib/quadratic_op.cc — the
    tutorial op; kept for example parity)."""
    return invoke_raw("quadratic",
                      lambda x: a * x * x + b * x + c, _wrap([data]))


def gradientmultiplier(data, scalar=1.0):
    """Identity forward, gradient scaled by ``scalar`` on backward
    (reference contrib/gradient_multiplier_op.cc — gradient-reversal trick
    when scalar < 0)."""
    import jax

    @jax.custom_vjp
    def _gm(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, ct):
        return (ct * scalar,)

    _gm.defvjp(fwd, bwd)
    return invoke_raw("gradientmultiplier", _gm, _wrap([data]))


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    """1.0 if all elements match within tolerance, else 0.0 (reference
    contrib/allclose_op.cc returns a scalar 0/1 tensor)."""
    return invoke_raw(
        "allclose",
        lambda x, y: jnp.allclose(x, y, rtol=rtol, atol=atol,
                                  equal_nan=equal_nan).astype(jnp.float32),
        _wrap([a, b]))


def index_copy(old, index_vector, new_tensor):
    """Copy rows of ``new_tensor`` into ``old`` at positions
    ``index_vector`` (reference contrib/index_copy.cc; functional — returns
    the updated tensor)."""
    return invoke_raw(
        "index_copy",
        lambda o, i, n: o.at[i.astype(jnp.int32)].set(n),
        _wrap([old, index_vector, new_tensor]))


def index_array(data, axes=None):
    """(d1..dn) -> (d1..dn, m) index mesh (reference
    contrib/index_array.cc; see its describe block for semantics)."""
    axes_t = tuple(axes) if axes is not None else None

    def fn(x):
        nd_ = x.ndim
        sel = axes_t if axes_t is not None else tuple(range(nd_))
        comps = []
        for ax in sel:
            ax = ax % nd_
            shape = [1] * nd_
            shape[ax] = x.shape[ax]
            comp = jnp.arange(x.shape[ax], dtype=jnp.int64).reshape(shape)
            comps.append(jnp.broadcast_to(comp, x.shape))
        return jnp.stack(comps, axis=-1)

    return invoke_raw("index_array", fn, _wrap([data]))


def boolean_mask(data, index, axis=0):
    """Select slices where ``index`` is nonzero (reference
    contrib/boolean_mask.cc). Output shape is data-dependent, so this is an
    EAGER-only op (the reference computes it with a host-synchronized
    prefix-sum too); inside jit use ``jnp.where``-style masking."""
    import jax
    d, i = _datas(_wrap([data, index]))
    if isinstance(d, jax.core.Tracer) or isinstance(i, jax.core.Tracer):
        raise MXNetError("boolean_mask has a data-dependent output shape "
                         "and cannot run inside jit; mask with where()")
    keep = onp.nonzero(onp.asarray(i))[0]
    from .ndarray import NDArray
    return NDArray(jnp.take(d, jnp.asarray(keep, jnp.int32), axis=axis))


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """arange shaped like ``data`` (reference contrib arange_like)."""
    def fn(x):
        if axis is None:
            n = x.size
            out = start + step * jnp.floor(
                jnp.arange(n * repeat) / repeat)[:n * repeat]
            return out[:n].reshape(x.shape).astype(x.dtype)
        n = x.shape[axis % x.ndim]
        return (start + step * jnp.floor(
            jnp.arange(n) / repeat)).astype(x.dtype)

    return invoke_raw("arange_like", fn, _wrap([data]))


# ---- graph (dgl) ops: CSR-backed, host-side like the reference's CPU
# sampling kernels (src/operator/contrib/dgl_graph.cc) ----

def getnnz(data, axis=None):
    """Stored-value count of a CSR matrix (reference _contrib_getnnz)."""
    from .sparse import CSRNDArray
    from .ndarray import NDArray
    if not isinstance(data, CSRNDArray):
        raise MXNetError("getnnz expects a CSRNDArray")
    if axis is None:
        return NDArray(jnp.asarray(
            int(data._aux["values"]._data.shape[0]), jnp.int32))
    if axis in (1, -1):
        indptr = data._aux["indptr"]._data
        return NDArray((indptr[1:] - indptr[:-1]).astype(jnp.int32))
    raise MXNetError("getnnz: axis must be None or 1")


def edge_id(data, u, v):
    """For each (u[i], v[i]) return the CSR stored value (edge id) or -1
    when no such edge exists (reference _contrib_edge_id)."""
    from .sparse import CSRNDArray
    from .ndarray import NDArray
    if not isinstance(data, CSRNDArray):
        raise MXNetError("edge_id expects a CSRNDArray")
    uu = onp.asarray((u._data if hasattr(u, "_data") else u)).astype("int64")
    vv = onp.asarray((v._data if hasattr(v, "_data") else v)).astype("int64")
    indptr = onp.asarray(data._aux["indptr"]._data)
    indices = onp.asarray(data._aux["indices"]._data)
    values = onp.asarray(data._aux["values"]._data)
    out = onp.full(uu.shape, -1.0, "float32")
    for i, (a, b) in enumerate(zip(uu, vv)):
        lo, hi = indptr[a], indptr[a + 1]
        cols = indices[lo:hi]
        hit = onp.nonzero(cols == b)[0]
        if hit.size:
            out[i] = values[lo + hit[0]]
    return NDArray(jnp.asarray(out))


def dgl_adjacency(data):
    """CSR graph -> adjacency CSR whose stored values are all 1
    (reference _contrib_dgl_adjacency: float32 data carrying ones)."""
    from .sparse import CSRNDArray, _make_csr
    if not isinstance(data, CSRNDArray):
        raise MXNetError("dgl_adjacency expects a CSRNDArray")
    ones = jnp.ones_like(data._aux["values"]._data, jnp.float32)
    # rebuild the dense mirror from the STRUCTURE (indptr/indices), not the
    # stored values: an explicitly-stored 0 edge value is still an edge
    indptr = onp.asarray(data._aux["indptr"]._data)
    indices = onp.asarray(data._aux["indices"]._data)
    dense = onp.zeros(data._data.shape, "float32")
    for u in range(indptr.shape[0] - 1):
        dense[u, indices[indptr[u]:indptr[u + 1]]] = 1.0
    return _make_csr(jnp.asarray(dense), ones,
                     data._aux["indices"]._data,
                     data._aux["indptr"]._data)


def dgl_csr_neighbor_uniform_sample(csr, seeds, num_hops=1, num_neighbor=2,
                                    max_num_vertices=100, seed=None):
    """Uniform neighbor sampling from a CSR graph (reference
    _contrib_dgl_csr_neighbor_uniform_sample). Host-side like the
    reference's CPU kernel. Returns (sampled_vertex_ids (padded with -1 to
    max_num_vertices, last slot = count), sub-CSR with the sampled edges)."""
    from .sparse import CSRNDArray, _make_csr
    from .ndarray import NDArray
    if not isinstance(csr, CSRNDArray):
        raise MXNetError("neighbor sampling expects a CSRNDArray")
    rng = onp.random.RandomState(seed)
    indptr = onp.asarray(csr._aux["indptr"]._data)
    indices = onp.asarray(csr._aux["indices"]._data)
    values = onp.asarray(csr._aux["values"]._data)
    n = indptr.shape[0] - 1
    seed_ids = onp.asarray(seeds._data if hasattr(seeds, "_data")
                           else seeds).astype("int64").reshape(-1)
    # the last ids slot carries the count, so at most max-1 vertices fit —
    # bound the seed set itself, not just hop-added vertices
    visited = list(dict.fromkeys(seed_ids.tolist()))[:max_num_vertices - 1]
    frontier = list(visited)
    picked = {}  # (u, pos) -> True for chosen edges
    for _ in range(num_hops):
        nxt = []
        for u in frontier:
            lo, hi = int(indptr[u]), int(indptr[u + 1])
            deg = hi - lo
            if deg == 0:
                continue
            k = min(num_neighbor, deg)
            for pos in rng.choice(deg, size=k, replace=False):
                picked[(u, lo + int(pos))] = True
                vtx = int(indices[lo + int(pos)])
                if vtx not in visited and \
                        len(visited) < max_num_vertices - 1:
                    visited.append(vtx)
                    nxt.append(vtx)
        frontier = nxt
    # sub-CSR over the ORIGINAL vertex numbering, keeping sampled edges
    sub_indptr = [0]
    sub_indices, sub_values = [], []
    for u in range(n):
        lo, hi = int(indptr[u]), int(indptr[u + 1])
        for e in range(lo, hi):
            if (u, e) in picked:
                sub_indices.append(int(indices[e]))
                sub_values.append(float(values[e]))
        sub_indptr.append(len(sub_indices))
    ids = onp.full((max_num_vertices,), -1, "int64")
    ids[:len(visited)] = onp.asarray(visited, "int64")
    ids[-1] = len(visited)  # reference convention: count rides the tail
    dense = onp.zeros(csr._data.shape, "float32")
    for u in range(n):
        for j in range(sub_indptr[u], sub_indptr[u + 1]):
            dense[u, sub_indices[j]] = sub_values[j]
    sub = _make_csr(jnp.asarray(dense),
                    jnp.asarray(onp.asarray(sub_values, "float32")),
                    jnp.asarray(onp.asarray(sub_indices, "int32")),
                    jnp.asarray(onp.asarray(sub_indptr, "int32")))
    return NDArray(jnp.asarray(ids)), sub


def hawkes_ll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Log likelihood of K independent univariate Hawkes processes with
    exponential kernels (reference contrib/hawkes_ll.cc — see its describe
    block for the intensity definition). Inputs: lda (N,K) background
    rates, alpha (K,) branching ratios, beta (K,) decay rates, state (N,K)
    prior memory s_k(0), lags/marks (N,T) left-aligned ragged sequences,
    valid_length (N,), max_time (N,). Returns (log-likelihood (N,),
    end-state s_k(T) (N,K)). One lax.scan over T — fully differentiable."""
    from jax import lax as _lax

    def fn(lda_, alpha_, beta_, state_, lags_, marks_, vl_, mt_):
        n, k = lda_.shape
        t_steps = lags_.shape[1]
        marks_i = marks_.astype(jnp.int32)

        def step(carry, inp):
            s, t_cur, ll, idx, cnt = carry
            lag, mark = inp                          # (N,), (N,)
            valid = (idx < vl_).astype(lda_.dtype)   # (N,)
            t_new = t_cur + lag
            decay = jnp.exp(-beta_[None, :] * lag[:, None])
            s_dec = s * decay
            mark_oh = jax.nn.one_hot(mark, k, dtype=lda_.dtype)
            lam = lda_ + alpha_[None, :] * beta_[None, :] * s_dec
            lam_m = jnp.sum(lam * mark_oh, axis=1)
            ll = ll + valid * jnp.log(jnp.maximum(lam_m, 1e-30))
            s_new = s_dec + mark_oh * valid[:, None]
            # only advance on valid points
            s_out = jnp.where(valid[:, None] > 0, s_new, s)
            t_out = jnp.where(valid > 0, t_new, t_cur)
            cnt = cnt + mark_oh * valid[:, None]
            return (s_out, t_out, ll, idx + 1, cnt), None

        init = (state_, jnp.zeros((n,), lda_.dtype),
                jnp.zeros((n,), lda_.dtype),
                jnp.zeros((n,), jnp.int32),
                jnp.zeros((n, k), lda_.dtype))
        (s_end, t_end, ll, _, cnt), _ = _lax.scan(
            step, init, (lags_.T, marks_i.T), length=t_steps)
        # s at the end of the observation window
        s_at_T = s_end * jnp.exp(-beta_[None, :]
                                 * (mt_[:, None] - t_end[:, None]))
        # compensator over (0, max_time]:
        #   ∫λ_k = λ_k T + α_k [Σ_i 1{y_i=k}(1 - e^{-β_k(T-t_i)})
        #                       + s_k(0)(1 - e^{-β_k T})]
        # and Σ_i e^{-β(T-t_i)} + s_0 e^{-β T} == s_at_T, so the bracket
        # collapses to count_k + s_k(0) - s_k(T)
        comp_bg = jnp.sum(lda_, axis=1) * mt_
        comp_exc = jnp.sum(alpha_[None, :] * (cnt + state_ - s_at_T),
                           axis=1)
        return ll - comp_bg - comp_exc, s_at_T

    return invoke_raw("hawkes_ll", fn,
                      _wrap([lda, alpha, beta, state, lags, marks,
                             valid_length, max_time]), n_outputs=2)


# ---------------------------------------------------------------------------
# AdamW update ops + candidate sampling + float checks
# (reference python/mxnet/ndarray/contrib.py adamw_update :556,
#  rand_zipfian :39, isinf/isfinite/isnan :469-524;
#  kernels src/operator/contrib/adamw.cc)
# ---------------------------------------------------------------------------

__all__ += ["adamw_update", "mp_adamw_update", "multi_adamw_update",
            "rand_zipfian", "isinf", "isfinite", "isnan"]


def _require_state_handles(**named):
    """The adamw ops mutate their state arguments in place; a raw jax/numpy
    array would silently receive the update on a throwaway wrapper."""
    for nm, a in named.items():
        if not isinstance(a, NDArray):
            raise MXNetError(
                f"adamw_update: {nm} must be an NDArray handle (its update "
                f"is written in place, reference stateful kernel "
                f"contrib/adamw.cc); got {type(a).__name__}")


def adamw_update(weight, grad, mean, var, rescale_grad, lr, eta, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, wd=0.0, clip_gradient=-1,
                 out=None, **_ignored):
    """AdamW with DECOUPLED weight decay (reference contrib/adamw.cc):
    w -= eta * (lr * m/(sqrt(v)+eps) + wd * w) — NO bias correction, same
    as the reference kernel (callers fold the correction into lr/eta).
    Updates mean/var in place like the reference's stateful kernel; returns
    the new weight (written to ``out``/``weight``)."""
    _require_state_handles(weight=weight, mean=mean, var=var)
    weight, grad, mean, var = _wrap([weight, grad, mean, var])
    rg = rescale_grad._data if hasattr(rescale_grad, "_data") \
        else jnp.asarray(rescale_grad)

    def fn(w, g, m, v):
        g = g * rg.reshape(()).astype(w.dtype)
        if clip_gradient is not None and clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * g * g
        upd = lr * m_new / (jnp.sqrt(v_new) + epsilon) + wd * w
        return w - eta * upd, m_new, v_new

    new_w, new_m, new_v = invoke_raw("adamw_update", fn,
                                     [weight, grad, mean, var], n_outputs=3)
    mean._data = new_m._data
    var._data = new_v._data
    target = out if out is not None else weight
    target._data = new_w._data
    return target


def mp_adamw_update(weight, grad, mean, var, weight32, rescale_grad, lr,
                    eta, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
                    clip_gradient=-1, out=None, **_ignored):
    """Mixed-precision AdamW: master fp32 weights carry the update, the
    low-precision weight is the cast-down copy (reference mp_adamw_update)."""
    _require_state_handles(weight=weight, weight32=weight32)
    new32 = adamw_update(weight32, grad, mean, var, rescale_grad, lr, eta,
                         beta1, beta2, epsilon, wd, clip_gradient)
    target = out if out is not None else weight
    target._data = new32._data.astype(weight._data.dtype)
    return target


def multi_adamw_update(weights, grads, means, varrs, rescale_grad, lrs,
                       wds, etas, beta1=0.9, beta2=0.999, epsilon=1e-8,
                       clip_gradient=-1, out=None, **_ignored):
    """Fused multi-tensor AdamW (reference multi_adamw_update,
    src/operator/contrib/adamw.cc multi_*): ALL parameter updates run as
    ONE dispatched computation — one invoke instead of one per parameter,
    the same single-program shape as Optimizer._jitted_multi."""
    n = len(weights)
    for group, nm in ((weights, "weights"), (means, "means"),
                      (varrs, "vars")):
        for a in group:
            _require_state_handles(**{nm: a})
    ws, gs = _wrap(list(weights)), _wrap(list(grads))
    ms, vs = _wrap(list(means)), _wrap(list(varrs))
    rg = rescale_grad._data if hasattr(rescale_grad, "_data") \
        else jnp.asarray(rescale_grad)

    def fn(*arrs):
        ws_, gs_ = arrs[:n], arrs[n:2 * n]
        ms_, vs_ = arrs[2 * n:3 * n], arrs[3 * n:4 * n]
        new_w, new_m, new_v = [], [], []
        for i in range(n):
            g = gs_[i] * rg.reshape(()).astype(ws_[i].dtype)
            if clip_gradient is not None and clip_gradient > 0:
                g = jnp.clip(g, -clip_gradient, clip_gradient)
            m = beta1 * ms_[i] + (1 - beta1) * g
            v = beta2 * vs_[i] + (1 - beta2) * g * g
            upd = lrs[i] * m / (jnp.sqrt(v) + epsilon) + wds[i] * ws_[i]
            new_w.append(ws_[i] - etas[i] * upd)
            new_m.append(m)
            new_v.append(v)
        return tuple(new_w) + tuple(new_m) + tuple(new_v)

    res = invoke_raw("multi_adamw_update", fn, ws + gs + ms + vs,
                     n_outputs=3 * n)
    outs = []
    for i in range(n):
        ms[i]._data = res[n + i]._data
        vs[i]._data = res[2 * n + i]._data
        target = out[i] if out is not None else ws[i]
        target._data = res[i]._data
        outs.append(target)
    return outs


def rand_zipfian(true_classes, num_sampled, range_max, ctx=None):
    """Log-uniform (Zipfian) candidate sampler (reference contrib.py:39):
    returns (sampled_candidates (num_sampled,), expected_count_true,
    expected_count_sampled)."""
    from .random import uniform as nd_uniform
    from .ndarray import NDArray
    log_range = float(onp.log(range_max + 1))
    rand = nd_uniform(0, log_range, shape=(num_sampled,))
    sampled = (jnp.exp(rand._data.astype(jnp.float32)) - 1.0) \
        .astype(jnp.int32) % range_max
    tc = (true_classes._data if hasattr(true_classes, "_data")
          else jnp.asarray(true_classes)).astype(jnp.float32)
    exp_true = jnp.log((tc + 2.0) / (tc + 1.0)) / log_range * num_sampled
    sc = sampled.astype(jnp.float32)
    exp_sampled = jnp.log((sc + 2.0) / (sc + 1.0)) / log_range * num_sampled
    return NDArray(sampled), NDArray(exp_true), NDArray(exp_sampled)


def isinf(data):
    return invoke_raw("isinf", lambda x: jnp.isinf(x).astype(jnp.float32),
                      _wrap([data]))


def isfinite(data):
    return invoke_raw("isfinite",
                      lambda x: jnp.isfinite(x).astype(jnp.float32),
                      _wrap([data]))


def isnan(data):
    return invoke_raw("isnan", lambda x: jnp.isnan(x).astype(jnp.float32),
                      _wrap([data]))


def BilinearResize2D(data, **kwargs):
    """Reference contrib.BilinearResize2D (alias of the nn op)."""
    from .nn_ops import BilinearResize2D as _br
    return _br(data, **kwargs)


__all__ += ["BilinearResize2D"]
