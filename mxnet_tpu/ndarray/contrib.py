"""Control-flow operators: foreach / while_loop / cond.

Reference analog: src/operator/control_flow.cc (`_foreach` :1094,
`_while_loop` :1155, `_cond` :1216) — subgraph-holding stateful ops with full
backward, exposed as ``mx.nd.contrib.*`` (python/mxnet/ndarray/contrib.py).

TPU-native design: the body/cond/branch callables trace into ``lax.scan`` /
``lax.cond`` — XLA compiles the body ONCE regardless of trip count (the
reference re-executes the subgraph per step through the engine). while_loop
lowers to a masked fixed-trip ``lax.scan`` rather than ``lax.while_loop``:
scan is reverse-differentiable and maps to a static TPU program; the mask
reproduces data-dependent termination. All three integrate with autograd via
the op-invoke funnel, so gradients flow through loop bodies and branches.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import _tape, autograd
from ..base import MXNetError
from ..ops.registry import invoke_raw
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x) -> Tuple[List, bool]:
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False


def _wrap(arrs):
    return [NDArray(a) if not isinstance(a, NDArray) else a for a in arrs]


def _datas(arrs):
    return [a._data if isinstance(a, NDArray) else a for a in arrs]


def _call_sub(fn, *nd_args):
    """Run a user subgraph callable with recording off (the whole control-flow
    op records as ONE tape node; jax.vjp differentiates through the body)."""
    prev = _tape.set_recording(False)
    try:
        return fn(*nd_args)
    finally:
        _tape.set_recording(prev)


def foreach(body, data, init_states):
    """Scan ``body`` over the leading axis of ``data``
    (reference _foreach, control_flow.cc:1094; python frontend
    python/mxnet/ndarray/contrib.py foreach).

    body(step_data, states) -> (outputs, new_states). Returns
    (stacked_outputs, final_states) with input list/single structure
    preserved.
    """
    data_list, data_is_list = _as_list(data)
    states, states_is_list = _as_list(init_states)
    n_d, n_s = len(data_list), len(states)

    # probe the body once to learn the output structure (the reference infers
    # the same from the traced subgraph)
    step0 = [d.take(0, axis=0) for d in data_list]
    with autograd.pause():
        probe_out, probe_states = _call_sub(
            body,
            step0 if data_is_list else step0[0],
            list(states) if states_is_list else states[0])
    probe_outs, out_is_list = _as_list(probe_out)
    probe_new_states, _ = _as_list(probe_states)
    if len(probe_new_states) != n_s:
        raise MXNetError("foreach body must return the same number of states")
    n_o = len(probe_outs)

    def fn(*arrs):
        xs = arrs[:n_d]
        st = list(arrs[n_d:])

        def step(carry, x_t):
            d_nd = _wrap(list(x_t))
            s_nd = _wrap(list(carry))
            out, new_st = _call_sub(
                body,
                d_nd if data_is_list else d_nd[0],
                s_nd if states_is_list else s_nd[0])
            outs, _ = _as_list(out)
            new_states, _ = _as_list(new_st)
            return tuple(_datas(new_states)), tuple(_datas(outs))

        carry, ys = lax.scan(step, tuple(st), tuple(xs))
        return tuple(ys) + tuple(carry)

    res = invoke_raw("_foreach", fn, data_list + states,
                     n_outputs=n_o + n_s)
    res = res if isinstance(res, tuple) else (res,)
    outs = list(res[:n_o])
    fin = list(res[n_o:])
    return (outs if out_is_list else outs[0],
            fin if states_is_list else fin[0])


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Differentiable while (reference _while_loop, control_flow.cc:1155).

    cond(*loop_vars) -> boolean scalar; func(*loop_vars) ->
    (step_output, new_loop_vars). Returns (stacked_outputs, final_loop_vars);
    outputs rows beyond termination are zero (the reference leaves them
    undefined). Lowered as a masked fixed-trip lax.scan: reverse-mode
    differentiable and a static TPU program, unlike lax.while_loop.
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    loop_list, vars_is_list = _as_list(loop_vars)
    n_v = len(loop_list)

    with autograd.pause():
        probe_out, probe_vars = _call_sub(func, *loop_list)
    probe_outs, out_is_list = _as_list(probe_out)
    probe_new_vars, _ = _as_list(probe_vars)
    if len(probe_new_vars) != n_v:
        raise MXNetError("while_loop func must preserve loop_vars arity")
    n_o = len(probe_outs)

    def fn(*arrs):
        def step(carry, _):
            active, vs = carry
            vs_nd = _wrap(list(vs))
            c = _call_sub(cond, *vs_nd)
            c = (c._data if isinstance(c, NDArray) else c).reshape(())
            active = jnp.logical_and(active, c.astype(bool))
            out, new_vs = _call_sub(func, *vs_nd)
            outs = _datas(_as_list(out)[0])
            new_vs = _datas(_as_list(new_vs)[0])
            sel = lambda n, o: jnp.where(
                active.reshape((1,) * n.ndim), n, o)
            kept = tuple(sel(n, o) for n, o in zip(new_vs, vs))
            step_out = tuple(jnp.where(active.reshape((1,) * o.ndim), o,
                                       jnp.zeros_like(o)) for o in outs)
            return (active, kept), step_out

        init = (jnp.asarray(True), tuple(arrs))
        (_, final), ys = lax.scan(step, init, None, length=max_iterations)
        return tuple(ys) + tuple(final)

    res = invoke_raw("_while_loop", fn, loop_list, n_outputs=n_o + n_v)
    res = res if isinstance(res, tuple) else (res,)
    outs = list(res[:n_o])
    fin = list(res[n_o:])
    return (outs if out_is_list else outs[0],
            fin if vars_is_list else fin[0])


def cond(pred, then_func, else_func, inputs=None):
    """Two-branch conditional (reference _cond, control_flow.cc:1216).

    pred: boolean scalar NDArray (or a callable over ``inputs``); both
    branches must return the same structure. Lowers to ``lax.cond`` — only
    the taken branch executes on device.
    """
    ins, ins_is_list = _as_list(inputs if inputs is not None else [])

    if callable(pred):
        with autograd.pause():
            pred = _call_sub(pred, *ins)
    with autograd.pause():
        probe = _call_sub(then_func, *ins) if callable(then_func) else None
    probe_outs, out_is_list = _as_list(probe)
    n_o = len(probe_outs)

    def fn(p, *arrs):
        def run(branch):
            def f(xs):
                out = _call_sub(branch, *_wrap(list(xs)))
                return tuple(_datas(_as_list(out)[0]))
            return f

        return lax.cond(p.reshape(()).astype(bool),
                        run(then_func), run(else_func), tuple(arrs))

    res = invoke_raw("_cond", fn, [pred] + ins, n_outputs=n_o)
    res = list(res) if isinstance(res, tuple) else [res]
    return res if out_is_list else res[0]
