"""Grid-sampling / detection / correlation operator family.

Reference analogs (all CUDA/C++ there, pure-XLA here):
- BilinearSampler        src/operator/bilinear_sampler.cc
- GridGenerator          src/operator/grid_generator.cc
- SpatialTransformer     src/operator/spatial_transformer.cc
- DeformableConvolution  src/operator/contrib/deformable_convolution.cc
  (offset-channel layout per deformable_im2col.h:239-243: for deformable
  group g and kernel tap k=(i*kw+j), channel 2k is the ROW offset map and
  2k+1 the COLUMN offset map)
- DeformablePSROIPooling src/operator/contrib/deformable_psroi_pooling.cc
- Proposal               src/operator/contrib/proposal.cc
- Correlation            src/operator/correlation-inl.h:98-116
- CountSketch            src/operator/contrib/count_sketch.cc
- SyncBatchNorm          src/operator/contrib/sync_batch_norm.cc

TPU-native design: ONE shared differentiable bilinear-grid kernel
(`_grid_sample`) backs the sampler family — each op is a coordinate
transform plus that kernel, and XLA fuses the gathers. All kernel taps /
displacement loops are static Python loops over small constant ranges, so
everything stays a single fused XLA computation (no dynamic shapes).
SyncBatchNorm is the degenerate case: one mesh-sharded logical batch is
already globally normalized, with an optional `axis_name` for explicit
shard_map code.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from ..ops.registry import invoke_raw
from .ndarray import NDArray

__all__ = ["BilinearSampler", "GridGenerator", "SpatialTransformer",
           "DeformableConvolution", "ModulatedDeformableConvolution",
           "DeformablePSROIPooling", "Proposal",
           "MultiProposal", "Correlation", "count_sketch", "SyncBatchNorm"]


def _wrap(x):
    return x if isinstance(x, NDArray) else NDArray(x)


# ---------------------------------------------------------------------------
# shared bilinear-grid kernel
# ---------------------------------------------------------------------------

def _grid_sample(data: jax.Array, ys: jax.Array, xs: jax.Array) -> jax.Array:
    """Sample ``data`` (B, C, H, W) at fractional pixel coords ``ys``/``xs``
    (B, *S), zero-padded outside the image (reference bilinear_sampler.cc /
    deformable_im2col.h boundary semantics). Returns (B, C, *S).

    Differentiable wrt data AND coords; the 4 corner gathers vectorize to
    XLA gathers that fuse with the weighting arithmetic.
    """
    B, C, H, W = data.shape
    sshape = ys.shape[1:]
    ys = ys.reshape(B, -1)
    xs = xs.reshape(B, -1)

    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    wy0 = 1.0 - wy1
    wx0 = 1.0 - wx1

    def corner(yi, xi, wy, wx):
        valid = (yi >= 0) & (yi <= H - 1) & (xi >= 0) & (xi <= W - 1)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        # (B, C, N) gather of per-batch pixel lists
        flat = data.reshape(B, C, H * W)
        idx = yc * W + xc                              # (B, N)
        vals = jnp.take_along_axis(flat, idx[:, None, :], axis=2)
        w = (wy * wx * valid.astype(data.dtype))[:, None, :]
        return vals * w

    out = (corner(y0, x0, wy0, wx0) + corner(y0, x0 + 1, wy0, wx1)
           + corner(y0 + 1, x0, wy1, wx0) + corner(y0 + 1, x0 + 1, wy1, wx1))
    return out.reshape((B, C) + sshape)


# ---------------------------------------------------------------------------
# sampler family
# ---------------------------------------------------------------------------

def BilinearSampler(data, grid, **_ignored):
    """``out[b,c,i,j] = G(data[b,c], grid[b,1,i,j], grid[b,0,i,j])`` with
    grid in [-1, 1] (reference bilinear_sampler.cc: -1 ↦ pixel 0,
    +1 ↦ pixel H-1/W-1; outside ↦ 0)."""
    data, grid = _wrap(data), _wrap(grid)

    def fn(d, g):
        H, W = d.shape[2], d.shape[3]
        xs = (g[:, 0] + 1.0) * (W - 1) / 2.0
        ys = (g[:, 1] + 1.0) * (H - 1) / 2.0
        return _grid_sample(d, ys, xs)

    return invoke_raw("BilinearSampler", fn, [data, grid])


def GridGenerator(data, transform_type: str = "affine",
                  target_shape: Optional[Sequence[int]] = None, **_ignored):
    """Generate a sampling grid (B, 2, H, W) with channel 0 = x, 1 = y in
    [-1, 1] (reference grid_generator.cc). 'affine': data (B, 6) row-major
    2x3 matrices applied to the regular target grid. 'warp': data (B,2,H,W)
    optical flow in pixels added to the regular grid then normalized."""
    data = _wrap(data)
    if transform_type == "affine":
        if target_shape is None:
            raise MXNetError("GridGenerator(affine) needs target_shape")
        H, W = int(target_shape[0]), int(target_shape[1])

        def fn(theta):
            B = theta.shape[0]
            ys, xs = jnp.meshgrid(jnp.linspace(-1.0, 1.0, H),
                                  jnp.linspace(-1.0, 1.0, W), indexing="ij")
            ones = jnp.ones_like(xs)
            src = jnp.stack([xs, ys, ones], 0).reshape(3, -1)  # (3, H*W)
            m = theta.reshape(B, 2, 3)
            out = jnp.einsum("bij,jn->bin", m, src)            # (B, 2, H*W)
            return out.reshape(B, 2, H, W)

        return invoke_raw("GridGenerator", fn, [data])

    if transform_type == "warp":
        def fn(flow):
            B, _, H, W = flow.shape
            ys, xs = jnp.meshgrid(jnp.arange(H, dtype=flow.dtype),
                                  jnp.arange(W, dtype=flow.dtype),
                                  indexing="ij")
            x = (xs[None] + flow[:, 0]) * 2.0 / max(W - 1, 1) - 1.0
            y = (ys[None] + flow[:, 1]) * 2.0 / max(H - 1, 1) - 1.0
            return jnp.stack([x, y], 1)

        return invoke_raw("GridGenerator", fn, [data])
    raise MXNetError(f"unknown transform_type {transform_type!r}")


def SpatialTransformer(data, loc, target_shape=None,
                       transform_type: str = "affine",
                       sampler_type: str = "bilinear", **_ignored):
    """Affine spatial transformer network op (reference
    spatial_transformer.cc): grid-generate from ``loc`` then bilinear-sample
    ``data``."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError("SpatialTransformer supports affine/bilinear")
    grid = GridGenerator(loc, "affine", target_shape)
    return BilinearSampler(data, grid)


# ---------------------------------------------------------------------------
# deformable family
# ---------------------------------------------------------------------------

def DeformableConvolution(data, offset, weight, bias=None, kernel=None,
                          stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                          num_filter=None, num_group: int = 1,
                          num_deformable_group: int = 1, no_bias=False,
                          **_ignored):
    """DCNv1 (reference contrib/deformable_convolution.cc): each kernel tap
    samples the input at a learned fractional offset. Implemented as K
    bilinear grid-samples (one per tap, static loop) building the
    deformable im2col tensor, then one einsum onto the MXU."""
    data, offset, weight = _wrap(data), _wrap(offset), _wrap(weight)
    kh, kw = (int(kernel[0]), int(kernel[1])) if kernel is not None \
        else (int(weight.shape[2]), int(weight.shape[3]))
    sh, sw = int(stride[0]), int(stride[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    ph, pw = int(pad[0]), int(pad[1])
    dg = int(num_deformable_group)

    fn = _make_deformable_fn(kh, kw, sh, sw, dh, dw, ph, pw, dg,
                             num_group, modulated=False)
    args = [data, offset, weight]
    if not no_bias and bias is not None:
        args.append(_wrap(bias))
    return invoke_raw("DeformableConvolution", fn, args)


def ModulatedDeformableConvolution(data, offset, mask, weight, bias=None,
                                   kernel=None, stride=(1, 1),
                                   dilate=(1, 1), pad=(0, 0),
                                   num_filter=None, num_group: int = 1,
                                   num_deformable_group: int = 1,
                                   no_bias=False, **_ignored):
    """DCNv2 (reference contrib/modulated_deformable_convolution.cc):
    v1's offset sampling plus a learned per-tap modulation scalar
    multiplied into each sampled column before the einsum. ``mask`` has
    ``num_deformable_group*kh*kw`` channels ordered like the offset
    pairs (modulated_deformable_im2col.cuh tap layout)."""
    data, offset, mask, weight = (_wrap(data), _wrap(offset), _wrap(mask),
                                  _wrap(weight))
    kh, kw = (int(kernel[0]), int(kernel[1])) if kernel is not None \
        else (int(weight.shape[2]), int(weight.shape[3]))
    fn = _make_deformable_fn(kh, kw, int(stride[0]), int(stride[1]),
                             int(dilate[0]), int(dilate[1]),
                             int(pad[0]), int(pad[1]),
                             int(num_deformable_group), num_group,
                             modulated=True)
    args = [data, offset, mask, weight]
    if not no_bias and bias is not None:
        args.append(_wrap(bias))
    return invoke_raw("ModulatedDeformableConvolution", fn, args)


def _make_deformable_fn(kh, kw, sh, sw, dh, dw, ph, pw, dg, num_group,
                        modulated):
    def fn(x, off, *rest):
        if modulated:
            msk, w = rest[0], rest[1]
            maybe_b = rest[2:]
        else:
            msk, w = None, rest[0]
            maybe_b = rest[1:]
        B, C, H, W = x.shape
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        base_y = jnp.arange(Ho) * sh - ph
        base_x = jnp.arange(Wo) * sw - pw
        gy, gx = jnp.meshgrid(base_y.astype(x.dtype),
                              base_x.astype(x.dtype), indexing="ij")
        cols = []  # K entries of (B, C, Ho, Wo)
        cpg = C // dg  # data channels per deformable group
        for k in range(kh * kw):
            i, j = divmod(k, kw)
            per_g = []
            for g in range(dg):
                oy = off[:, (g * kh * kw + k) * 2]        # (B, Ho, Wo)
                ox = off[:, (g * kh * kw + k) * 2 + 1]
                ys = gy[None] + i * dh + oy
                xs = gx[None] + j * dw + ox
                smp = _grid_sample(x[:, g * cpg:(g + 1) * cpg], ys, xs)
                if msk is not None:
                    smp = smp * msk[:, g * kh * kw + k][:, None]
                per_g.append(smp)
            cols.append(jnp.concatenate(per_g, axis=1) if dg > 1
                        else per_g[0])
        col = jnp.stack(cols, axis=2)                     # (B, C, K, Ho, Wo)
        O = w.shape[0]
        cg = C // num_group
        og = O // num_group
        col = col.reshape(B, num_group, cg, kh * kw, Ho, Wo)
        wg = w.reshape(num_group, og, cg, kh * kw)
        out = jnp.einsum("bgckn,gock->bgon",
                         col.reshape(B, num_group, cg, kh * kw, Ho * Wo), wg)
        out = out.reshape(B, O, Ho, Wo)
        if maybe_b:
            out = out + maybe_b[0].reshape(1, -1, 1, 1)
        return out

    return fn


def DeformablePSROIPooling(data, rois, trans=None, spatial_scale=1.0,
                           output_dim=None, group_size=1, pooled_size=7,
                           part_size=0, sample_per_part=1, trans_std=0.0,
                           no_trans=False, **_ignored):
    """Deformable position-sensitive ROI pooling (reference
    contrib/deformable_psroi_pooling.cc). data channels =
    output_dim * group_size^2; each pooled bin (ph, pw) averages
    sample_per_part^2 bilinear samples from its position-sensitive channel
    group, displaced by the learned normalized offsets in ``trans``."""
    data, rois = _wrap(data), _wrap(rois)
    P = int(pooled_size)
    G = int(group_size)
    part = int(part_size) if part_size else P
    spp = int(sample_per_part)
    out_dim = int(output_dim) if output_dim else data.shape[1] // (G * G)

    def fn(x, r, *maybe_t):
        B, C, H, W = x.shape
        R = r.shape[0]
        batch_idx = r[:, 0].astype(jnp.int32)
        # rois scaled to feature coords; +pixel rounding per reference
        x1 = jnp.round(r[:, 1]) * spatial_scale - 0.5
        y1 = jnp.round(r[:, 2]) * spatial_scale - 0.5
        x2 = (jnp.round(r[:, 3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(r[:, 4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w = rw / P                                     # (R,)
        bin_h = rh / P
        sub_w = bin_w / spp
        sub_h = bin_h / spp

        ph = jnp.arange(P)
        pw = jnp.arange(P)
        gph, gpw = jnp.meshgrid(ph, pw, indexing="ij")     # (P, P)

        if maybe_t and not no_trans:
            t = maybe_t[0]                                 # (R, 2*cls, part, part)
            cls = t.shape[1] // 2
            pidx_h = jnp.clip((gph * part) // P, 0, part - 1)
            pidx_w = jnp.clip((gpw * part) // P, 0, part - 1)
            # class-0 offsets; reference layout is x at channel 2*cls,
            # y at 2*cls+1 (deformable_psroi_pooling.cu:110-118) — NOT the
            # row-first order the deformable-conv offsets use
            dx = t[:, 0, pidx_h, pidx_w] * trans_std       # (R, P, P)
            dy = t[:, 1, pidx_h, pidx_w] * trans_std
        else:
            dy = jnp.zeros((R, P, P), x.dtype)
            dx = jnp.zeros((R, P, P), x.dtype)

        # sample grid per bin: (R, P, P, spp, spp)
        s = (jnp.arange(spp, dtype=x.dtype) + 0.5)
        ys = (y1[:, None, None] + gph[None] * bin_h[:, None, None]
              + dy * rh[:, None, None])[..., None, None] \
            + s[None, None, None, :, None] * sub_h[:, None, None, None, None]
        xs = (x1[:, None, None] + gpw[None] * bin_w[:, None, None]
              + dx * rw[:, None, None])[..., None, None] \
            + s[None, None, None, None, :] * sub_w[:, None, None, None, None]

        # gather each roi's source image: (R, C, H, W)
        src = x[batch_idx]
        samp = _grid_sample(src, ys, xs)   # (R, C, P, P, spp, spp)
        samp = samp.mean(axis=(-2, -1))    # (R, C, P, P)
        # position-sensitive channel select: channel block depends on bin
        samp = samp.reshape(R, out_dim, G, G, P, P)
        gh = jnp.clip((gph * G) // P, 0, G - 1)            # (P, P)
        gw = jnp.clip((gpw * G) // P, 0, G - 1)
        out = samp[:, :, gh, gw, gph, gpw]                 # (R, out_dim, P, P)
        return out

    args = [data, rois]
    if trans is not None and not no_trans:
        args.append(_wrap(trans))
    return invoke_raw("DeformablePSROIPooling", fn, args)


# ---------------------------------------------------------------------------
# proposal (RPN)
# ---------------------------------------------------------------------------

def _make_anchors(base_size, scales, ratios):
    """Anchor windows centered on a base_size cell (reference
    contrib/proposal.cc GenerateAnchors semantics)."""
    import numpy as onp
    px = (base_size - 1) * 0.5
    anchors = []
    for r in ratios:
        size = base_size * base_size / r
        ws = onp.round(onp.sqrt(size))
        hs = onp.round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            anchors.append([px - 0.5 * (w - 1), px - 0.5 * (h - 1),
                            px + 0.5 * (w - 1), px + 0.5 * (h - 1)])
    return onp.array(anchors, dtype="float32")


def Proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False,
             **_ignored):
    """RPN proposal op (reference contrib/proposal.cc): decode anchor
    deltas, clip to image, drop small boxes, take pre-NMS top-K by score,
    greedy-NMS, pad to post-NMS count. Output (B*post_nms, 5):
    [batch_idx, x1, y1, x2, y2] (+ scores when output_score)."""
    from .contrib import box_nms
    cls_prob, bbox_pred, im_info = \
        _wrap(cls_prob), _wrap(bbox_pred), _wrap(im_info)
    anchors_base = _make_anchors(feature_stride, scales, ratios)
    A = anchors_base.shape[0]
    pre_n = int(rpn_pre_nms_top_n)
    post_n = int(rpn_post_nms_top_n)

    def fn(cp, bp, info):
        B, _, H, W = cp.shape
        shift_y = jnp.arange(H, dtype=jnp.float32) * feature_stride
        shift_x = jnp.arange(W, dtype=jnp.float32) * feature_stride
        sy, sx = jnp.meshgrid(shift_y, shift_x, indexing="ij")
        shifts = jnp.stack([sx, sy, sx, sy], -1).reshape(-1, 1, 4)
        anc = (jnp.asarray(anchors_base)[None] + shifts).reshape(-1, 4)
        N = anc.shape[0]                                  # H*W*A

        # deltas (B, 4A, H, W) -> (B, N, 4) matching anchor order (h,w,a)
        d = bp.reshape(B, A, 4, H, W).transpose(0, 3, 4, 1, 2).reshape(B, N, 4)
        scores = cp[:, A:].reshape(B, A, H, W) \
            .transpose(0, 2, 3, 1).reshape(B, N)          # fg scores

        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + 0.5 * (aw - 1)
        acy = anc[:, 1] + 0.5 * (ah - 1)
        cx = d[..., 0] * aw + acx
        cy = d[..., 1] * ah + acy
        w = jnp.exp(jnp.clip(d[..., 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(d[..., 3], -10, 10)) * ah
        x1 = cx - 0.5 * (w - 1)
        y1 = cy - 0.5 * (h - 1)
        x2 = cx + 0.5 * (w - 1)
        y2 = cy + 0.5 * (h - 1)
        # clip to image (im_info rows: [height, width, scale])
        imh = info[:, 0][:, None]
        imw = info[:, 1][:, None]
        x1 = jnp.clip(x1, 0, imw - 1.0)
        y1 = jnp.clip(y1, 0, imh - 1.0)
        x2 = jnp.clip(x2, 0, imw - 1.0)
        y2 = jnp.clip(y2, 0, imh - 1.0)
        # min-size filter (scaled by im scale)
        min_sz = rpn_min_size * info[:, 2][:, None]
        keep = ((x2 - x1 + 1.0) >= min_sz) & ((y2 - y1 + 1.0) >= min_sz)
        scores_f = jnp.where(keep, scores, -1.0)

        k = min(pre_n, N)
        top_scores, top_idx = lax.top_k(scores_f, k)
        def take(v):
            return jnp.take_along_axis(v, top_idx, axis=1)
        rows = jnp.stack([jnp.zeros_like(top_scores), top_scores,
                          take(x1), take(y1), take(x2), take(y2)], -1)
        return rows                                       # (B, k, 6)

    rows = invoke_raw("Proposal_decode", fn, [cls_prob, bbox_pred, im_info])
    # NMS over the ENTIRE pre-NMS pool (topk=-1): survivors beyond rank
    # post_n must backfill suppressed slots, as the reference does
    # (proposal.cc keeps the top post_nms_top_n SURVIVORS of the 6000-box
    # pool, not the survivors among the top 300)
    kept = box_nms(rows, overlap_thresh=threshold, valid_thresh=0.0,
                   topk=-1, coord_start=2, score_index=1, id_index=0,
                   force_suppress=True)

    def pick(kr):
        B = kr.shape[0]
        # box_nms output is score-sorted with suppressed rows all -1;
        # stable-compact survivors to the front (preserving score order)
        survd = kr[..., 0] >= 0
        order = jnp.argsort(jnp.where(survd, 0, 1), axis=1, stable=True)
        kr = jnp.take_along_axis(kr, order[..., None], 1)
        if kr.shape[1] < post_n:   # fewer anchors than post-NMS count
            kr = jnp.pad(kr, ((0, 0), (0, post_n - kr.shape[1]), (0, 0)),
                         constant_values=-1.0)
        out = kr[:, :post_n, :]                           # (B, post_n, 6)
        # remaining invalid slots are -1 markers; emit them as all-zero
        # padding rows (fixed output shape, reference pads too)
        valid = (out[..., 0] >= 0)[..., None]
        out = jnp.where(valid, out, jnp.zeros_like(out))
        bidx = jnp.broadcast_to(
            jnp.arange(B, dtype=kr.dtype)[:, None], out.shape[:2])
        boxes = jnp.concatenate([bidx[..., None], out[..., 2:6]], -1)
        score = out[..., 1:2]
        boxes = boxes.reshape(B * post_n, 5)
        score = score.reshape(B * post_n, 1)
        return (jnp.concatenate([boxes, score], -1) if output_score
                else boxes)

    return invoke_raw("Proposal_pick", pick, [kept])


def MultiProposal(*args, **kwargs):
    """Batch variant — identical here (Proposal already handles B > 1;
    reference contrib/multi_proposal.cc)."""
    return Proposal(*args, **kwargs)


# ---------------------------------------------------------------------------
# correlation (FlowNet)
# ---------------------------------------------------------------------------

def Correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True, **_ignored):
    """Patch cross-correlation between two feature maps (reference
    correlation-inl.h:98-116). Output channels = ((2*max_displacement /
    stride2) + 1)^2, each the kernel-window correlation at one displacement
    — a static displacement loop of shifted elementwise products that XLA
    fuses; no explicit im2col buffer."""
    data1, data2 = _wrap(data1), _wrap(data2)
    K = int(kernel_size)
    md = int(max_displacement)
    s1, s2 = int(stride1), int(stride2)
    pad = int(pad_size)
    kr = (K - 1) // 2
    border = md + kr
    ngr = md // s2                       # neighborhood grid radius
    ngw = 2 * ngr + 1

    def fn(a, b):
        B, C, H, W = a.shape
        ap = jnp.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        bp = jnp.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        Hp, Wp = H + 2 * pad, W + 2 * pad
        Ho = int(jnp.ceil((Hp - border * 2) / s1))
        Wo = int(jnp.ceil((Wp - border * 2) / s1))
        ys = border + jnp.arange(Ho) * s1
        xs = border + jnp.arange(Wo) * s1
        sumelems = K * K * C

        def reduce_window(oy, ox):
            # kernel-window reduction of a⋆b at displacement (oy, ox):
            # (B, Ho, Wo) after channel sum
            acc = 0.0
            for ky in range(-kr, K - kr):
                for kx in range(-kr, K - kr):
                    a_w = ap[:, :, (ys + ky)[:, None], (xs + kx)[None, :]]
                    b_w = bp[:, :, (ys + oy + ky)[:, None],
                             (xs + ox + kx)[None, :]]
                    acc = acc + (a_w * b_w if is_multiply
                                 else jnp.abs(a_w - b_w))
            return acc.sum(axis=1) / sumelems

        outs = [reduce_window(dy * s2, dx * s2)
                for dy in range(-ngr, ngr + 1)
                for dx in range(-ngr, ngr + 1)]
        return jnp.stack(outs, axis=1)    # (B, ngw*ngw, Ho, Wo)

    return invoke_raw("Correlation", fn, [data1, data2])


# ---------------------------------------------------------------------------
# count sketch
# ---------------------------------------------------------------------------

def count_sketch(data, h, s, out_dim: int, **_ignored):
    """Count-sketch projection (reference contrib/count_sketch.cc, used by
    MCB pooling): out[b, h[i]] += s[i] * data[b, i]. One XLA scatter-add;
    autodiff gives the transpose gather for free."""
    data, h, s = _wrap(data), _wrap(h), _wrap(s)
    out_dim = int(out_dim)

    def fn(x, hh, ss):
        B = x.shape[0]
        idx = hh.reshape(-1).astype(jnp.int32)
        sign = ss.reshape(-1).astype(x.dtype)
        out = jnp.zeros((B, out_dim), x.dtype)
        return out.at[:, idx].add(x * sign[None, :])

    return invoke_raw("count_sketch", fn, [data, h, s])


# ---------------------------------------------------------------------------
# sync batch norm
# ---------------------------------------------------------------------------

def SyncBatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                  momentum=0.9, fix_gamma=True, use_global_stats=False,
                  ndev=1, key=None, axis_name=None, **_ignored):
    """Cross-device BatchNorm (reference contrib/sync_batch_norm.cc, which
    all-reduces batch mean/var over GPUs via a barrier rendezvous).

    TPU-native: a mesh-sharded batch is ONE logical array, so plain
    BatchNorm statistics are already global — XLA inserts the psum when the
    batch axis is sharded. That makes this the default path (ndev/key are
    accepted for API parity). Inside explicit shard_map/pmap code pass
    ``axis_name`` to psum the per-shard moments."""
    if axis_name is None:
        from .nn_ops import BatchNorm
        return BatchNorm(data, gamma, beta, moving_mean, moving_var,
                         eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                         use_global_stats=use_global_stats)

    from .. import _tape
    from .nn_ops import _tape_paused
    data = _wrap(data)
    gamma, beta = _wrap(gamma), _wrap(beta)
    mm, mv = _wrap(moving_mean), _wrap(moving_var)
    training = _tape.is_training() and not use_global_stats
    shape_of = lambda x: (1, -1) + (1,) * (x.ndim - 2)  # noqa: E731

    if not training:
        # inference: normalize by running stats (no cross-device moment
        # exchange needed — reference sync BN only syncs training moments)
        def infer(x, g, b, m, v):
            sh = shape_of(x)
            gg = jnp.ones_like(g) if fix_gamma else g
            xn = (x - m.reshape(sh)) * lax.rsqrt(v.reshape(sh) + eps)
            return xn * gg.reshape(sh) + b.reshape(sh)
        return invoke_raw("SyncBatchNorm", infer, [data, gamma, beta, mm, mv])

    def fn(x, g, b):
        axes = (0,) + tuple(range(2, x.ndim))
        mean = jax.lax.pmean(jnp.mean(x, axis=axes), axis_name)
        var = jax.lax.pmean(jnp.mean(x * x, axis=axes), axis_name) \
            - mean * mean
        sh = shape_of(x)
        xn = (x - mean.reshape(sh)) * lax.rsqrt(var.reshape(sh) + eps)
        gg = jnp.ones_like(g) if fix_gamma else g
        return xn * gg.reshape(sh) + b.reshape(sh), mean, var

    out, bm, bv = invoke_raw("SyncBatchNorm", fn, [data, gamma, beta],
                             n_outputs=3)
    # running-stats update with the synced moments (reference
    # sync_batch_norm.cc momentum update), outside the recorded graph —
    # same contract as nn_ops.BatchNorm
    with _tape_paused():
        mm._data = momentum * mm._data + (1 - momentum) * bm._data
        mv._data = momentum * mv._data + (1 - momentum) * bv._data
    return out
