"""Legacy NN op wrappers (``mx.nd.Convolution`` etc.) over ops/nn.py kernels.

Reference analog: the generated wrappers for src/operator/nn/ registrations.
Parameter names/semantics follow the reference ops so model code ports 1:1.
"""
from __future__ import annotations

import numpy as onp

import jax.numpy as jnp

from .. import _tape
from ..base import MXNetError
from ..ops import nn as K
from ..ops.registry import invoke_raw
from .ndarray import NDArray

__all__ = ["Convolution", "Deconvolution", "Pooling", "BatchNorm",
           "LayerNorm", "GroupNorm", "InstanceNorm", "L2Normalization",
           "LRN", "UpSampling", "BilinearResize2D", "RNN"]


def _wrap(x):
    return x if isinstance(x, NDArray) else NDArray(x)


def Convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout=None, **_ignored):
    data, weight = _wrap(data), _wrap(weight)
    if no_bias or bias is None:
        return invoke_raw(
            "convolution",
            lambda x, w: K.conv(x, w, None, stride, dilate, pad, num_group),
            [data, weight])
    return invoke_raw(
        "convolution",
        lambda x, w, b: K.conv(x, w, b, stride, dilate, pad, num_group),
        [data, weight, _wrap(bias)])


def Deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, num_filter=None,
                  num_group=1, no_bias=True, target_shape=None, **_ignored):
    data, weight = _wrap(data), _wrap(weight)
    if no_bias or bias is None:
        return invoke_raw(
            "deconvolution",
            lambda x, w: K.conv_transpose(x, w, None, stride, dilate, pad,
                                          adj, num_group),
            [data, weight])
    return invoke_raw(
        "deconvolution",
        lambda x, w, b: K.conv_transpose(x, w, b, stride, dilate, pad, adj,
                                         num_group),
        [data, weight, _wrap(bias)])


def Pooling(data, kernel=None, pool_type="max", stride=None, pad=None,
            global_pool=False, count_include_pad=True, pooling_convention=None,
            ceil_mode=False, p_value=2, **_ignored):
    data = _wrap(data)
    if global_pool:
        return invoke_raw("global_pool",
                          lambda x: K.global_pool(x, pool_type), [data])
    ceil = ceil_mode or pooling_convention == "full"
    return invoke_raw(
        "pooling",
        lambda x: K.pool(x, kernel, pool_type, stride, pad, count_include_pad,
                         ceil, p_value),
        [data])


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
              momentum=0.9, fix_gamma=False, use_global_stats=False,
              output_mean_var=False, axis=1, **_ignored):
    """Imperative BatchNorm. In training mode returns normalized output and
    updates moving stats in place on the passed arrays (the Gluon layer calls
    the functional kernels directly for the hybridized path)."""
    data = _wrap(data)
    gamma, beta = _wrap(gamma), _wrap(beta)
    mm, mv = _wrap(moving_mean), _wrap(moving_var)
    training = _tape.is_training() and not use_global_stats
    if fix_gamma:
        gamma = NDArray(gamma._data * 0 + 1)
    if not training:
        return invoke_raw(
            "batch_norm",
            lambda x, g, b, m, v: K.batch_norm_infer(x, g, b, m, v, eps),
            [data, gamma, beta, mm, mv])
    out, bm, bv = invoke_raw(
        "batch_norm",
        lambda x, g, b: K.batch_norm_train(x, g, b, eps),
        [data, gamma, beta], n_outputs=3)
    # update running stats outside the recorded graph (stats reused from the
    # same kernel invocation; batch mean/var get zero cotangents)
    with _tape_paused():
        mm._data = momentum * mm._data + (1 - momentum) * bm._data
        mv._data = momentum * mv._data + (1 - momentum) * bv._data
    return out


class _tape_paused:
    def __enter__(self):
        self._old = _tape.set_recording(False)

    def __exit__(self, *exc):
        _tape.set_recording(self._old)


def LayerNorm(data, gamma, beta, axis=-1, eps=1e-5, **_ignored):
    return invoke_raw(
        "layer_norm",
        lambda x, g, b: K.layer_norm(x, g, b, axis, eps),
        [_wrap(data), _wrap(gamma), _wrap(beta)])


def GroupNorm(data, gamma, beta, num_groups=1, eps=1e-5, **_ignored):
    return invoke_raw(
        "group_norm",
        lambda x, g, b: K.group_norm(x, g, b, num_groups, eps),
        [_wrap(data), _wrap(gamma), _wrap(beta)])


def InstanceNorm(data, gamma, beta, eps=1e-5, **_ignored):
    return invoke_raw(
        "instance_norm",
        lambda x, g, b: K.instance_norm(x, g, b, eps),
        [_wrap(data), _wrap(gamma), _wrap(beta)])


def L2Normalization(data, eps=1e-10, mode="instance", **_ignored):
    return invoke_raw("l2_normalization",
                      lambda x: K.l2_norm(x, eps=eps, mode=mode), [_wrap(data)])


def LRN(data, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0, **_ignored):
    return invoke_raw("lrn",
                      lambda x: K.lrn(x, nsize, alpha, beta, knorm),
                      [_wrap(data)])


def UpSampling(data, scale=2, sample_type="nearest", num_args=1, **_ignored):
    import jax
    data = _wrap(data)

    def fn(x):
        n, c, h, w = x.shape
        method = "nearest" if sample_type == "nearest" else "linear"
        return jax.image.resize(x, (n, c, h * scale, w * scale), method=method)
    return invoke_raw("upsampling", fn, [data])


def BilinearResize2D(data, height=None, width=None, scale_height=None,
                     scale_width=None, mode="size", **_ignored):
    """Resize NCHW to an explicit (height, width) (``mode='size'``) or by
    scale factors (``mode='scale'``, output = floor(in * scale) — the
    ONNX Resize convention the importer maps onto); half-pixel linear
    interpolation via jax.image.resize (reference contrib
    BilinearResize2D, src/operator/contrib/bilinear_resize.cc)."""
    import math as _math
    data = _wrap(data)
    n, c, h, w = data.shape
    if mode == "size":
        if height is None or width is None:
            raise MXNetError(
                "BilinearResize2D mode='size' needs height and width")
    elif mode == "scale":
        if scale_height is None or scale_width is None:
            raise MXNetError("BilinearResize2D mode='scale' needs "
                             "scale_height and scale_width")
        height = int(_math.floor(h * scale_height))
        width = int(_math.floor(w * scale_width))
    else:
        raise MXNetError(f"BilinearResize2D mode {mode!r} unsupported "
                         "(size/scale)")
    return invoke_raw(
        "bilinear_resize",
        lambda x: K.bilinear_resize(x, int(height), int(width)), [data])


def _rnn_layout(mode, input_size, state_size, num_layers, bidirectional):
    """Slice table for the reference RNN op's packed parameter vector
    (rnn-inl.h: all weights layer/direction-major, then all biases):
    returns [(offset, shape)] in fused_rnn's [w_ih, w_hh, b_ih, b_hh]
    per-(layer, dir) order."""
    from ..ops.rnn import GATES
    g = GATES[mode]
    h = state_size
    dirs = 2 if bidirectional else 1
    w_slices, b_slices = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * dirs
        for _ in range(dirs):
            w_slices.append((off, (g * h, in_sz)))
            off += g * h * in_sz
            w_slices.append((off, (g * h, h)))
            off += g * h * h
    for layer in range(num_layers):
        for _ in range(dirs):
            b_slices.append((off, (g * h,)))
            off += g * h
            b_slices.append((off, (g * h,)))
            off += g * h
    order = []
    for i in range(num_layers * dirs):
        order.append(w_slices[2 * i])       # w_ih
        order.append(w_slices[2 * i + 1])   # w_hh
        order.append(b_slices[2 * i])       # b_ih
        order.append(b_slices[2 * i + 1])   # b_hh
    return order, off


def _rnn_unpack(pv, order):
    """Slice a packed parameter vector by an _rnn_layout order table
    (single owner of the slice/reshape contract; used by the op kernel
    and the ONNX exporter)."""
    return [pv[o:o + int(onp.prod(s))].reshape(s) for o, s in order]


def RNN(data, parameters, state=None, state_cell=None, state_size=None,
        num_layers=1, bidirectional=False, mode="lstm", p=0.0,
        state_outputs=False, onnx_outputs=False, **_ignored):
    """Legacy fused RNN op over a single packed parameter vector
    (reference src/operator/rnn.cc; cuDNN packing: weights then biases,
    layer/direction-major). data: (T, N, C); state/state_cell:
    (L*D, N, H). Returns output (T, N, D*H), or
    ``[output, state_h(, state_cell)]`` with ``state_outputs=True``.
    ``onnx_outputs=True`` instead emits the ONNX recurrent-node layout
    ``[Y (T, D, N, H), Y_h(, Y_c)]`` (the importer's target)."""
    from ..ops import rnn as K_rnn
    if state_size is None:
        raise MXNetError("RNN requires state_size")
    data = _wrap(data)
    h = int(state_size)
    num_layers = int(num_layers)
    dirs = 2 if bidirectional else 1
    c_in = data.shape[-1]
    order, total = _rnn_layout(mode, c_in, h, num_layers, bidirectional)
    inputs = [data, _wrap(parameters)]
    have_h = state is not None
    have_c = state_cell is not None
    if have_c and not have_h:
        # positional symbol/executor binding would silently feed the cell
        # in as the hidden state — refuse the ambiguous form
        raise MXNetError("RNN: state_cell without state is unsupported; "
                         "pass both (in that order for symbolic calls)")
    if have_h:
        inputs.append(_wrap(state))
    if have_c:
        inputs.append(_wrap(state_cell))

    # inter-layer dropout (reference rnn-inl.h p): training-mode only,
    # keyed from the framework RNG stream (captured host-side)
    train = _tape.is_training() and float(p) > 0.0 and num_layers > 1
    if train:
        from .random import next_key
        drop_key = next_key()
    else:
        drop_key = None

    def fn(x, pv, *states):
        if pv.size != total:
            raise MXNetError(
                f"RNN packed parameter size {pv.size} != expected {total} "
                f"(mode={mode}, input={c_in}, hidden={h}, "
                f"layers={num_layers}, dirs={dirs})")
        flat = _rnn_unpack(pv, order)
        n = x.shape[1]
        zero = jnp.zeros((num_layers * dirs, n, h), x.dtype)
        si = 0
        if have_h:
            h0 = states[si]
            si += 1
        else:
            h0 = zero
        if mode == "lstm":
            c0 = states[si] if have_c else zero
        else:
            c0 = None
        y, h_out, c_out = K_rnn.fused_rnn(x, h0, c0, flat, mode,
                                          num_layers, bool(bidirectional),
                                          dropout=float(p), train=train,
                                          key=drop_key)
        if onnx_outputs:
            t = y.shape[0]
            y_onnx = y.reshape(t, n, dirs, h).transpose(0, 2, 1, 3)
            outs = [y_onnx, h_out]
            if mode == "lstm":
                outs.append(c_out)
            return tuple(outs)
        if state_outputs:
            outs = [y, h_out]
            if mode == "lstm":
                outs.append(c_out)
            return tuple(outs)
        return y

    n_out = 1
    if onnx_outputs or state_outputs:
        n_out = 3 if mode == "lstm" else 2
    res = invoke_raw("rnn_packed", fn, inputs, n_outputs=n_out)
    return res
