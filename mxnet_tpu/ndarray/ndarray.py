"""NDArray: the imperative tensor handle, backed by XLA device buffers.

TPU-native re-design of the reference NDArray (reference:
include/mxnet/ndarray.h:82, src/ndarray/ndarray.cc). The reference pairs a
Storage chunk with an engine variable for dependency ordering; here the
backing store is a ``jax.Array`` (PjRt buffer) whose runtime is already
async + ordered, so the handle keeps only:

- ``_data``       the current jax.Array (functional; in-place ops rebind it)
- ``_ctx``        logical Context (mx.cpu()/mx.tpu(i))
- autograd state  ``_grad``/``_grad_req``/``_tape_entry`` (reference AGInfo)

Mutation semantics: XLA buffers are immutable, so every in-place op
(``+=``, ``[...] = v``) rewrites ``_data`` with a functionally-updated array
— the "version-tracking aliasing layer" of SURVEY §7. Basic indexing returns
copies (deviation from the reference's first-axis views; write-through is
preserved because ``x[i:j] += v`` routes through ``__setitem__``).

NDArray is registered as a JAX pytree node, so handles flow through
``jax.jit`` / ``pjit`` / ``shard_map`` transparently — this is what makes
``HybridBlock.hybridize()`` a plain jit trace.
"""
from __future__ import annotations

import numbers
from typing import Any, Optional, Sequence, Tuple, Union

import numpy as onp

import jax
import jax.numpy as jnp

from .. import _tape, engine
from ..analysis import guard as _tguard
from ..base import MXNetError, jx_dtype, dtype_name
from ..context import Context, current_context
from ..ops.registry import invoke_raw

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "linspace", "eye", "concatenate", "waitall", "from_jax", "moveaxis"]


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class NDArray:
    """Multi-dimensional array with imperative mutation + autograd hooks."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_tape_entry",
                 "_fresh_grad", "__weakref__")

    # make NDArray win against numpy in mixed binary expressions
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array) and not _is_tracer(data):
            keep_dtype = isinstance(data, (onp.ndarray, onp.generic))
            data = onp.asarray(data, dtype=None if dtype is None else jx_dtype(dtype))
            if dtype is None:
                if data.dtype == onp.float64:
                    data = data.astype(onp.float32)  # MXNet default_dtype=float32
                elif not keep_dtype and data.dtype != onp.bool_:
                    # python lists/scalars default to float32 like mx.nd.array
                    data = data.astype(onp.float32)
            data = _put(data, ctx)
        else:
            if dtype is not None and data.dtype != jx_dtype(dtype):
                data = data.astype(jx_dtype(dtype))
            if ctx is not None and not _is_tracer(data):
                data = _put(data, ctx)  # honor explicit placement request
        self._data = data
        self._ctx = ctx
        self._grad = None
        self._grad_req = "write"
        self._tape_entry = None
        self._fresh_grad = False

    def _init_empty(self):
        """Used by invoke_raw to allocate output handles before record_op."""
        self._data = None
        self._ctx = None
        self._grad = None
        self._grad_req = "write"
        self._tape_entry = None
        self._fresh_grad = False

    # ---------------- properties ----------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return onp.dtype(str(self._data.dtype)) if str(self._data.dtype) != "bfloat16" \
            else jnp.bfloat16

    @property
    def size(self) -> int:
        return int(onp.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def nbytes(self) -> int:
        """Logical bytes of the full array (size × itemsize)."""
        return self.size * onp.dtype(str(self._data.dtype)).itemsize

    @property
    def device_nbytes(self) -> int:
        """PER-REPLICA bytes this handle's backing buffer occupies on
        one device: the addressable-shard footprint (1/N for
        NamedSharding-partitioned buffers, full size when replicated) —
        the accounting rule of the device-memory census
        (``mx.telemetry.memory.device_bytes``)."""
        from ..telemetry.memory import device_bytes
        return device_bytes(self._data)

    def track_memory(self, pool: str = "ndarray") -> "NDArray":
        """File this handle in the live-buffer census
        (``mx.telemetry.memory.census()``) under ``pool`` (default
        ``ndarray`` — the user pool). Weakref-based: the buffer leaves
        the census when the handle is collected. Returns ``self`` so it
        chains: ``x = mx.nd.array(...).track_memory()``."""
        from ..telemetry.memory import census
        census().register(pool, self)
        return self

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        if _is_tracer(self._data):
            return current_context()
        try:
            dev = list(self._data.devices())[0]
        except Exception:
            return current_context()
        if dev.platform == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", _accel_index(dev))

    ctx = context
    device = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def fresh_grad(self) -> bool:
        return self._fresh_grad

    @fresh_grad.setter
    def fresh_grad(self, v: bool):
        self._fresh_grad = v

    # ---------------- materialization ----------------
    def asnumpy(self) -> onp.ndarray:
        if _tguard.armed():
            # transfer guard (MXNET_TRANSFER_GUARD): a host
            # materialization inside a declared hot region logs its
            # stack or raises (analysis/guard.py); the nested
            # wait_to_read must not double-report
            _tguard.on_sync("asnumpy", self._what())
            with _tguard.allow_transfers():
                self.wait_to_read()
                return onp.asarray(self._data)
        self.wait_to_read()
        a = onp.asarray(self._data)
        return a

    def _what(self) -> str:
        try:
            return (f"NDArray(shape={tuple(self.shape)}, "
                    f"dtype={dtype_name(self._data.dtype)})")
        except Exception:            # pragma: no cover - defensive
            return "NDArray"

    def item(self):
        return self.asnumpy().item()

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.item()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size == 0:
            return False
        if self.size == 1:
            return bool(self.item())
        raise MXNetError("The truth value of an NDArray with multiple "
                         "elements is ambiguous")

    def __len__(self):
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        if self._data is None:
            return "<NDArray (uninitialized)>"
        if _is_tracer(self._data):
            return f"<NDArray {self.shape} {dtype_name(self._data.dtype)} (traced)>"
        return f"{onp.asarray(self._data)}\n<NDArray {'x'.join(map(str, self.shape))} @{self.context}>"

    # ---------------- sync (engine semantics) ----------------
    def wait_to_read(self):
        """Block until the value is ready; async errors surface here
        (reference NDArray::WaitToRead, engine exception rethrow). A
        deferred RESOURCE_EXHAUSTED surfacing at this sync point writes
        its OOM post-mortem (telemetry/memory.py) before propagating."""
        if not _is_tracer(self._data):
            _tguard.count_sync("wait_to_read")
            if _tguard.armed():
                _tguard.on_sync("wait_to_read", self._what())
            try:
                jax.block_until_ready(self._data)
            except Exception as e:
                from ..telemetry.memory import maybe_record_oom
                maybe_record_oom(e, "NDArray.wait_to_read")
                raise

    wait_to_write = wait_to_read

    # ---------------- device / dtype movement ----------------
    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context
    as_ctx = as_in_context

    def to_device(self, ctx):
        return self.as_in_context(ctx)

    def copyto(self, other: Union[Context, "NDArray"]) -> "NDArray":
        if isinstance(other, NDArray):
            other._data = _put(self._data, other.context)
            return other
        return NDArray(_put(self._data, other), ctx=other)

    def copy(self) -> "NDArray":
        return NDArray(self._data)

    def astype(self, dtype, copy: bool = True) -> "NDArray":
        dt = jx_dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return invoke_raw("cast", lambda x, _dt=dt: x.astype(_dt), [self])

    def tostype(self, stype: str) -> "NDArray":
        if stype == "default":
            return self
        from . import sparse
        return sparse.cast_storage(self, stype)

    def as_np_ndarray(self):
        from ..numpy import ndarray as np_ndarray
        out = np_ndarray.__new__(np_ndarray)
        out._init_empty()
        out._data = self._data
        out._ctx = self._ctx
        out._grad = self._grad
        out._tape_entry = self._tape_entry
        return out

    def as_nd_ndarray(self):
        return self

    # ---------------- autograd ----------------
    def attach_grad(self, grad_req: str = "write", stype: Optional[str] = None):
        """Allocate gradient buffer and mark as autograd leaf
        (reference python/mxnet/ndarray/ndarray.py attach_grad)."""
        self._grad = NDArray(jnp.zeros(self.shape, self._data.dtype))
        self._grad_req = grad_req
        self._tape_entry = None

    def drop_grad(self):
        self._grad = None
        self._grad_req = "null"

    def backward(self, out_grad=None, retain_graph: bool = False,
                 train_mode: bool = True):
        _tape.backward([self], [out_grad], retain_graph=retain_graph,
                       train_mode=train_mode)

    def detach(self) -> "NDArray":
        out = NDArray(self._data)
        return out

    # ---------------- shape manipulation ----------------
    def reshape(self, *shape, **kwargs) -> "NDArray":
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if kwargs.get("shape") is not None:
            shape = tuple(kwargs["shape"])
        new_shape = _infer_reshape(self.shape, shape, kwargs.get("reverse", False))
        return invoke_raw("reshape", lambda x, _s=new_shape: x.reshape(_s), [self])

    def reshape_like(self, other: "NDArray") -> "NDArray":
        return self.reshape(other.shape)

    def transpose(self, *axes) -> "NDArray":
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        ax = axes if axes else None
        return invoke_raw("transpose", lambda x, _a=ax: jnp.transpose(x, _a), [self])

    def swapaxes(self, a1: int, a2: int) -> "NDArray":
        return invoke_raw("swapaxes", lambda x: jnp.swapaxes(x, a1, a2), [self])

    def flatten(self) -> "NDArray":
        # MXNet Flatten: collapse all but first axis (2D result)
        n = self.shape[0] if self.ndim else 1
        return self.reshape(n, -1)

    def squeeze(self, axis=None) -> "NDArray":
        return invoke_raw("squeeze", lambda x: jnp.squeeze(x, axis), [self])

    def expand_dims(self, axis: int) -> "NDArray":
        return invoke_raw("expand_dims", lambda x: jnp.expand_dims(x, axis), [self])

    def broadcast_to(self, shape) -> "NDArray":
        return invoke_raw("broadcast_to",
                          lambda x, _s=tuple(shape): jnp.broadcast_to(x, _s), [self])

    def broadcast_like(self, other: "NDArray") -> "NDArray":
        return self.broadcast_to(other.shape)

    def tile(self, reps) -> "NDArray":
        return invoke_raw("tile", lambda x: jnp.tile(x, reps), [self])

    def repeat(self, repeats, axis=None) -> "NDArray":
        return invoke_raw("repeat", lambda x: jnp.repeat(x, repeats, axis), [self])

    def flip(self, axis) -> "NDArray":
        return invoke_raw("flip", lambda x: jnp.flip(x, axis), [self])

    def diag(self, k: int = 0) -> "NDArray":
        return invoke_raw("diag", lambda x: jnp.diag(x, k), [self])

    def pad(self, pad_width, mode="constant", constant_value=0.0) -> "NDArray":
        return invoke_raw(
            "pad", lambda x: jnp.pad(x, pad_width, mode=mode,
                                     constant_values=constant_value)
            if mode == "constant" else jnp.pad(x, pad_width, mode=mode), [self])

    # ---------------- reductions / linalg (method forms) ----------------
    def _reduce(self, name, jfn, axis=None, keepdims=False):
        ax = _norm_axis(axis)
        return invoke_raw(name, lambda x: jfn(x, axis=ax, keepdims=keepdims), [self])

    def sum(self, axis=None, keepdims=False):
        return self._reduce("sum", jnp.sum, axis, keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._reduce("mean", jnp.mean, axis, keepdims)

    def max(self, axis=None, keepdims=False):
        return self._reduce("max", jnp.max, axis, keepdims)

    def min(self, axis=None, keepdims=False):
        return self._reduce("min", jnp.min, axis, keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._reduce("prod", jnp.prod, axis, keepdims)

    def std(self, axis=None, keepdims=False):
        return self._reduce("std", jnp.std, axis, keepdims)

    def var(self, axis=None, keepdims=False):
        return self._reduce("var", jnp.var, axis, keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        ax = _norm_axis(axis)
        if ord == 2:
            fn = lambda x: jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))
        elif ord == 1:
            fn = lambda x: jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
        else:
            raise MXNetError(f"norm ord={ord} unsupported")
        return invoke_raw("norm", fn, [self])

    def argmax(self, axis=None, keepdims=False):
        return invoke_raw("argmax", lambda x: jnp.argmax(x, axis=axis,
                          keepdims=keepdims).astype(jnp.float32), [self])

    def argmin(self, axis=None, keepdims=False):
        return invoke_raw("argmin", lambda x: jnp.argmin(x, axis=axis,
                          keepdims=keepdims).astype(jnp.float32), [self])

    def dot(self, other: "NDArray") -> "NDArray":
        from . import ops as _nd_ops
        return _nd_ops.dot(self, other)

    def clip(self, a_min=None, a_max=None) -> "NDArray":
        return invoke_raw("clip", lambda x: jnp.clip(x, a_min, a_max), [self])

    def abs(self):
        return invoke_raw("abs", jnp.abs, [self])

    def sign(self):
        return invoke_raw("sign", jnp.sign, [self])

    def sqrt(self):
        return invoke_raw("sqrt", jnp.sqrt, [self])

    def square(self):
        return invoke_raw("square", jnp.square, [self])

    def exp(self):
        return invoke_raw("exp", jnp.exp, [self])

    def log(self):
        return invoke_raw("log", jnp.log, [self])

    def sigmoid(self):
        return invoke_raw("sigmoid", jax.nn.sigmoid, [self])

    def tanh(self):
        return invoke_raw("tanh", jnp.tanh, [self])

    def relu(self):
        return invoke_raw("relu", jax.nn.relu, [self])

    def softmax(self, axis=-1):
        return invoke_raw("softmax", lambda x: jax.nn.softmax(x, axis=axis), [self])

    def log_softmax(self, axis=-1):
        return invoke_raw("log_softmax",
                          lambda x: jax.nn.log_softmax(x, axis=axis), [self])

    def one_hot(self, depth, on_value=1.0, off_value=0.0, dtype="float32"):
        dt = jx_dtype(dtype)
        return invoke_raw(
            "one_hot",
            lambda x: jnp.where(
                jax.nn.one_hot(x.astype(jnp.int32), depth, dtype=jnp.bool_),
                jnp.asarray(on_value, dt), jnp.asarray(off_value, dt)), [self])

    def round(self):
        return invoke_raw("round", jnp.round, [self])

    def floor(self):
        return invoke_raw("floor", jnp.floor, [self])

    def ceil(self):
        return invoke_raw("ceil", jnp.ceil, [self])

    def slice_axis(self, axis, begin, end):
        from . import ops as _nd_ops
        return _nd_ops.slice_axis(self, axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        from . import ops as _nd_ops
        return _nd_ops.take(self, indices, axis=axis, mode=mode)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        from . import ops as _nd_ops
        return _nd_ops.topk(self, axis=axis, k=k, ret_typ=ret_typ,
                            is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        from . import ops as _nd_ops
        return _nd_ops.sort(self, axis=axis, is_ascend=is_ascend)

    def argsort(self, axis=-1, is_ascend=True):
        from . import ops as _nd_ops
        return _nd_ops.argsort(self, axis=axis, is_ascend=is_ascend)

    # ---------------- arithmetic ----------------
    def _binary(self, name, other, jfn, reverse=False):
        if isinstance(other, NDArray):
            if reverse:
                return invoke_raw(name, lambda a, b: jfn(b, a), [self, other])
            return invoke_raw(name, jfn, [self, other])
        if isinstance(other, (numbers.Number, onp.number)):
            if reverse:
                return invoke_raw(name + "_scalar",
                                  lambda a, _s=other: jfn(_s, a), [self])
            return invoke_raw(name + "_scalar",
                              lambda a, _s=other: jfn(a, _s), [self])
        if isinstance(other, (onp.ndarray, list, tuple)):
            return self._binary(name, NDArray(other), jfn, reverse)
        return NotImplemented

    def __add__(self, o):
        return self._binary("add", o, jnp.add)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary("sub", o, jnp.subtract)

    def __rsub__(self, o):
        return self._binary("sub", o, jnp.subtract, reverse=True)

    def __mul__(self, o):
        return self._binary("mul", o, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary("div", o, jnp.divide)

    def __rtruediv__(self, o):
        return self._binary("div", o, jnp.divide, reverse=True)

    def __mod__(self, o):
        return self._binary("mod", o, jnp.mod)

    def __rmod__(self, o):
        return self._binary("mod", o, jnp.mod, reverse=True)

    def __pow__(self, o):
        return self._binary("pow", o, jnp.power)

    def __rpow__(self, o):
        return self._binary("pow", o, jnp.power, reverse=True)

    def __floordiv__(self, o):
        return self._binary("floordiv", o, jnp.floor_divide)

    def __matmul__(self, o):
        return self.dot(o)

    def __neg__(self):
        return invoke_raw("negative", jnp.negative, [self])

    def __abs__(self):
        return self.abs()

    # in-place: rebind _data (functional update; see module docstring)
    def _inplace(self, name, other, jfn):
        out = self._binary(name, other, jfn)
        self._data = out._data
        self._tape_entry = out._tape_entry
        return self

    def __iadd__(self, o):
        return self._inplace("add", o, jnp.add)

    def __isub__(self, o):
        return self._inplace("sub", o, jnp.subtract)

    def __imul__(self, o):
        return self._inplace("mul", o, jnp.multiply)

    def __itruediv__(self, o):
        return self._inplace("div", o, jnp.divide)

    # comparisons: legacy nd returns 0/1 in the operand dtype (reference
    # broadcast_equal etc.), except same-dtype bools pass through
    def _compare(self, name, other, jfn):
        dt = self._data.dtype
        if isinstance(other, NDArray):
            return invoke_raw(name, lambda a, b: jfn(a, b).astype(dt),
                              [self, other], record=False)
        return invoke_raw(name + "_scalar",
                          lambda a, _s=other: jfn(a, _s).astype(dt),
                          [self], record=False)

    def __eq__(self, o):
        if o is None:
            return False
        return self._compare("equal", o, jnp.equal)

    def __ne__(self, o):
        if o is None:
            return True
        return self._compare("not_equal", o, jnp.not_equal)

    def __gt__(self, o):
        return self._compare("greater", o, jnp.greater)

    def __ge__(self, o):
        return self._compare("greater_equal", o, jnp.greater_equal)

    def __lt__(self, o):
        return self._compare("lesser", o, jnp.less)

    def __le__(self, o):
        return self._compare("lesser_equal", o, jnp.less_equal)

    __hash__ = object.__hash__

    # ---------------- indexing ----------------
    def __getitem__(self, key):
        key = _norm_key(key)
        return invoke_raw("slice", lambda x, _k=key: x[_k], [self])

    def __setitem__(self, key, value):
        key = _norm_key(key)
        # Route through invoke_raw so autograd records the functional
        # scatter-update (stale-tape-entry writes would corrupt gradients).
        if isinstance(value, NDArray):
            out = invoke_raw("set_item",
                             lambda x, v, _k=key: x.at[_k].set(v),
                             [self, value])
        else:
            out = invoke_raw("set_item_scalar",
                             lambda x, _k=key, _v=value: x.at[_k].set(_v),
                             [self])
        self._data = out._data
        self._tape_entry = out._tape_entry

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # numpy interop
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


def _accel_index(dev) -> int:
    accels = [d for d in jax.local_devices() if d.platform != "cpu"]
    for i, d in enumerate(accels):
        if d == dev:
            return i
    return 0


def _put(data, ctx: Optional[Context]):
    """Place host data on the right device (reference CopyFromTo analog).
    Invalid devices raise (MXNetError), like the reference's ctx checks."""
    if ctx is None:
        ctx = current_context()
    dev = ctx.jax_device  # raises MXNetError for out-of-range device ids
    try:
        return jax.device_put(data, dev)
    except (TypeError, ValueError):
        # tracers / weak types can't be device_put mid-trace; leave to XLA
        return jnp.asarray(data)


def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _norm_key(key):
    """Convert NDArray indices inside keys to jax arrays."""
    if isinstance(key, NDArray):
        k = key._data
        return k.astype(jnp.int32) if k.dtype not in (jnp.int32, jnp.int64, jnp.bool_) else k
    if isinstance(key, tuple):
        return tuple(_norm_key(k) for k in key)
    return key


def _infer_reshape(old: Tuple[int, ...], spec, reverse=False) -> Tuple[int, ...]:
    """MXNet reshape special codes (reference src/operator/tensor/matrix_op-inl.h
    ReshapeParam): 0 copy dim, -1 infer, -2 copy rest, -3 merge two, -4 split."""
    if reverse:
        old = old[::-1]
        spec = tuple(spec)[::-1]
    out = []
    i = 0  # index into old
    spec = list(spec)
    j = 0
    while j < len(spec):
        s = spec[j]
        if s == 0:
            out.append(old[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(old[i:]); i = len(old)
        elif s == -3:
            out.append(old[i] * old[i + 1]); i += 2
        elif s == -4:
            d1, d2 = spec[j + 1], spec[j + 2]
            cur = old[i]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(int(s))
            if i < len(old):
                i += 1
        j += 1
    if out.count(-1) > 1:
        raise MXNetError("can only specify one unknown dimension")
    if -1 in out:
        known = int(onp.prod([d for d in out if d != -1])) or 1
        total = int(onp.prod(old)) if old else 1
        out[out.index(-1)] = total // known
    if reverse:
        out = out[::-1]
    return tuple(out)


# ---- pytree registration: NDArray flows through jit/pjit/vmap/shard_map ----
def _flatten(x: NDArray):
    return (x._data,), None


def _unflatten(aux, children):
    out = NDArray.__new__(NDArray)
    out._init_empty()
    out._data = children[0]
    return out


jax.tree_util.register_pytree_node(NDArray, _flatten, _unflatten)


# ---------------- creation functions ----------------
def array(source, ctx=None, dtype=None) -> NDArray:
    return NDArray(source, ctx=ctx, dtype=dtype)


def from_jax(x, ctx=None) -> NDArray:
    return NDArray(x, ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(_put(jnp.zeros(shape, jx_dtype(dtype)), ctx), ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(_put(jnp.ones(shape, jx_dtype(dtype)), ctx), ctx=ctx)


def full(shape, val, ctx=None, dtype=None) -> NDArray:
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return NDArray(_put(jnp.full(shape, val, jx_dtype(dtype)), ctx), ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    a = jnp.arange(start, stop, step, jx_dtype(dtype))
    if repeat != 1:
        a = jnp.repeat(a, repeat)
    return NDArray(_put(a, ctx), ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None) -> NDArray:
    return NDArray(_put(jnp.linspace(start, stop, num, endpoint=endpoint,
                                     dtype=jx_dtype(dtype)), ctx), ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    return NDArray(_put(jnp.eye(N, M if M else None, k, jx_dtype(dtype)), ctx),
                   ctx=ctx)


def concatenate(arrays, axis=0) -> NDArray:
    return invoke_raw("concat", lambda *xs: jnp.concatenate(xs, axis=axis),
                      list(arrays))


def moveaxis(a: NDArray, source, destination) -> NDArray:
    return invoke_raw("moveaxis", lambda x: jnp.moveaxis(x, source, destination), [a])


def waitall():
    """Reference mx.nd.waitall — block until all async compute completes."""
    engine.get().wait_for_all()
