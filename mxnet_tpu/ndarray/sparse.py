"""Sparse storage types: row_sparse and csr.

Reference analog: ndarray.h:61-65 storage types + python/mxnet/ndarray/sparse.py.
XLA has no first-class sparsity (SURVEY §7 hard parts), so these are
structured wrappers: the compressed representation lives in dense index/value
arrays (TPU-friendly — gathers/scatters are XLA ops on the MXU/VPU), and any
op without a sparse-aware path falls back to the dense form, mirroring the
reference's storage-fallback mechanism (``FInferStorageType`` fallback casts,
src/common/exec_utils.h).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import MXNetError, jx_dtype
from .ndarray import NDArray, _put

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "retain",
           "sparse_dot"]


class BaseSparseNDArray(NDArray):
    """Common base; behaves as its dense form for any generic op (dense
    fallback), while keeping the compressed arrays for sparse-aware paths."""

    __slots__ = ("_aux",)

    @property
    def stype(self) -> str:
        raise NotImplementedError

    def tostype(self, stype: str):
        if stype == "default":
            return NDArray(self._data)
        return cast_storage(self, stype)

    def asdense(self) -> NDArray:
        return NDArray(self._data)


class RowSparseNDArray(BaseSparseNDArray):
    """First-dim-sparse tensor: (indices, values-rows) (reference
    RowSparseNDArray; used for sparse gradients of Embedding/FC)."""

    @property
    def stype(self) -> str:
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return self._aux["indices"]

    @property
    def data(self) -> NDArray:
        return self._aux["values"]

    def retain(self, row_ids) -> "RowSparseNDArray":
        return retain(self, row_ids)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference CSRNDArray)."""

    @property
    def stype(self) -> str:
        return "csr"

    @property
    def indices(self) -> NDArray:
        return self._aux["indices"]

    @property
    def indptr(self) -> NDArray:
        return self._aux["indptr"]

    @property
    def data(self) -> NDArray:
        return self._aux["values"]


def _make_row_sparse(dense_data, indices, values) -> RowSparseNDArray:
    out = RowSparseNDArray.__new__(RowSparseNDArray)
    out._init_empty()
    out._data = dense_data
    out._aux = {"indices": NDArray(indices), "values": NDArray(values)}
    return out


class LazyRowSparseNDArray(RowSparseNDArray):
    """Row-sparse array whose dense mirror is materialized ON FIRST DENSE
    ACCESS instead of eagerly. Sparse-aware consumers (lazy optimizer
    update, kvstore sparse round-trip) read only (indices, values), so an
    Embedding sparse gradient costs O(rows) memory traffic end-to-end; the
    O(vocab) scatter happens only if something actually needs the dense
    form (reference row_sparse arrays are likewise never densified on the
    sparse path, src/operator/optimizer_op.cc sparse kernels)."""

    __slots__ = ("_dense_thunk",)

    # the subclass property shadows the NDArray `_data` slot; the slot
    # descriptor on NDArray is still the storage
    @property
    def _data(self):
        d = NDArray._data.__get__(self)
        if d is None:
            thunk = self._dense_thunk
            if thunk is not None:
                d = thunk()
                NDArray._data.__set__(self, d)
                self._dense_thunk = None
        return d

    @_data.setter
    def _data(self, value):
        NDArray._data.__set__(self, value)
        self._dense_thunk = None

    @property
    def is_materialized(self) -> bool:
        return NDArray._data.__get__(self) is not None


def _make_row_sparse_lazy(dense_thunk, indices, values):
    out = LazyRowSparseNDArray.__new__(LazyRowSparseNDArray)
    out._dense_thunk = None
    out._init_empty()
    out._aux = {"indices": NDArray(indices), "values": NDArray(values)}
    out._dense_thunk = dense_thunk
    return out


def _make_csr(dense_data, data, indices, indptr) -> CSRNDArray:
    out = CSRNDArray.__new__(CSRNDArray)
    out._init_empty()
    out._data = dense_data
    out._aux = {"values": NDArray(data), "indices": NDArray(indices),
                "indptr": NDArray(indptr)}
    return out


def row_sparse_array(arg1, shape: Optional[Tuple[int, ...]] = None,
                     ctx=None, dtype=None) -> RowSparseNDArray:
    """Create from (values, indices) or a dense array (reference
    mx.nd.sparse.row_sparse_array)."""
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2:
        values, indices = arg1
        values = values._data if isinstance(values, NDArray) \
            else jnp.asarray(values, jx_dtype(dtype))
        indices = indices._data if isinstance(indices, NDArray) \
            else jnp.asarray(indices, jnp.int32)
        if shape is None:
            nrows = int(jnp.max(indices)) + 1 if indices.size else 0
            shape = (nrows,) + tuple(values.shape[1:])
        dense = jnp.zeros(shape, values.dtype) \
            .at[indices.astype(jnp.int32)].set(values)
        return _make_row_sparse(_put(dense, ctx), indices, values)
    d = arg1._data if isinstance(arg1, NDArray) else jnp.asarray(arg1)
    return cast_storage(NDArray(d), "row_sparse")


def csr_matrix(arg1, shape: Optional[Tuple[int, ...]] = None, ctx=None,
               dtype=None) -> CSRNDArray:
    """Create from (data, indices, indptr) or dense (reference
    mx.nd.sparse.csr_matrix)."""
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = (
            a._data if isinstance(a, NDArray) else jnp.asarray(a)
            for a in arg1)
        data = data.astype(jx_dtype(dtype)) if dtype else data
        indices = indices.astype(jnp.int32)
        indptr = indptr.astype(jnp.int32)
        if shape is None:
            ncols = int(jnp.max(indices)) + 1 if indices.size else 0
            shape = (len(indptr) - 1, ncols)
        # expand indptr -> row ids, scatter into dense
        counts = indptr[1:] - indptr[:-1]
        row_ids = jnp.repeat(jnp.arange(shape[0]), counts,
                             total_repeat_length=data.shape[0])
        dense = jnp.zeros(shape, data.dtype) \
            .at[row_ids, indices.astype(jnp.int32)].set(data)
        return _make_csr(_put(dense, ctx), data, indices, indptr)
    d = arg1._data if isinstance(arg1, NDArray) else jnp.asarray(arg1)
    return cast_storage(NDArray(d), "csr")


def cast_storage(arr: NDArray, stype: str):
    """Convert between storage types (reference cast_storage op)."""
    if stype == "default":
        return NDArray(arr._data)
    dense = onp.asarray(arr._data)
    if stype == "row_sparse":
        nz_rows = onp.nonzero(dense.reshape(dense.shape[0], -1)
                              .any(axis=1))[0]
        return _make_row_sparse(arr._data, jnp.asarray(nz_rows, jnp.int32),
                                jnp.asarray(dense[nz_rows]))
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr storage requires a 2-D array")
        indptr = [0]
        indices, values = [], []
        for row in dense:
            nz = onp.nonzero(row)[0]
            indices.extend(nz.tolist())
            values.extend(row[nz].tolist())
            indptr.append(len(indices))
        return _make_csr(arr._data,
                         jnp.asarray(onp.array(values, dense.dtype)),
                         jnp.asarray(onp.array(indices, onp.int32)),
                         jnp.asarray(onp.array(indptr, onp.int32)))
    raise MXNetError(f"unknown storage type {stype!r}")


def retain(arr: RowSparseNDArray, row_ids) -> RowSparseNDArray:
    """Keep only the requested rows (reference sparse retain op — the
    row_sparse pull-on-demand primitive, parameter.py:527)."""
    rids = row_ids._data if isinstance(row_ids, NDArray) \
        else jnp.asarray(row_ids)
    rids = rids.astype(jnp.int32)
    vals = jnp.take(arr._data, rids, axis=0)
    dense = jnp.zeros_like(arr._data).at[rids].set(vals)
    return _make_row_sparse(dense, rids.astype(jnp.int32), vals)


def sparse_dot(lhs, rhs, transpose_a=False) -> NDArray:
    """dot(csr, dense) (reference sparse dot). The compressed values ride a
    segment-sum; on TPU the dense fallback is usually faster for the shapes
    the MXU likes, so small nnz uses gather+segment_sum, else dense dot."""
    if isinstance(lhs, CSRNDArray) and not transpose_a:
        data = lhs._aux["values"]._data
        indices = lhs._aux["indices"]._data.astype(jnp.int32)
        indptr = lhs._aux["indptr"]._data
        counts = indptr[1:] - indptr[:-1]
        row_ids = jnp.repeat(jnp.arange(lhs.shape[0]), counts,
                             total_repeat_length=data.shape[0])
        rhs_rows = jnp.take(rhs._data, indices, axis=0)
        contrib = rhs_rows * data[:, None]
        out = jax.ops.segment_sum(contrib, row_ids,
                                  num_segments=lhs.shape[0])
        return NDArray(out)
    return NDArray(jnp.matmul(
        lhs._data.T if transpose_a else lhs._data, rhs._data))


dot = sparse_dot
