"""``mx.nd`` namespace: NDArray + legacy op surface.

Reference analog: python/mxnet/ndarray/ (generated op wrappers + NDArray
class). Ops here are hand-defined pure-JAX functions rather than codegen from
a C++ registry.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      linspace, eye, concatenate, waitall, from_jax, moveaxis)
from .ops import *  # noqa: F401,F403
from .nn_ops import *  # noqa: F401,F403
from .vision_ops import (BilinearSampler, GridGenerator, SpatialTransformer,
                         Correlation)
from . import ops as op
from . import random
from . import sparse
from . import contrib
from .utils import save, load
