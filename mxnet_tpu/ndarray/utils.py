"""NDArray serialization: ``mx.nd.save`` / ``mx.nd.load``.

Reference analog: NDArray binary format (include/mxnet/ndarray.h:399-411,
list save/load :797-811 — magic + shape/dtype + raw bytes) and Python helpers
python/mxnet/ndarray/utils.py:149,222. We keep the same capability (save a
list or str-keyed dict of arrays to one file, load it back) with an .npz
container — portable, mmap-able, and holds bfloat16 via a view trick.
"""
from __future__ import annotations

import io
import zipfile
from typing import Dict, List, Union

import numpy as onp

import jax.numpy as jnp

from ..base import MXNetError
from .ndarray import NDArray

__all__ = ["save", "load"]

_MAGIC_LIST = "__mx_tpu_list__"
_BF16_SUFFIX = "__bf16"


def _to_numpy(arr: NDArray):
    data = arr._data
    if data.dtype == jnp.bfloat16:
        return onp.asarray(data.view(jnp.uint16) if hasattr(data, "view")
                           else onp.asarray(data).view(onp.uint16)), True
    return onp.asarray(data), False


def save(fname: str, data: Union[NDArray, List[NDArray], Dict[str, NDArray]]):
    if isinstance(data, NDArray):
        data = [data]
    payload = {}
    if isinstance(data, dict):
        items = data.items()
        payload[_MAGIC_LIST] = onp.array(0)
    elif isinstance(data, (list, tuple)):
        items = ((str(i), v) for i, v in enumerate(data))
        payload[_MAGIC_LIST] = onp.array(1)
    else:
        raise MXNetError("save expects NDArray, list, or dict of NDArray")
    for k, v in items:
        if not isinstance(v, NDArray):
            raise MXNetError(f"value for key {k!r} is not an NDArray")
        a, is_bf16 = _to_numpy(v)
        payload[k + (_BF16_SUFFIX if is_bf16 else "")] = a
    # crash-safe write: serialize fully in memory, stage to a temp file,
    # fsync, then os.replace — a kill mid-save can never clobber an
    # existing good file with a torn archive (savez to a file object
    # also keeps numpy from appending '.npz' to the requested path)
    from ..checkpoint.atomic import atomic_write_bytes
    buf = io.BytesIO()
    onp.savez(buf, **payload)
    atomic_write_bytes(fname, buf.getvalue(), fault="ndarray.save")


def load(fname: str):
    if not zipfile.is_zipfile(fname):
        raise MXNetError(f"{fname} is not a valid saved NDArray file")
    with onp.load(fname, allow_pickle=False) as z:
        is_list = bool(z[_MAGIC_LIST]) if _MAGIC_LIST in z.files else False
        out = {}
        for k in z.files:
            if k == _MAGIC_LIST:
                continue
            a = z[k]
            if k.endswith(_BF16_SUFFIX):
                k = k[: -len(_BF16_SUFFIX)]
                a = jnp.asarray(a).view(jnp.bfloat16)
            out[k] = NDArray(a)
    if is_list:
        return [out[str(i)] for i in range(len(out))]
    return out
