"""Random sampling ops.

Reference analog: src/operator/random/ (sampler kernels backed by per-device
PRNG states via Resource kRandom, reference include/mxnet/resource.h:39). On
TPU the idiomatic design is counter-based stateless PRNG: a process-global
``jax.random`` key chain (split per op) gives reproducibility under
``mx.random.seed`` while every sample op stays a pure XLA kernel.
"""
from __future__ import annotations

import threading

import numpy as onp

import jax
import jax.numpy as jnp

from ..base import jx_dtype
from ..ops.registry import invoke_raw
from .ndarray import NDArray, _put

__all__ = ["seed", "next_key", "get_key_state", "set_key_state",
           "uniform", "normal", "randn", "randint",
           "exponential", "gamma", "poisson", "negative_binomial",
           "generalized_negative_binomial", "multinomial", "shuffle",
           "bernoulli", "laplace"]

_state = threading.local()
_GLOBAL_SEED = [0]


def _key_state():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_GLOBAL_SEED[0])
    return _state.key


def seed(seed_state, ctx="all"):
    """Reference mx.random.seed (python/mxnet/random.py)."""
    _GLOBAL_SEED[0] = int(seed_state)
    _state.key = jax.random.PRNGKey(int(seed_state))
    onp.random.seed(int(seed_state) & 0x7FFFFFFF)


def next_key():
    """Split off a fresh PRNG key. Inside a hybridized (jit) trace, keys
    derive from the traced per-call key so dropout etc. stays random across
    calls instead of baking one mask into the compiled program."""
    stack = getattr(_state, "trace_keys", None)
    if stack:
        k, sub = jax.random.split(stack[-1])
        stack[-1] = k
        return sub
    k = _key_state()
    k, sub = jax.random.split(k)
    _state.key = k
    return sub


def get_key_state():
    """The current PRNG key chain head as a host array — checkpointing
    this (mx.checkpoint) makes a resumed run draw the SAME random stream
    (dropout masks, samplers) the uninterrupted run would have."""
    return onp.asarray(_key_state())


def set_key_state(key):
    """Restore a key captured by :func:`get_key_state`."""
    _state.key = jnp.asarray(onp.asarray(key), dtype=jnp.uint32)


def push_trace_key(key):
    if not hasattr(_state, "trace_keys"):
        _state.trace_keys = []
    _state.trace_keys.append(key)


def pop_trace_key():
    _state.trace_keys.pop()


def _maybe_out(res, out):
    if out is not None:
        out._data = res._data
        return out
    return res


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def _sample(name, fn, shape, dtype, ctx):
    key = next_key()
    out = fn(key, _shape(shape), jx_dtype(dtype or "float32"))
    return NDArray(_put(out, ctx), ctx=ctx)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    res = _sample("uniform",
                  lambda k, s, d: jax.random.uniform(k, s, d, low, high),
                  shape, dtype, ctx)
    return _maybe_out(res, out)


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    res = _sample("normal",
                  lambda k, s, d: loc + scale * jax.random.normal(k, s, d),
                  shape, dtype, ctx)
    return _maybe_out(res, out)


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None):
    return normal(loc, scale, shape, dtype, ctx)


def randint(low, high=None, shape=None, dtype=None, ctx=None, out=None):
    if high is None:
        low, high = 0, low
    key = next_key()
    out_arr = jax.random.randint(key, _shape(shape), low, high,
                                 jx_dtype(dtype or "int32"))
    return _maybe_out(NDArray(_put(out_arr, ctx), ctx=ctx), out)


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    res = _sample("exponential",
                  lambda k, s, d: scale * jax.random.exponential(k, s, d),
                  shape, dtype, ctx)
    return _maybe_out(res, out)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None):
    res = _sample("gamma",
                  lambda k, s, d: beta * jax.random.gamma(k, alpha, s, d),
                  shape, dtype, ctx)
    return _maybe_out(res, out)


def laplace(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None):
    res = _sample("laplace",
                  lambda k, s, d: loc + scale * jax.random.laplace(k, s, d),
                  shape, dtype, ctx)
    return _maybe_out(res, out)


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None):
    key = next_key()
    out_arr = jax.random.poisson(key, lam, _shape(shape)).astype(
        jx_dtype(dtype or "float32"))
    return _maybe_out(NDArray(_put(out_arr, ctx), ctx=ctx), out)


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None, out=None):
    # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    key = next_key()
    g = jax.random.gamma(key, k, _shape(shape)) * (1.0 - p) / p
    out_arr = jax.random.poisson(next_key(), g).astype(jx_dtype(dtype or "float32"))
    return _maybe_out(NDArray(_put(out_arr, ctx), ctx=ctx), out)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None, out=None):
    k = 1.0 / alpha
    p = k / (k + mu)
    return negative_binomial(k=k, p=p, shape=shape, dtype=dtype, ctx=ctx, out=out)


def bernoulli(prob=0.5, shape=None, dtype=None, ctx=None, out=None):
    key = next_key()
    out_arr = jax.random.bernoulli(key, prob, _shape(shape)).astype(
        jx_dtype(dtype or "float32"))
    return _maybe_out(NDArray(_put(out_arr, ctx), ctx=ctx), out)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    """Sample category indices from (batched) probability rows; with
    get_prob=True also return log-likelihoods of the samples for
    REINFORCE-style use (reference sample_multinomial semantics)."""
    data = data if isinstance(data, NDArray) else NDArray(data)
    key = next_key()
    n = 1 if shape is None else int(onp.prod(_shape(shape)))

    def fn(p, _key=key):
        logits = jnp.log(jnp.maximum(p, 1e-37))
        if p.ndim == 1:
            out = jax.random.categorical(_key, logits, shape=(n,))
            if shape is None:
                out = out[0]
        else:
            out = jax.random.categorical(_key, logits[:, None, :], axis=-1,
                                         shape=(p.shape[0], n))
            if shape is None:
                out = out[:, 0]
        return out.astype(jx_dtype(dtype))

    samples = invoke_raw("multinomial", fn, [data], record=False)

    if not get_prob:
        return samples

    def logp_fn(p, s):
        logits = jnp.log(jnp.maximum(p, 1e-37))
        logp = jax.nn.log_softmax(logits, axis=-1)
        idx = s.astype(jnp.int32)
        if p.ndim == 1:
            return jnp.take(logp, idx)
        take = jnp.take_along_axis(
            logp, idx.reshape(p.shape[0], -1), axis=-1)
        return take.reshape(idx.shape)
    logp = invoke_raw("multinomial_logp", logp_fn, [data, samples])
    return samples, logp


def shuffle(data, **kw):
    data = data if isinstance(data, NDArray) else NDArray(data)
    key = next_key()
    return invoke_raw("shuffle",
                      lambda x, _k=key: jax.random.permutation(_k, x, axis=0),
                      [data], record=False)
