"""Base utilities: dtype registry, errors, env-var config.

TPU-native rebuild of the reference's dmlc base layer. Where the reference
reads ~103 ``MXNET_*`` environment variables through ``dmlc::GetEnv`` at use
sites (reference: docs/static_site/src/pages/api/faq/env_var.md), we keep the
same two-tier config model: environment variables + dataclass-reflected
module/op parameters.
"""
from __future__ import annotations

import os
from typing import Any

import numpy as onp

import jax.numpy as jnp

__all__ = [
    "MXNetError",
    "get_env",
    "data_dir",
    "np_dtype",
    "jx_dtype",
    "dtype_name",
    "DTYPE_NAMES",
]


class MXNetError(RuntimeError):
    """Default error type for the framework (reference: include/mxnet/base.h)."""


def data_dir() -> str:
    """Data/model cache root, MXNET_HOME-overridable (reference
    python/mxnet/base.py data_dir, env_var.md MXNET_HOME)."""
    return os.path.expanduser(os.environ.get(
        "MXNET_HOME", os.path.join("~", ".mxnet")))


def get_env(name: str, default: Any = None, dtype: type = str) -> Any:
    """Read an ``MXNET_*`` style env var with a typed default.

    Mirrors ``dmlc::GetEnv`` usage across the reference runtime
    (e.g. engine selection at src/engine/engine.cc:33).
    """
    val = os.environ.get(name)
    if val is None:
        return default
    if dtype is bool:
        return val.lower() not in ("0", "false", "off", "")
    return dtype(val)


# Canonical dtype table. The reference enumerates dtypes as integer type flags
# (mshadow base.h kFloat32=0, ...); we key by name and map to numpy/jax dtypes.
_DTYPES = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "bool": jnp.bool_,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "int16": jnp.int16,
}

DTYPE_NAMES = tuple(_DTYPES)

# Integer type flags for serialization compatibility with the reference's
# NDArray binary format (mshadow/base.h TypeFlag order).
DTYPE_FLAG = {
    "float32": 0, "float64": 1, "float16": 2, "uint8": 3, "int32": 4,
    "int8": 5, "int64": 6, "bool": 7, "int16": 8, "uint16": 9,
    "uint32": 10, "uint64": 11, "bfloat16": 12,
}
FLAG_DTYPE = {v: k for k, v in DTYPE_FLAG.items()}


def np_dtype(dtype) -> onp.dtype:
    """Normalize any dtype spec to a numpy dtype (bfloat16 stays jax-side)."""
    if dtype is None:
        return onp.dtype("float32")
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return jnp.bfloat16
        return onp.dtype(dtype)
    return onp.dtype(dtype) if dtype is not jnp.bfloat16 else jnp.bfloat16


def jx_dtype(dtype):
    """Normalize a dtype spec to a jax-compatible dtype object."""
    if dtype is None:
        return jnp.float32
    if isinstance(dtype, str):
        try:
            return _DTYPES[dtype]
        except KeyError as e:
            raise MXNetError(f"unknown dtype {dtype!r}") from e
    return dtype


def dtype_name(dtype) -> str:
    """Canonical string name of a dtype."""
    if isinstance(dtype, str):
        return dtype
    return jnp.dtype(dtype).name
