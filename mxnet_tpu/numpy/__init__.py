"""mx.np — NumPy-compatible frontend (reference python/mxnet/numpy/).

``from mxnet_tpu import np`` gives a NumPy drop-in whose arrays live on TPU,
record onto the autograd tape, and trace through jit/pjit. Submodules:
``np.linalg``, ``np.random``, ``np.fft``.
"""
import numpy as _onp
import jax.numpy as _jnp

import types as _types

from . import multiarray as _ma
from .multiarray import ndarray, array, _invoke, _DEFAULT_DTYPE  # noqa: F401

_EXCLUDE = {"NDArray", "Context", "current_context", "invoke_raw",
            "set_np_ndarray_cls", "jx_dtype", "dtype_name", "MXNetError"}
for _n in dir(_ma):
    if _n.startswith("_") or _n in _EXCLUDE:
        continue
    _v = getattr(_ma, _n)
    if isinstance(_v, _types.ModuleType) or _v is None:
        continue
    globals()[_n] = _v
del _types, _n, _v
from . import linalg  # noqa: F401
from . import random  # noqa: F401
from . import fft  # noqa: F401

# NumPy-fallback tail (reference numpy/fallback.py): installs ONLY the
# names without a native TPU implementation above.
from . import fallback as _fallback  # noqa: E402
for _n in _fallback._INSTALLED:
    if _n not in globals():
        globals()[_n] = getattr(_fallback, _n)
del _fallback, _n

# dtype aliases (reference python/mxnet/numpy/__init__.py re-exports numpy's)
float16 = _onp.float16
float32 = _onp.float32
float64 = _onp.float64
bfloat16 = _jnp.bfloat16
int8 = _onp.int8
int16 = _onp.int16
int32 = _onp.int32
int64 = _onp.int64
uint8 = _onp.uint8
uint16 = _onp.uint16
uint32 = _onp.uint32
uint64 = _onp.uint64
bool_ = _onp.bool_
bool = _onp.bool_  # noqa: A001
complex64 = _onp.complex64
complex128 = _onp.complex128
intc = _onp.intc
dtype = _onp.dtype

pi = _onp.pi
e = _onp.e
euler_gamma = _onp.euler_gamma
inf = _onp.inf
nan = _onp.nan
newaxis = None
PZERO = 0.0
NZERO = -0.0

finfo = _onp.finfo
iinfo = _onp.iinfo

_np_version = _onp.__version__
